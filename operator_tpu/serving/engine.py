"""Continuous-batching generation engine — the ai-interface's compute, in-tree.

The reference POSTs each analysis to an external LLM service one request at
a time (reference AIInterfaceRestClient.java:37-39, 180 s read budget).
Here generation runs on the local TPU with **continuous batching**:

- **Slots**: the KV cache holds ``max_slots`` sequences; decode always runs
  the full ``[max_slots, 1]`` batch (a fixed shape XLA compiles once), with
  finished/empty slots masked.  A new request joins at the next step
  boundary instead of waiting for the batch to drain.
- **Batched prefill**: concurrent arrivals are tokenised, right-padded to a
  shared bucket and prefilled as ONE forward pass (BASELINE config 4: 32
  concurrent failure events -> one prefill).  Prompt shapes are bucketed to
  powers of two so XLA compiles a handful of prefill programs, not one per
  request.
- **Ragged positions**: every slot decodes at its own offset; the model's
  cache update takes a per-sequence offset vector (models/llama.py).
- **Per-slot sampling params**: temperature / top-p ride in ``[B]`` arrays,
  so requests with different AIProvider configs share one batch.

Two layers: :class:`BatchedGenerator` is the synchronous JAX core (jitted
prefill / decode-step / sampler); :class:`ServingEngine` is the asyncio
front the operator talks to (queue, admission, futures).  The split keeps
the JAX code testable without an event loop.

Module layout (round-5 split; this module remains the public import
surface): program construction lives in :mod:`.programs`
(ProgramBuilderMixin — every jitted XLA program), admission policy in
:mod:`.admission` (AdmissionMixin — wave formation, truncation, prefix
decision, page grants, warmup grid), shared dataclasses in :mod:`.types`.
This module keeps the STATE and the loops: slot/cache/page lifecycle,
decode stepping + pipelining, guided-automaton registry, chunked-prefill
job advancement, and the async engine.

Grown-in serving subsystems (each opt-in or zero-cost when unused):
multi-step decode blocks + decode-ahead pipelining; sharded TP/DP serving
over a mesh; multi-LoRA (per-slot adapters stacked into one program);
guided decoding (choice/regex automata as scan-carried device state);
Sarathi-style chunked prefill (``prefill_chunk``); priority admission
(pipeline explanations outrank external API callers); bounded
auto-recovery after device errors (:meth:`ServingEngine._try_recover`);
and slot/page reclamation for cancelled callers.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import threading
import time
from dataclasses import dataclass
from typing import Any, Optional, Sequence

import numpy as np

from ..models.configs import ModelConfig
from ..models.llama import KVCache, forward
from ..models.tokenizer import Tokenizer
from ..obs import span as obs_span
from ..utils.timing import METRICS, MetricsRegistry
from .admission import AdmissionMixin
from .programs import ProgramBuilderMixin

# re-exported types: the public import surface predates the round-5 module
# split (every consumer does `from operator_tpu.serving.engine import ...`)
from .types import (  # noqa: F401
    DeadlineExceeded,
    GenerationResult,
    OversizedRequest,
    PageAllocator,
    SamplingParams,
    _bucket,
    _PrefillJob,
    _Slot,
)

log = logging.getLogger(__name__)


def _params_dtype_name(params: Any) -> str:
    """Dtype label for the AOT-cache fingerprint: int8-quantized param
    trees carry scale leaves, so detect via models.quant, else report the
    first leaf's dtype."""
    from ..models.quant import is_quantized

    if is_quantized(params):
        return "int8"
    try:
        import jax

        leaf = jax.tree_util.tree_leaves(params)[0]
        return str(leaf.dtype)
    except Exception:  # noqa: BLE001 - fingerprint label only
        return "?"


class EngineStalled(RuntimeError):
    """The decode loop made no step progress within the supervisor's stall
    budget — the device (or its runtime) is wedged, not merely slow."""


@dataclass
class SupervisorPolicy:
    """Watchdog policy for the serving engine (docs/ROBUSTNESS.md).

    With a policy installed, a decode step exceeding ``stall_timeout_s`` —
    or a serve-loop death — triggers a supervised restart: the engine
    resets its device state, audits slot/page leaks, dumps a black-box
    flight-recorder record, and requeues in-flight requests up to
    ``max_requeues`` times with their residual deadlines (the deadline is
    an absolute instant, so queue time already spent stays spent).
    Without one (the default), the engine keeps the pre-supervisor
    semantics: loop death fails every in-flight future and recovery is
    lazy (``_try_recover`` on the next generate).
    """

    #: a step may legitimately hide a multi-second in-band XLA compile
    #: (novel bucket): only a genuinely wedged device should trip this.
    #: Must match OperatorConfig.supervisor_stall_s (the config-driven
    #: production default) so direct constructions behave identically
    stall_timeout_s: float = 120.0
    #: how long to wait for an abandoned (stalled) decode thread to return
    #: before resetting device state under it anyway
    join_grace_s: float = 10.0
    #: each request is re-admitted at most this many times; beyond it the
    #: supervisor gives up and fails the caller
    max_requeues: int = 1


@dataclass
class _Request:
    """One queued/admitted generation request — kept whole (prompt +
    params + priority) so the supervisor can re-admit it after an engine
    restart; the bare future the queue used to carry cannot be requeued."""

    prompt: str
    params: "SamplingParams"
    future: asyncio.Future
    priority: int = 0
    requeues: int = 0
    #: token-level streaming resume (router/resume.py): generated token
    #: ids to re-prefill VERBATIM after the prompt on a failover
    #: survivor; the result then carries only the continuation
    resume_tokens: Optional[list] = None
    #: perf_counter at submit (ServingEngine.generate) — queue wait is
    #: measured admission-minus-submit, not inferred from wall deltas
    submitted: float = 0.0
    queue_wait_ms: float = 0.0


class BatchedGenerator(AdmissionMixin, ProgramBuilderMixin):
    """Slot-based generation over one shared KV cache (single host thread).

    Not thread-safe by design: the ServingEngine serialises all calls on
    one worker; the TPU itself is the serial resource.
    """

    def __init__(
        self,
        params: Any,
        config: ModelConfig,
        tokenizer: Tokenizer,
        *,
        max_slots: int = 8,
        max_seq: Optional[int] = None,
        cache_dtype: Any = None,
        metrics: Optional[MetricsRegistry] = None,
        seed: int = 0,
        paged: bool = False,
        page_size: int = 64,
        kv_pages: Optional[int] = None,
        mesh: Any = None,
        decode_block: int = 1,
        sample_top_k: Optional[int] = None,
        pipeline_depth: int = 1,
        lora_adapters: Optional[dict[str, Any]] = None,
        lora_alpha: float = 16.0,
        prefill_chunk: Optional[int] = None,
        roofline_token_s: Optional[float] = None,
        aot_cache: Any = None,
        step_ring_capacity: Optional[int] = None,
    ) -> None:
        import jax
        import jax.numpy as jnp

        self._jax = jax
        self._jnp = jnp
        self.config = config
        self.tokenizer = tokenizer
        self.max_slots = max_slots
        self.max_seq = min(max_seq or config.max_seq_len, config.max_seq_len)
        self.metrics = metrics or METRICS
        # ---- step clock (obs/steptrace.py + serving/perf.py): a bounded
        # ring of per-step host-gap/device/sample-xfer records with the
        # analytic flops-per-token model for the serving dtype, so every
        # decode step carries an attributed MFU (STEP_RING_CAPACITY)
        from .perf import StepClock, flops_per_token, peak_tflops

        _serving_dtype = _params_dtype_name(params)
        self.step_clock = StepClock(
            capacity=step_ring_capacity,
            flops_per_token=flops_per_token(config, _serving_dtype),
            peak_tflops=peak_tflops(_serving_dtype),
            max_slots=max_slots,
            metrics=self.metrics,
        )
        # deadline budgets (admission.deadline_policy): per-token decode
        # estimate before any block has been measured; the clock is an
        # attribute so chaos tests can inject a fake one
        self.roofline_token_s = roofline_token_s
        self._clock = time.monotonic
        #: value-aware overload ladder (router/value.py OverloadPolicy):
        #: when wired, admission.deadline_policy degrades/sheds by value
        #: under pressure; None = pre-overload-control semantics
        self.overload_policy = None
        #: opt-in chaos seam (utils/faultinject.py): consulted per step()
        #: round — stalls and simulated device errors for recovery tests
        self.fault_plan = None
        cache_dtype = cache_dtype or jnp.bfloat16
        self.cache_dtype = cache_dtype
        # decode in blocks of K steps per host round-trip (lax.scan): one
        # dispatch + one token fetch per K tokens hides host latency for
        # K-1 of every K steps.  Finished slots may decode up to K-1 junk
        # tokens into their OWN cache rows/pages before the host notices —
        # harmless by the same argument that lets inactive slots keep
        # decoding garbage.  Trade-off: admissions join at block boundaries
        # (adds up to K-1 steps of queueing to p50, microseconds-to-ms).
        assert decode_block >= 1
        self.decode_block = decode_block
        self.sample_top_k = sample_top_k or self.SAMPLE_TOP_K
        # decode-ahead: blocks in flight before the host fetches tokens
        # (see step()); 1 = synchronous, 2 = one block of lookahead
        assert pipeline_depth >= 1
        if pipeline_depth * decode_block * 2 > self.max_seq:
            raise ValueError(
                f"pipeline_depth*decode_block={pipeline_depth * decode_block} "
                f"reserves more than half of max_seq={self.max_seq} as the "
                f"stop margin — generations would truncate immediately"
            )
        self.pipeline_depth = pipeline_depth
        #: optional ``hook(slot_id, token_ids_so_far)`` called after each
        #: processed block for slots that are still generating — the
        #: streaming feed (ServingEngine marshals it onto the event loop).
        #: Called from the decode worker thread; must not block.
        self.partial_hook: Optional[Any] = None
        self._inflight_blocks: list[tuple[Any, dict]] = []

        # ---- chunked prefill (Sarathi-style interleaving): a long prompt
        # is prefilled ``prefill_chunk`` tokens per engine round instead of
        # one shot, so in-flight decodes stall for at most one chunk's wall
        # time per round rather than the whole prompt's.  One job at a time;
        # its slots are RESERVED (not yet decoding) until the finish step
        # scatters the mini cache and samples the first token.  None = off.
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError(f"prefill_chunk={prefill_chunk} must be >= 1")
        self.prefill_chunk = prefill_chunk
        self._prefill_job: Optional[_PrefillJob] = None
        self._reserved: set[int] = set()
        self._chunk_fns: dict[tuple[int, int, int], Any] = {}
        self._finish_fns: dict[tuple, Any] = {}  # (n_pad, t_pad, guided)

        # ---- guided decoding (serving/guided.py): automaton tables stacked
        # [A_pad, S_pad, vocab] on device, per-slot (automaton, state)
        # vectors carried through the decode scan.  None = no guided slot
        # active; the unguided programs keep compiling/running untouched.
        self._guided_cache: dict[tuple, Any] = {}   # choices -> ChoiceAutomaton
        # submit-time validation mutates the cache from the HTTP event-loop
        # thread while the serve loop's executor thread reads it; the lock
        # guards bookkeeping only (builds run unlocked), and
        # _guided_protect shields an in-flight refresh wave from
        # submit-thread eviction
        self._guided_lock = threading.Lock()
        self._guided_protect: frozenset = frozenset()
        self._guided_tables = None                  # device stack, or None
        self._guided_index: dict[tuple, int] = {}   # choices -> stacked idx
        self._guided_aut_np = np.zeros((max_slots,), np.int32)
        self.guided_aut = None                      # device [B] automaton ids
        self.guided_state = None                    # device [B] DFA states
        self._decode_fn_guided = None

        # ---- multi-LoRA serving: adapters stacked [n_layers, n_adapters+1,
        # ...] with the all-zeros base at index 0; every request picks its
        # adapter per slot inside ONE compiled program (models/llama.py
        # _lora_path).  Passed as ARGUMENTS to the jitted fns — closure
        # capture would embed tens of MB as program constants.
        self.lora_alpha = lora_alpha
        if lora_adapters:
            from ..parallel.lora import stack_adapters, zero_lora

            names = sorted(lora_adapters)
            first = lora_adapters[names[0]]
            first_a = first[next(iter(first))]["a"]
            zero = zero_lora(
                config, rank=first_a.shape[-1], targets=tuple(first),
                dtype=first_a.dtype,
            )
            self.lora = stack_adapters([zero] + [lora_adapters[n] for n in names])
            self._adapter_ids: dict[Optional[str], int] = {
                None: 0, **{n: i + 1 for i, n in enumerate(names)}
            }
        else:
            self.lora = None
            self._adapter_ids = {None: 0}

        # ---- sharded serving (BASELINE configs 3/5): params TP on heads /
        # MLP columns, slots DP over the batch axis; one jitted program per
        # mesh — XLA inserts the tp psums and dp scatter collectives
        self.mesh = mesh
        if mesh is not None:
            from ..models.quant import is_quantized

            self._init_shardings(mesh, quantized=is_quantized(params))
            params = self._jax.tree_util.tree_map(
                jax.device_put, params, self._param_shardings
            )
        else:
            self._shardings = None
        self.params = params

        self.paged = paged
        self.page_size = page_size

        # ---- persisted AOT executables (serving/aotcache.py): every
        # serving-program construction site below routes through _aot_wrap,
        # so a warm boot (or a supervised restart) deserializes executables
        # instead of recompiling.  ``aot_cache`` is a directory path (the
        # generator builds + fingerprints its own cache), a prebuilt
        # AotCache (provider overlap path), or None = off.
        self._aot = None
        if aot_cache is not None:
            from .aotcache import AotCache, generator_fingerprint

            if isinstance(aot_cache, AotCache):
                self._aot = aot_cache
                self._aot.metrics = self.metrics
            else:
                try:
                    payload = generator_fingerprint(
                        config=config,
                        weight_dtype=_params_dtype_name(params),
                        max_slots=max_slots,
                        max_seq=max_seq,
                        cache_dtype=cache_dtype,
                        paged=paged,
                        page_size=page_size,
                        kv_pages=kv_pages,
                        mesh=mesh,
                        decode_block=decode_block,
                        sample_top_k=sample_top_k,
                        pipeline_depth=pipeline_depth,
                        prefill_chunk=prefill_chunk,
                        lora_names=[n for n in self._adapter_ids if n],
                    )
                    self._aot = AotCache(
                        str(aot_cache), payload, metrics=self.metrics
                    )
                except Exception:  # noqa: BLE001 - cache is an optimisation only
                    log.warning(
                        "AOT executable cache disabled: fingerprint "
                        "construction failed", exc_info=True,
                    )

        # ---- shared-prefix KV cache (add_shared_prefix): each registered
        # prompt prefix is prefilled ONCE into generator-owned pages;
        # admitted prompts that start with one reference those pages
        # read-only and prefill only their suffix.  Registry entries:
        # {"text", "tokens", "pages"} in registration order (the default
        # template first, then custom AIProvider promptTemplates).
        # Initialised unconditionally: reset() and the compat properties
        # read it in contiguous (non-paged) mode too, where it stays empty
        self._prefixes: list[dict] = []
        self._prefix_fns: dict[tuple, Any] = {}  # (n_pad, t_sfx, shared, guided)
        if paged:
            from ..ops.paged_attention import PagedKVCache

            self.pages_per_seq = -(-self.max_seq // page_size)
            # default: worst case + trash page (configure kv_pages smaller to
            # oversubscribe HBM — admission then backpressures on the free
            # list instead of reserving max_seq per slot up front)
            num_pages = kv_pages or (max_slots * self.pages_per_seq + 1)
            self.allocator = PageAllocator(num_pages)
            self.cache = None
            self._alloc_decode_state()
            if mesh is not None:
                s = self._shardings
                from jax.sharding import NamedSharding, PartitionSpec as P

                block_tokens = NamedSharding(mesh, P(None, ("dp", "fsdp")))
                self._decode_fn = self._aot_wrap("decode", jax.jit(
                    self._decode_block_paged,
                    in_shardings=(
                        self._param_shardings, s["paged"], s["tokens"],
                        s["repl"], s["batch"], s["batch"], s["batch"],
                        s["repl"], s["batch"],  # stacked lora (small), idx
                    ),
                    out_shardings=(s["paged"], block_tokens, s["tokens"], s["repl"]),
                    donate_argnums=(1,),  # page pool: update in place, no copy
                ))
            else:
                self._decode_fn = self._aot_wrap(
                    "decode",
                    jax.jit(self._decode_block_paged, donate_argnums=(1,)),
                )
        else:
            self._alloc_decode_state()
            if mesh is not None:
                s = self._shardings
                from jax.sharding import NamedSharding, PartitionSpec as P

                block_tokens = NamedSharding(mesh, P(None, ("dp", "fsdp")))
                self._decode_fn = self._aot_wrap("decode", jax.jit(
                    self._decode_block,
                    in_shardings=(
                        self._param_shardings, s["cache"], s["tokens"],
                        s["batch"], s["repl"], s["batch"], s["batch"], s["batch"],
                        s["repl"], s["batch"],  # stacked lora (small), idx
                    ),
                    out_shardings=(
                        s["cache"], block_tokens, s["tokens"], s["batch"], s["repl"]
                    ),
                    donate_argnums=(1,),  # KV cache: update in place, no copy
                ))
            else:
                self._decode_fn = self._aot_wrap(
                    "decode",
                    jax.jit(self._decode_block, donate_argnums=(1,)),
                )
        self.slots: list[_Slot] = [_Slot() for _ in range(max_slots)]
        # per-slot generation counter: an in-flight decode block carries the
        # epoch it was dispatched under, so tokens from a block dispatched
        # before a slot was recycled are never credited to the new sequence
        self._slot_epoch = [0] * max_slots
        self._rng = jax.random.PRNGKey(seed)
        # host shadow of per-slot token counts (BOTH cache layouts): the
        # decode loop must never fetch offsets from the device — at the 8B
        # target the per-step host budget is ~10ms and a blocking read eats it
        self._host_offsets = np.zeros((max_slots,), np.int64)
        # per-slot sampling tensors change only at admit/finish; cache the
        # device copies so steady-state decode transfers nothing but tokens
        self._sampling_cache: Optional[tuple] = None

        self._prefill_fns: dict[tuple, Any] = {}  # (n_pad, t_pad, guided)

    def _aot_wrap(self, name: str, fn: Any) -> Any:
        """Route one serving program through the AOT executable cache.

        Identity when the cache is off — every construction site stays a
        plain ``jax.jit`` callable then, so the wrapping is zero-cost in
        the default configuration."""
        if self._aot is None:
            return fn
        from .aotcache import CachedProgram

        return CachedProgram(self._aot, name, fn)

    def _init_shardings(self, mesh: Any, *, quantized: bool = False) -> None:
        """Validate the mesh against the model and build the sharding table."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.mesh import kv_cache_spec, paged_cache_specs, param_shardings

        jax = self._jax
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        tp = sizes.get("tp", 1)
        dp_total = sizes.get("dp", 1) * sizes.get("fsdp", 1)
        if self.config.num_kv_heads % tp or self.config.num_heads % tp:
            raise ValueError(
                f"tp={tp} must divide kv_heads={self.config.num_kv_heads} "
                f"and heads={self.config.num_heads}"
            )
        if self.max_slots % dp_total:
            raise ValueError(
                f"max_slots={self.max_slots} must be a multiple of "
                f"dp*fsdp={dp_total} (slots shard over the data axes)"
            )
        self._dp_total = dp_total

        def ns(spec):
            return NamedSharding(mesh, spec)

        self._param_shardings = param_shardings(mesh, self.config, quantized=quantized)
        self._shardings = {
            "repl": ns(P()),
            "batch": ns(P(("dp", "fsdp"))),          # [B] per-slot vectors
            "tokens": ns(P(("dp", "fsdp"), None)),   # [B, 1] decode tokens
            "cache": KVCache(k=ns(kv_cache_spec()), v=ns(kv_cache_spec())),
            "paged": jax.tree_util.tree_map(
                ns, paged_cache_specs(), is_leaf=lambda x: isinstance(x, P)
            ),
        }

    def _put_batch_vec(self, array):
        """Place a per-slot [B] vector: batch sharding under a mesh (one
        host->mesh transfer), plain device array otherwise.  The one
        placement helper for guided aut/state AND the sampling tensors."""
        if self.mesh is not None:
            return self._jax.device_put(array, self._shardings["batch"])
        return self._jnp.asarray(array)

    def _guided_row_aut(self, specs: list, n_pad: int):
        """[n_pad] automaton ids for a wave's rows (padding rows duplicate
        row 0); id 0 = identity for unguided rows."""
        row_aut = np.zeros((n_pad,), np.int32)
        for row, spec in enumerate(specs):
            row_aut[row] = self._guided_index.get(spec, 0)
        for row in range(len(specs), n_pad):
            row_aut[row] = row_aut[0]
        return row_aut

    def _apply_guided_activation(self, row_aut, taken, first_state) -> None:
        """Post-activation guided bookkeeping, shared by the one-shot and
        chunked paths: bind each slot's automaton id (0/identity for
        unguided slots — this RESET matters: a recycled slot may carry a
        stale accept-state from a previous guided occupant) and scatter the
        first DFA states."""
        jnp = self._jnp
        for row, slot_id in enumerate(taken):
            self._guided_aut_np[slot_id] = row_aut[row]
        self.guided_aut = self._put_batch_vec(self._guided_aut_np)
        self.guided_state = self._put_batch_vec(
            self.guided_state.at[
                jnp.asarray(np.asarray(taken, np.int32))
            ].set(first_state[: len(taken)])
        )

    # ------------------------------------------------------------------
    # guided decoding registry (serving/guided.py)
    # ------------------------------------------------------------------

    #: automaton-state cap: bounds the [A_pad, S_pad, vocab] table (int32)
    #: the guided programs carry; matches _refresh_guided_tables' s_pad
    #: clamp so an oversized request is rejected at SUBMIT time, never at
    #: admission
    MAX_GUIDED_STATES = 1 << 14

    @staticmethod
    def _guided_spec(params: "SamplingParams | None") -> Optional[tuple]:
        """The hashable automaton key for a request: ("choice", names) or
        ("regex", pattern); None = unconstrained."""
        if params is None:
            return None
        if params.guided_choice is not None:
            return ("choice", tuple(params.guided_choice))
        if params.guided_regex is not None:
            return ("regex", str(params.guided_regex))
        return None

    def _automaton_cached(self, spec: tuple) -> bool:
        """Lock-guarded cache probe (with the LRU touch) so async submit
        paths can skip the executor hop for already-built specs."""
        with self._guided_lock:
            if spec in self._guided_cache:
                self._guided_cache[spec] = self._guided_cache.pop(spec)
                return True
        return False

    def _ensure_automaton(self, spec: tuple) -> None:
        """Build (and cache) the automaton for a guided spec; raises
        ValueError on anything unservable — called at SUBMIT time so a bad
        request can never fail a co-batched wave.

        Eviction never touches specs in ``_guided_protect`` (the full set a
        ``_refresh_guided_tables`` pass is about to index) — without that
        window, a pass ensuring >cap distinct specs could evict one it
        ensured moments earlier and KeyError inside the serve loop.

        Thread safety: submit-time validation runs on the HTTP event-loop
        thread while the serve loop's executor thread refreshes the
        stacked tables.  Cache bookkeeping (touch/evict/insert) holds
        ``_guided_lock`` — the LRU touch is a pop-then-reinsert which,
        unlocked, opens a transient-absence window for exactly the
        KeyError the protection exists to prevent.  The automaton BUILD
        runs outside the lock: DFA compilation can take seconds, and
        holding the lock through it would stall the decode loop from the
        event-loop thread (or all HTTP traffic from the executor)."""
        if self._automaton_cached(spec):
            return
        kind, payload = spec
        if kind == "choice":
            from .guided import build_choice_automaton

            automaton = build_choice_automaton(
                payload, self.tokenizer, self.config.vocab_size
            )
        else:
            from .regex_dfa import compile_regex_automaton

            automaton = compile_regex_automaton(
                payload, self.tokenizer, self.config.vocab_size,
                max_states=self.MAX_GUIDED_STATES,
            )
        if automaton.num_states > self.MAX_GUIDED_STATES:
            raise ValueError(
                f"guided automaton needs {automaton.num_states} states, "
                f"above the {self.MAX_GUIDED_STATES} cap — simplify the "
                f"choices/pattern"
            )
        with self._guided_lock:
            if spec in self._guided_cache:  # raced another builder: theirs won
                self._guided_cache[spec] = self._guided_cache.pop(spec)
                return
            # bound host memory (LRU), but never evict a spec bound to an
            # ACTIVE slot, indexed in the current stacked tables, or in the
            # refresh pass currently in flight (_guided_protect) — the
            # serve loop indexes the cache directly for those
            live = {
                self._guided_spec(slot.params)
                for slot in self.slots
                if slot.active
            }
            live.update(self._guided_index)
            live.update(self._guided_protect)
            live.discard(None)
            evictable = [k for k in self._guided_cache if k not in live]
            while len(self._guided_cache) >= 32 and evictable:
                self._guided_cache.pop(evictable.pop(0))
            self._guided_cache[spec] = automaton

    def _refresh_guided_tables(self, wave_specs: "list[tuple | None]") -> None:
        """(Re)stack the automata needed by active + newly admitted guided
        slots; None when no guided slot remains (fast unguided path)."""
        from .guided import identity_automaton, stack_automata

        jnp = self._jnp
        specs = {
            self._guided_spec(slot.params)
            for slot in self.slots
            if slot.active and self._guided_spec(slot.params)
        }
        specs.update(spec for spec in wave_specs if spec)
        if not specs:
            self._guided_tables = None
            self._guided_index = {}
            self.guided_aut = None
            self.guided_state = None
            return
        # advertise the wave to submit-thread evictions BEFORE ensuring:
        # without the protect window, an eviction between this pass's
        # ensure loop and the locked cache reads below could drop a wave
        # spec before it lands in _guided_index.  Builds themselves run
        # unlocked (inside _ensure_automaton), so a slow DFA compile here
        # never blocks HTTP submits.
        with self._guided_lock:
            self._guided_protect = frozenset(specs)
        try:
            for spec in specs:
                self._ensure_automaton(spec)
            with self._guided_lock:
                ordered = sorted(specs)
                new_index = {spec: i + 1 for i, spec in enumerate(ordered)}
                if self._guided_tables is not None and new_index == self._guided_index:
                    return  # byte-identical stack: skip the rebuild + upload
                automata = [identity_automaton(self.config.vocab_size)]
                automata += [self._guided_cache[spec] for spec in ordered]
                self._guided_index = new_index
        finally:
            # _guided_index now carries the wave (or we raised); either way
            # the explicit protect window is over
            with self._guided_lock:
                self._guided_protect = frozenset()
        a_pad = _bucket(len(automata), 2, 64)
        s_pad = _bucket(
            max(a.num_states for a in automata), 8, self.MAX_GUIDED_STATES
        )
        while len(automata) < a_pad:
            automata.append(identity_automaton(self.config.vocab_size))
        stacked = stack_automata(automata, self.config.vocab_size, state_pad=s_pad)
        if self.mesh is not None:
            # commit the replication ONCE: an uncommitted table would be
            # re-broadcast across the mesh on every decode-block dispatch
            self._guided_tables = self._jax.device_put(
                stacked, self._shardings["repl"]
            )
        else:
            self._guided_tables = jnp.asarray(stacked)
        # remap every ACTIVE slot's automaton id under the new ordering
        for i, slot in enumerate(self.slots):
            spec = self._guided_spec(slot.params) if slot.active else None
            if spec:
                self._guided_aut_np[i] = self._guided_index[spec]
            elif i not in self._reserved:
                self._guided_aut_np[i] = 0
        self.guided_aut = self._put_batch_vec(self._guided_aut_np)
        if self.guided_state is None:
            self.guided_state = self._put_batch_vec(
                np.zeros((self.max_slots,), np.int32)
            )

    # ------------------------------------------------------------------
    # shared-prefix KV cache (automatic prefix caching, paged mode)
    # ------------------------------------------------------------------

    #: registered-prefix cap: each entry owns up to ~max_seq/page_size KV
    #: pages for the engine's lifetime — a runaway CR set must not eat the
    #: pool (realistic deployments have a handful of AIProvider templates)
    MAX_SHARED_PREFIXES = 8

    @property
    def _prefix_tokens(self) -> list:
        """PRIMARY (first-registered) prefix's tokens — compatibility view
        for single-prefix call sites; multi-prefix logic iterates
        ``self._prefixes``."""
        return self._prefixes[0]["tokens"] if self._prefixes else []

    @property
    def _prefix_pages(self) -> list:
        return self._prefixes[0]["pages"] if self._prefixes else []

    @property
    def prefix_held_pages(self) -> int:
        """KV pages owned by ALL registered prefixes (leak-audit and page
        pool accounting: these are held for the engine's lifetime by
        design, never in any slot's grant)."""
        return sum(len(p["pages"]) for p in self._prefixes)

    def _prefix_keep_len(self, tokens: list) -> int:
        """Page-floored cacheable length of a prefix's tokens: leave at
        least one page of room for every suffix + generation, and at
        least one suffix token so the sampled first token always has a
        logit row (admission additionally enforces this per wave)."""
        max_keep = self.max_seq - max(self.page_size, 64)
        return (
            min(len(tokens) - 1, max_keep) // self.page_size
        ) * self.page_size

    def set_shared_prefix(self, text: str) -> int:
        """Replace every registered prefix with this one (idle engine
        required: live slots' tables may reference the released pages).
        An UNCACHEABLE text (too short) leaves the existing registry
        intact rather than clearing it first.  See
        :meth:`add_shared_prefix` for semantics."""
        if not self.paged:
            log.warning("set_shared_prefix needs paged KV; ignoring")
            return 0
        if self.num_active:
            raise RuntimeError(
                "set_shared_prefix requires an idle engine "
                f"({self.num_active} sequences active)"
            )
        if self._prefix_keep_len(self.tokenizer.encode(text)) < self.page_size:
            log.warning("shared prefix shorter than one page; not caching")
            return 0
        self.clear_shared_prefixes()
        return self.add_shared_prefix(text)

    def clear_shared_prefixes(self) -> None:
        """Release every registered prefix's pages (idle engine only)."""
        if self.num_active:
            raise RuntimeError(
                "clear_shared_prefixes requires an idle engine "
                f"({self.num_active} sequences active)"
            )
        for entry in self._prefixes:
            self.allocator.release(entry["pages"])
        self._prefixes = []
        self._prefix_fns.clear()

    def add_shared_prefix(self, text: str) -> int:
        """Prefill ``text``'s KV ONCE into generator-owned pages; later
        prompts that start with it skip recomputing that prefix.

        The serving workload this system exists for shares a prompt
        template across every request (SURVEY.md §2.2: 32 concurrent
        failure events -> one prefill), so each template's static preamble
        is prefilled once and every admission forwards only its suffix —
        the vLLM "automatic prefix caching" idea reduced to the FEW shared
        prefixes that actually occur (the default template plus custom
        AIProvider promptTemplates), with no radix tree and no refcounts:
        prefix pages are OWNED by the generator (never in any slot's
        grant, so sequence teardown can never free them).

        Sharing is decided per admission wave by TOKEN comparison (BPE
        boundaries need not align with the text prefix) against every
        registered prefix — the longest one EVERY row fully matches wins,
        rounded down to whole pages; a wave matching none falls back to
        the ordinary full prefill.  Over-budget prompts keep the fast
        path: admission truncation drops their MIDDLE, preserving the
        prefix head and the evidence tail (``_truncate_prompt``).

        Safe while serving: registration only ALLOCATES pages and updates
        the cache functionally (release paths — set/clear — require an
        idle engine).  Registration is idempotent by cached tokens.  Paged
        mode only.  Returns the number of prefix tokens cached (0 =
        nothing cached)."""
        jnp = self._jnp
        if not self.paged:
            log.warning("add_shared_prefix needs paged KV; ignoring")
            return 0
        tokens = self.tokenizer.encode(text)
        n_keep = self._prefix_keep_len(tokens)
        if n_keep < self.page_size:
            log.warning("shared prefix shorter than one page; not caching")
            return 0
        for entry in self._prefixes:
            if entry["tokens"] == tokens[:n_keep]:
                return n_keep  # idempotent: already cached
        if len(self._prefixes) >= self.MAX_SHARED_PREFIXES:
            log.warning(
                "shared-prefix registry full (%d); %r not cached",
                self.MAX_SHARED_PREFIXES, text[:60],
            )
            return 0
        need = n_keep // self.page_size
        if self.allocator.available - need < self.pages_per_seq:
            # prefixes must never starve admission: keep at least one full
            # sequence's worth of pages grantable (registration is an
            # optimisation — a refused one costs full prefill, not errors)
            log.warning(
                "shared prefix %r needs %d pages but only %d are free "
                "(one-sequence reserve %d); not cached",
                text[:60], need, self.allocator.available, self.pages_per_seq,
            )
            return 0
        pages = self.allocator.allocate(need)
        config, jax = self.config, self._jax
        score_shards = self._prefill_score_shards() if self.mesh is not None else 1

        def build_fn(params, paged, ids, table):
            from ..ops.paged_attention import write_tokens

            mini = KVCache.create(config, 1, n_keep, dtype=paged.k_pages.dtype)
            positions = jnp.arange(n_keep, dtype=jnp.int32)[None]
            kv_valid = jnp.ones((1, n_keep), bool)
            lengths = jnp.full((1,), n_keep, jnp.int32)
            _, mini = forward(
                params, config, ids, positions, cache=mini, cache_offset=0,
                kv_valid=kv_valid, score_shards=score_shards,
                prefill_lengths=lengths,
            )
            zero = jnp.zeros((1,), jnp.int32)
            scatter = jax.vmap(write_tokens, in_axes=(0, None, 0, None, None))
            from ..ops.paged_attention import PagedKVCache

            return PagedKVCache(
                k_pages=scatter(paged.k_pages, table, mini.k, zero, lengths),
                v_pages=scatter(paged.v_pages, table, mini.v, zero, lengths),
                page_table=paged.page_table, lengths=paged.lengths,
            )

        if self.mesh is not None:
            s = self._shardings
            build = jax.jit(
                build_fn,
                in_shardings=(
                    self._param_shardings, s["paged"], s["repl"], s["repl"]
                ),
                out_shardings=s["paged"],
            )
        else:
            build = jax.jit(build_fn)
        try:
            self.paged_cache = build(
                self.params,
                self.paged_cache,
                jnp.asarray([tokens[:n_keep]], jnp.int32),
                jnp.asarray([pages], jnp.int32),
            )
        except BaseException:
            self.allocator.release(pages)
            raise
        self._prefixes.append(
            {"text": text, "tokens": tokens[:n_keep], "pages": pages}
        )
        log.info("shared prefix cached: %d tokens in %d pages (%d registered)",
                 n_keep, len(pages), len(self._prefixes))
        return n_keep

    # ------------------------------------------------------------------
    # host-side API
    # ------------------------------------------------------------------

    @property
    def adapter_names(self) -> list[str]:
        """Registered LoRA adapter names (multi-LoRA serving)."""
        return sorted(name for name in self._adapter_ids if name is not None)

    def _alloc_decode_state(self) -> None:
        """Fresh zeroed decode state: KV cache / page pool (+ mesh
        placement) and the per-slot device vectors.  Used at construction
        and by :meth:`reset` — one code path, so post-recovery state can
        never diverge from fresh-start state."""
        jnp = self._jnp
        if self.paged:
            from ..ops.paged_attention import PagedKVCache

            self.paged_cache = PagedKVCache.create(
                self.config.num_layers, self.allocator.num_pages,
                self.page_size, self.config.num_kv_heads,
                self.config.head_dim, self.max_slots, self.pages_per_seq,
                dtype=self.cache_dtype,
            )
            if self.mesh is not None:
                self.paged_cache = self._jax.device_put(
                    self.paged_cache, self._shardings["paged"]
                )
        else:
            self.cache = KVCache.create(
                self.config, self.max_slots, self.max_seq, dtype=self.cache_dtype
            )
            if self.mesh is not None:
                self.cache = self._jax.device_put(
                    self.cache, self._shardings["cache"]
                )
        self.offsets = jnp.zeros((self.max_slots,), jnp.int32)
        self.last_tokens = jnp.zeros((self.max_slots, 1), jnp.int32)

    def cancel(self, slot_id: int) -> bool:
        """Abort a DECODING sequence and reclaim its slot/pages now.

        The capacity lever for client disconnects: without it an abandoned
        request decodes to max_tokens, holding its slot and KV pages the
        whole time.  The epoch bump orphans any in-flight decode-ahead
        blocks carrying the dead sequence.  Chunk-prefilling (reserved)
        slots can't be cancelled mid-job — their wave finishes first and a
        sweep catches them next round.  Returns True if a slot was freed.
        """
        if 0 <= slot_id < self.max_slots and self.slots[slot_id].active:
            self._finish(slot_id, reason="cancelled")
            return True
        return False

    def reset(self) -> None:
        """Drop every sequence and rebuild the device decode state.

        The recovery path after a device/tunnel error mid-step: donated
        buffers (KV cache / page pool) may be invalid, so fresh zeroed
        caches are allocated, all pages freed, and every slot emptied —
        the WEIGHTS are reused (never donated, still resident).  In-flight
        generations are lost; their futures were already failed by the
        ServingEngine before it calls this.
        """
        self._inflight_blocks.clear()
        self._prefill_job = None
        self._reserved.clear()
        # the step timeline died with the device state (black-box dumps
        # captured the tail first — _dump_blackbox runs before reset)
        self.step_clock.reset()
        self._guided_tables = None
        self._guided_index = {}
        self._guided_aut_np[:] = 0
        self.guided_aut = None
        self.guided_state = None
        prefix_texts = [p["text"] for p in self._prefixes]
        if self.paged:
            self.allocator = PageAllocator(self.allocator.num_pages)
            self._prefixes = []
            self._prefix_fns.clear()
        self._alloc_decode_state()
        for i in range(self.max_slots):
            self._slot_epoch[i] += 1  # orphan any in-flight device tokens
            self.slots[i] = _Slot()
        self._host_offsets[:] = 0
        self._sampling_cache = None
        if self.paged and prefix_texts:
            # the page pool was rebuilt: re-prime every registered prefix
            # so post-recovery admissions keep their fast path.  Guarded: a
            # failed re-prime must not fail the RECOVERY — serving without
            # the optimisation beats staying down (_try_recover treats a
            # reset() exception as fatal)
            for text in prefix_texts:
                try:
                    self.add_shared_prefix(text)
                except Exception:  # noqa: BLE001
                    log.warning(
                        "shared-prefix re-prime failed after reset; serving "
                        "without it", exc_info=True,
                    )

    def free_slots(self) -> list[int]:
        return [
            i for i, s in enumerate(self.slots)
            if not s.active and i not in self._reserved
        ]

    @property
    def num_active(self) -> int:
        # reserved (chunk-prefilling) slots count: they occupy capacity and
        # need step() calls to make progress even before decoding starts
        return sum(s.active for s in self.slots) + len(self._reserved)

    @property
    def num_decoding(self) -> int:
        return sum(s.active for s in self.slots)

    def _activate_slots(
        self, first_np, lengths, taken, params_list, page_grants, prefill_ms
    ) -> list[int]:
        """Prompt KV is in the big cache and first tokens are sampled:
        flip the slots live (shared by one-shot and chunked prefill).
        ``prefill_ms`` is prefill COMPUTE time: the chunked path passes its
        accumulated chunk+finish time, not the interleaved wall span."""
        jnp = self._jnp
        self.metrics.record("prefill", prefill_ms)
        self.metrics.record("prefill_batch", float(len(taken)))
        # step clock: the wave's prefill is one phase-separated step; its
        # compute is all "device" (the chunked path's accumulated chunk
        # time), no per-component split is measurable post-hoc
        self.step_clock.observe(
            kind="prefill",
            tokens=int(sum(int(n) for n in lengths)),
            slots=len(taken),
            host_gap_ms=0.0,
            device_ms=float(prefill_ms),
            sample_xfer_ms=0.0,
        )
        if self.num_decoding:
            # wave-engine phase separation: this admission's prefill
            # compute ran while decode slots sat idle — the stall the
            # continuous scheduler (serving/sched/) exists to remove;
            # recorded so bench.py can put a number on the difference
            self.metrics.record("decode_stall", prefill_ms)

        # paged mode tracks positions in _host_offsets + paged_cache.lengths
        # only; the device offsets array belongs to the contiguous path
        offsets = None if self.paged else np.array(self.offsets)
        last = np.array(self.last_tokens)  # mutable host copy
        for row, slot_id in enumerate(taken):
            slot = self.slots[slot_id]
            self._slot_epoch[slot_id] += 1  # new generation begins
            slot.active = True
            slot.prompt_len = int(lengths[row])
            slot.generated = [int(first_np[row])]
            slot.params = params_list[row]
            slot.started = time.perf_counter()
            slot.prefill_ms = prefill_ms
            # decode time is derived from the step clock (not wall): the
            # cumulative decode-bearing ms the clock accrues between here
            # and _finish IS this slot's decode wall
            slot.decode_cum0 = self.step_clock.decode_cum_ms
            slot.pages = page_grants[row] if self.paged else []
            last[slot_id, 0] = int(first_np[row])
            self._host_offsets[slot_id] = int(lengths[row])
            if not self.paged:
                offsets[slot_id] = int(lengths[row])
        if not self.paged:
            self.offsets = jnp.asarray(offsets)
        self.last_tokens = jnp.asarray(last)
        self._sampling_cache = None  # slot set changed
        return list(taken)

    # ------------------------------------------------------------------
    # chunked prefill (Sarathi-style interleaving; prefill_chunk knob)
    # ------------------------------------------------------------------

    def _advance_prefill(self) -> None:
        """Run ONE chunk of the pending job (or its finish step)."""
        job = self._prefill_job
        assert job is not None
        jnp = self._jnp
        n_pad, t_pad = job.key
        t0 = time.perf_counter()

        if job.written < t_pad:
            # the last chunk may be PARTIAL: t_pad buckets clamp to max_seq,
            # which need not divide the chunk size — a fixed-width slice
            # there would clamp its start and silently re-forward tokens at
            # wrong positions (jax dynamic_slice semantics)
            step_chunk = min(self.prefill_chunk, t_pad - job.written)
            fn_key = (n_pad, t_pad, step_chunk)
            if fn_key not in self._chunk_fns:
                log.info("compiling prefill chunk n=%d t=%d chunk=%d",
                         n_pad, t_pad, step_chunk)
                self._chunk_fns[fn_key] = self._aot_wrap(
                    f"chunk_n{n_pad}_t{t_pad}_c{step_chunk}",
                    self._make_chunk_fn(n_pad, t_pad, step_chunk),
                )
            ids_chunk = self._jax.lax.dynamic_slice_in_dim(
                job.ids, job.written, step_chunk, axis=1
            )
            with self._annotation("podmortem.prefill_chunk", job.params_list):
                job.mini, job.last_logits = self._chunk_fns[fn_key](
                    self.params, job.mini, ids_chunk, job.lengths,
                    jnp.int32(job.written), job.last_logits,
                    self.lora, job.adapter_idx,
                )
            job.written += step_chunk
            elapsed_ms = (time.perf_counter() - t0) * 1e3
            job.chunk_ms += elapsed_ms
            self.metrics.record("prefill_chunk", elapsed_ms)
            if job.written < t_pad:
                return
            t0 = time.perf_counter()  # finish timed separately (no double count)
        # all chunks written: scatter + sample, then activate.  Guided
        # rows mask the first token at the finish step; the automaton
        # indices are resolved NOW (admissions between this job's chunks
        # may have restacked the tables)
        job_specs = [self._guided_spec(p) for p in job.params_list]
        if any(job_specs) or self._guided_tables is not None:
            self._refresh_guided_tables(job_specs)
        # SAME guard as the one-shot path: whenever tables are live, every
        # activated slot gets its automaton binding (identity for unguided
        # rows) — a recycled slot may hold a stale accept-state whose
        # padding row would mask ALL logits for an unguided occupant
        guided = self._guided_tables is not None
        row_aut = (
            self._guided_row_aut(job_specs, n_pad) if guided
            else np.zeros((n_pad,), np.int32)
        )
        guided_args = (
            (self._guided_tables, jnp.asarray(row_aut)) if guided else ()
        )
        fn_key2 = (n_pad, t_pad, guided)
        if fn_key2 not in self._finish_fns:
            self._finish_fns[fn_key2] = self._aot_wrap(
                f"finish_n{n_pad}_t{t_pad}_g{int(guided)}",
                self._make_finish_fn(n_pad, t_pad, guided),
            )
        if self.paged:
            staged, row_tables = self._stage_page_tables(
                len(job.taken), n_pad, job.slot_ids_np, job.page_grants,
                job.lengths_np,
            )
            with self._annotation("podmortem.prefill_finish", job.params_list):
                outs = self._finish_fns[fn_key2](
                    staged, job.mini, job.lengths,
                    jnp.asarray(row_tables), job.last_logits,
                    self._rng, job.temp, job.top_p, *guided_args,
                )
        else:
            with self._annotation("podmortem.prefill_finish", job.params_list):
                outs = self._finish_fns[fn_key2](
                    self.cache, job.mini, job.lengths,
                    jnp.asarray(job.slot_ids_np), job.last_logits,
                    self._rng, job.temp, job.top_p, *guided_args,
                )
        if guided:
            cache_out, first_tokens, self._rng, first_state = outs
        else:
            cache_out, first_tokens, self._rng = outs
        if self.paged:
            self.paged_cache = cache_out
        else:
            self.cache = cache_out
        self._prefill_job = None
        self._reserved.difference_update(job.taken)
        finish_ms = (time.perf_counter() - t0) * 1e3
        self._activate_slots(
            np.asarray(first_tokens), job.lengths_np, job.taken,
            job.params_list, job.page_grants, job.chunk_ms + finish_ms,
        )
        if guided:
            self._apply_guided_activation(row_aut, job.taken, first_state)

    # tracing ------------------------------------------------------------
    def _annotation(self, name: str, params_list: Optional[list] = None):
        """Host-side profiler marker around a prefill/decode region
        (``jax.profiler.TraceAnnotation``) carrying the obs trace tags of
        the wave, TraceMe-encoded (``name#trace=a,b#``) so an xplane
        capture (scripts/analyze_xplane.py) joins the flight recorder's
        per-analysis timeline.  A TraceMe costs nanoseconds while no
        profiler session is active, so every step wears one."""
        tags = sorted({
            p.trace_tag for p in (params_list or [])
            if p is not None and getattr(p, "trace_tag", None)
        })
        if tags:
            name = f"{name}#trace={','.join(tags)}#"
        try:
            return self._jax.profiler.TraceAnnotation(name)
        except Exception:  # noqa: BLE001 - profiler API unavailable: annotate nothing
            import contextlib

            return contextlib.nullcontext()

    def _sampling_tensors(self):
        """(active_np, temp_dev, top_p_dev, active_dev), rebuilt only when
        the slot set changes (admit/finish) — not every decode step."""
        if self._sampling_cache is None:
            jnp = self._jnp
            active = np.array([s.active for s in self.slots])
            temp = np.array(
                [s.params.temperature if s.active else 0.0 for s in self.slots],
                np.float32,
            )
            top_p = np.array(
                [s.params.top_p if s.active else 1.0 for s in self.slots], np.float32
            )
            adapter_idx = np.array(
                [self._adapter_ids[s.params.adapter] if s.active else 0
                 for s in self.slots],
                np.int32,
            )
            put = self._put_batch_vec
            self._sampling_cache = (
                active, put(temp), put(top_p), put(active), put(adapter_idx)
            )
        return self._sampling_cache

    def step(self) -> list[tuple[int, GenerationResult]]:
        """One decode round: dispatch a block, then process the oldest
        fetched block's tokens; returns finished (slot, result) pairs.

        With ``pipeline_depth=1`` the block just dispatched is fetched and
        processed immediately (classic synchronous decode).  With depth D>1,
        up to D-1 blocks stay IN FLIGHT while the host processes older
        tokens — the host<->device round trip (which dominates a tunneled
        TPU's block time) overlaps the next block's compute.  Slots may
        decode up to (D-1) extra junk blocks past their stop condition into
        their OWN rows/pages (the max_seq guard margin accounts for it);
        per-slot epochs keep a reused slot from ever consuming a stale
        block's tokens.
        """
        if self.num_active == 0 and not self._inflight_blocks:
            return []
        if self.fault_plan is not None:
            # chaos seam: a sleep action stalls this step (we run on the
            # decode worker, never the event loop); a raise action
            # simulates a device/tunnel error mid-step, driving the
            # ServingEngine recovery path (_try_recover -> reset)
            self.fault_plan.apply("engine.step", active=self.num_active)
        if self._prefill_job is not None:
            # one chunk per round: in-flight decodes stall for at most one
            # chunk's wall time before their next block dispatches
            self._advance_prefill()
        started = time.perf_counter()
        block = self.decode_block
        if self.num_decoding:
            # HELD slots (decoding + chunk-prefill reserved) over
            # capacity — the same definition the continuous scheduler's
            # sched_occupancy uses, so bench.py compares like with like
            self.metrics.record(
                "batch_occupancy", 100.0 * self.num_active / self.max_slots
            )
            with self._annotation(
                "podmortem.decode",
                [s.params for s in self.slots if s.active],
            ):
                self._dispatch_block()
        finished: list[tuple[int, GenerationResult]] = []
        # keep at most depth-1 blocks in flight; once nothing is active the
        # leftovers are flushed (their tokens belong to finished epochs)
        processed = 0
        while self._inflight_blocks and (
            len(self._inflight_blocks) >= self.pipeline_depth
            or self.num_active == 0
        ):
            finished.extend(self._process_block(*self._inflight_blocks.pop(0)))
            processed += 1
        if processed:  # dispatch-only warmup steps would skew the histograms
            elapsed_ms = (time.perf_counter() - started) * 1e3
            self.metrics.record("decode_step", elapsed_ms / (processed * block))
            if block > 1:
                self.metrics.record("decode_block", elapsed_ms / processed)
        return finished

    def _dispatch_block(self) -> None:
        """Launch one decode block; tokens stay on device until processed."""
        block = self.decode_block
        active, temp_dev, top_p_dev, active_dev, idx_dev = self._sampling_tensors()
        lora_idx = idx_dev if self.lora is not None else None
        if self._guided_tables is not None:
            fn = self._get_guided_decode_fn()
            if self.paged:
                (self.paged_cache, toks, last, self._rng,
                 self.guided_state) = fn(
                    self.params, self.paged_cache, self.last_tokens, self._rng,
                    temp_dev, top_p_dev, active_dev, self.lora, lora_idx,
                    self._guided_tables, self.guided_aut, self.guided_state,
                )
            else:
                (self.cache, toks, last, self.offsets, self._rng,
                 self.guided_state) = fn(
                    self.params, self.cache, self.last_tokens, self.offsets,
                    self._rng, temp_dev, top_p_dev, active_dev, self.lora,
                    lora_idx, self._guided_tables, self.guided_aut,
                    self.guided_state,
                )
        elif self.paged:
            self.paged_cache, toks, last, self._rng = self._decode_fn(
                self.params, self.paged_cache, self.last_tokens, self._rng,
                temp_dev, top_p_dev, active_dev, self.lora, lora_idx,
            )
        else:
            self.cache, toks, last, self.offsets, self._rng = self._decode_fn(
                self.params, self.cache, self.last_tokens, self.offsets, self._rng,
                temp_dev, top_p_dev, active_dev, self.lora, lora_idx,
            )
        self.last_tokens = last
        # snapshot which generation of each slot this block belongs to and
        # how many tokens it held pre-block, BEFORE advancing the shadow
        snapshot = {
            i: (self._slot_epoch[i], int(self._host_offsets[i]))
            for i, slot in enumerate(self.slots)
            if slot.active
        }
        self._host_offsets[active] += block
        # step-clock stamps: dispatch time + the host gap since the last
        # processed block's commit travel WITH the block, because with
        # pipeline_depth > 1 it is processed (and its record written) a
        # later round than it was dispatched
        t_dispatch = time.perf_counter()
        self._inflight_blocks.append((
            toks, snapshot,
            (t_dispatch, self.step_clock.host_gap_ms(t_dispatch), len(snapshot)),
        ))

    def _process_block(
        self, toks, snapshot, timing=None
    ) -> list[tuple[int, GenerationResult]]:
        block = self.decode_block
        if timing is not None:
            # resolve dispatch->ready BEFORE the fetch: the asarray below
            # would block on the same completion event anyway, so this adds
            # no new host sync — it only splits the wait into device time
            # vs the sampled-token device->host transfer (GL001: this
            # method is host loop code, never reachable from a jitted
            # entry point — same legality as the asarray it times)
            try:
                toks.block_until_ready()
            except AttributeError:  # fake arrays in tests
                pass
            t_ready = time.perf_counter()
        toks_np = np.asarray(toks)  # [K, B] — the ONE host sync per block
        if timing is not None:
            t_fetch = time.perf_counter()
            t_dispatch, host_gap_ms, live = timing
            self.step_clock.observe(
                kind="decode",
                tokens=block * live,
                slots=live,
                host_gap_ms=host_gap_ms,
                # device window is dispatch -> ready; waiting began at
                # t_ready0, but the block may have been ready long before
                # (pipelined depth>1), in which case the wait is ~0
                device_ms=max(0.0, (t_ready - t_dispatch) * 1e3),
                sample_xfer_ms=max(0.0, (t_fetch - t_ready) * 1e3),
                # the token-processing loop below runs AFTER the commit
                # stamp, so its wall lands in the NEXT record's host gap
                commit_t=t_fetch,
            )
        finished: list[tuple[int, GenerationResult]] = []
        eos = self.tokenizer.eos_id
        for i, (epoch, before) in snapshot.items():
            slot = self.slots[i]
            # the slot moved on (finished, possibly re-admitted) after this
            # block was dispatched: its lanes hold junk for the new epoch
            if not slot.active or self._slot_epoch[i] != epoch:
                continue
            generated_before = len(slot.generated)
            for k in range(block):
                token = int(toks_np[k, i])
                previous = slot.generated[-1] if slot.generated else None
                # the PREVIOUS sampled token ended generation?
                if slot.params.stop_on_eos and eos is not None and previous == eos:
                    finished.append((i, self._finish(i, reason="stop")))
                    break
                if len(slot.generated) >= slot.params.max_tokens:
                    # budget already consumed (the prefill-sampled token
                    # counts); discard this token so max_tokens is exact
                    finished.append((i, self._finish(i, reason="length")))
                    break
                slot.generated.append(token)
                total = before + k + 1
                # stop pipeline_depth BLOCKS short of max_seq: the device
                # decodes that many further blocks before the host can stop
                # it, and those writes must stay inside the slot's cache
                # row / pages
                if (
                    len(slot.generated) >= slot.params.max_tokens
                    or total >= self.max_seq - self.pipeline_depth * block
                ):
                    finished.append((i, self._finish(i, reason="length")))
                    break
            if (
                self.partial_hook is not None
                # identity: _finish() swaps in a fresh _Slot, so a slot that
                # finished inside this block is skipped (its result carries
                # the tail) — `slot.active` alone would read the OLD object
                and self.slots[i] is slot
                and len(slot.generated) > generated_before
            ):
                # list COPY: the hook crosses into the event-loop thread
                # while this worker keeps appending
                self.partial_hook(i, list(slot.generated))
        return finished

    def _finish(self, slot_id: int, *, reason: str) -> GenerationResult:
        slot = self.slots[slot_id]
        if self.paged and slot.pages:
            # point the slot's table row at the trash page BEFORE releasing
            # the grant — the freed pages may be handed to a new sequence
            # while this slot row still participates in batched decode
            from ..ops.paged_attention import PagedKVCache

            jnp = self._jnp
            paged = self.paged_cache
            self.paged_cache = PagedKVCache(
                k_pages=paged.k_pages, v_pages=paged.v_pages,
                page_table=paged.page_table.at[slot_id].set(0),
                lengths=paged.lengths.at[slot_id].set(0),
            )
            self.allocator.release(slot.pages)
        self._slot_epoch[slot_id] += 1  # stale in-flight tokens now orphaned
        self._host_offsets[slot_id] = 0
        self._sampling_cache = None  # slot set changed
        if self._guided_tables is not None:
            if self._guided_aut_np[slot_id]:
                self._guided_aut_np[slot_id] = 0
                self.guided_aut = self._put_batch_vec(self._guided_aut_np)
            if not self._guided_aut_np.any() and not any(
                s.active and self._guided_spec(s.params)
                for i, s in enumerate(self.slots)
                if i != slot_id  # this slot is finishing right now
            ):
                self._guided_tables = None  # back to the unguided programs
                self._guided_index = {}
                self.guided_aut = None
                self.guided_state = None
        eos = self.tokenizer.eos_id
        ids = [t for t in slot.generated if t != eos]
        text = self.tokenizer.decode(ids)
        if reason == "length" and slot.params.deadline_clamped:
            # the length cap was the deadline budget's roofline clamp, not
            # the caller's max_tokens — surface the difference
            reason = "deadline"
        result = GenerationResult(
            text=text,
            token_ids=ids,
            prompt_tokens=slot.prompt_len,
            completion_tokens=len(ids),
            finish_reason=reason,
            prefill_ms=slot.prefill_ms,
            # decode wall DERIVED FROM THE STEP CLOCK: the decode-bearing
            # ms the ring accrued while the slot was live (monotonic
            # cumulative, so ring eviction cannot corrupt it).  The old
            # coarse wall delta (now - slot.started) could disagree with
            # the step records; this cannot.
            decode_ms=max(
                0.0, self.step_clock.decode_cum_ms - slot.decode_cum0
            ),
            queue_wait_ms=slot.queue_wait_ms,
        )
        self.slots[slot_id] = _Slot()
        return result

    # profiling ---------------------------------------------------------
    def trace(self, log_dir: str):
        """``jax.profiler.trace`` context around a serving span: writes an
        xplane protobuf under ``log_dir`` for tensorboard/xprof (SURVEY.md
        §5 tracing — the reference has none; the TPU side needs it to
        attribute the p50 budget between prefill, decode and host work)."""
        return self._jax.profiler.trace(log_dir)

    # convenience for tests / bench -------------------------------------
    def generate(self, prompt: str, params: Optional[SamplingParams] = None) -> GenerationResult:
        """Synchronous single-prompt generation (drains the whole batch)."""
        sampling = params or SamplingParams()
        [slot_id] = self.admit([prompt], [sampling])
        while True:
            for finished_id, result in self.step():
                if finished_id == slot_id:
                    return result


class ServingEngine:
    """Asyncio front: queue -> admission -> shared decode loop -> futures.

    The decode loop runs JAX calls in a worker thread so the operator's
    event loop never blocks on device sync (the reference's worker-pool
    discipline, SURVEY.md §5 race-detection entry).
    """

    def __init__(
        self,
        generator: BatchedGenerator,
        *,
        admission_wait_s: float = 0.004,
        max_queue: int = 1024,
        supervisor: Optional[SupervisorPolicy] = None,
        recorder: Optional[Any] = None,  # obs.FlightRecorder for black boxes
        scheduler: Optional[Any] = None,  # sched.Scheduler: continuous mode
    ) -> None:
        import concurrent.futures

        self.generator = generator
        self.admission_wait_s = admission_wait_s
        #: continuous-batching scheduler (serving/sched/): when set, the
        #: serve loop runs schedule→dispatch→commit steps over ragged
        #: mixed prefill+decode waves instead of the wave machinery —
        #: _pending is then keyed by scheduler req id, not slot id
        self._sched = scheduler
        if scheduler is not None:
            scheduler.partial_hook = self._on_partial_from_worker
        #: watchdog policy (None = pre-supervisor semantics: loop death
        #: fails in-flight futures, stalls hang until the step returns)
        self._supervisor = supervisor
        self.recorder = recorder
        self._supervise_task: Optional[asyncio.Task] = None
        self._supervise_wakeup = asyncio.Event()
        self._stalled = False  # last loop death was a stall (executor abandoned)
        self._gave_up = False  # supervisor exhausted its reset budget
        # survivors collected by a restart in progress: close() must still
        # fail these futures if it interrupts the supervisor mid-recovery
        self._restarting: list[_Request] = []
        # one persistent worker: no per-step thread handoff through the
        # shared default executor (contextvars copy + pool contention), and
        # all jax dispatch happens from a single consistent thread
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="tpu-decode"
        )
        # priority queue: (-priority, arrival_seq, entry) — higher-priority
        # requests admit first, FIFO within a class.  The operator pipeline
        # submits explanations at priority 10 so external completion-API
        # callers sharing the engine cannot starve incident analysis.  The
        # queue itself is unbounded; max_queue bounds only the priority<=0
        # lane (via semaphore), so a flood of external callers blocks THEIR
        # puts while high-priority puts always enter immediately — a bounded
        # PriorityQueue would grant space to put-waiters in FIFO order,
        # reintroducing the starvation at the put() boundary.
        self._queue: asyncio.PriorityQueue = asyncio.PriorityQueue()
        self._low_lane = asyncio.Semaphore(max_queue)
        self._seq = itertools.count()
        self._pending: dict[int, _Request] = {}  # slot id -> admitted request
        self._inflight: list[_Request] = []  # popped from queue, not yet admitted
        # streaming: future -> on_partial registered in generate(); slot ->
        # on_partial once admitted.  The generator's hook fires on the
        # decode worker; call_soon_threadsafe marshals it onto the loop.
        self._partial_by_future: dict[asyncio.Future, Any] = {}
        self._partial_cbs: dict[int, Any] = {}
        #: key -> token count already delivered to the stream (loop-side
        #: monotonicity guard: pipelined commits + cancellation can leave
        #: stale snapshot deliveries queued behind a restart's fresh
        #: ones — a snapshot that does not EXTEND the stream is dropped)
        self._partial_sent: dict[int, int] = {}
        # single-flight dedup for guided-automaton builds (ensure_guided)
        self._guided_builds: dict[tuple, asyncio.Future] = {}
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        generator.partial_hook = self._on_partial_from_worker
        self._stalled_avail: Optional[int] = None  # pages free at last stall
        self._task: Optional[asyncio.Task] = None
        self._closed = False
        self._error: Optional[BaseException] = None
        # auto-recovery after a loop death (transient device/tunnel errors):
        # bounded resets per window, so a persistent fault still surfaces
        self._reset_times: list[float] = []
        self._reset_lock = asyncio.Lock()
        # per-class SLO aggregates (obs/sloledger.py SLOBoard): bounded
        # O(classes) state carried on load_report()/healthz and rolled up
        # fleet-wide by the router.  Metric-free — the operator-side
        # ledger owns the podmortem_slo_* counters, so an in-process
        # operator+serving pair never double-counts.
        from ..obs.sloledger import SLOBoard

        self._slo_board = SLOBoard()
        #: fleet KV fabric (operator_tpu/fabric/): a FabricFetcher wired
        #: post-construction when KV_FABRIC=1; admission-time prefix
        #: misses then consult the fleet index and pull pages from a
        #: holder's host pool instead of recomputing.  None = local-only
        #: (the pre-fabric behaviour, and the default).
        self.fabric: Optional[Any] = None
        #: fabric/peers.py PeerPoller feeding the fetcher's index from
        #: peer /healthz inventories (KV_FABRIC_PEERS) — the standalone
        #: replica's substitute for an in-process router's kv_index.
        #: Wired post-construction; start() runs it, close() cancels it.
        self.fabric_poller: Optional[Any] = None
        self._fabric_poll_task: Optional[asyncio.Task] = None
        #: prefill/decode disaggregation role advertised on /healthz
        #: (fabric/disagg.py): "prefill" | "decode" | "mixed"
        self.replica_role: str = "mixed"

    def _unwrap(self, item: tuple) -> "_Request":
        """Pop bookkeeping for a queue entry: low-lane slots free on pop.
        Supervisor requeues re-enter at priority >= 1 (never through the
        lane), so the release here stays balanced."""
        neg_priority, _, request = item
        if neg_priority >= 0:  # priority <= 0 went through the bounded lane
            self._low_lane.release()
        return request

    def _page_stalled(self, batch: list) -> bool:
        """True when a backpressured batch has no new pages to retry with —
        skipping the retry avoids re-tokenising every waiting prompt each
        loop round while decode slowly frees pages."""
        if self._stalled_avail is None:
            return False
        allocator = getattr(self.generator, "allocator", None)
        if allocator is None:
            return False
        if allocator.available > self._stalled_avail:
            self._stalled_avail = None
            return False
        return True

    #: auto-recovery budget: at most this many loop restarts per window —
    #: a persistent device fault must still surface instead of silently
    #: thrashing (reference-equivalent discipline: the watch loop's 5s
    #: auto-restart is likewise unconditional but visible in events)
    MAX_RESETS_PER_WINDOW = 3
    RESET_WINDOW_S = 600.0

    def _reset_engine(self) -> None:
        """Rebuild device state after a loop death (decode worker).  In
        continuous mode the scheduler's host rows/queue are dropped too —
        the supervisor already collected their requests as survivors."""
        self.generator.reset()
        if self._sched is not None:
            self._sched.reset()

    async def _try_recover(self) -> None:
        """One bounded attempt to revive a dead serve loop.

        A transient device/tunnel error mid-step may have invalidated the
        DONATED buffers (KV cache / page pool), so the generator rebuilds
        its decode state from scratch (weights survive); in-flight requests
        were already failed when the loop died.  Leaves ``_error`` set when
        the reset budget is exhausted or the rebuild itself fails.
        """
        async with self._reset_lock:
            if self._error is None or self._closed:  # raced another caller
                return
            now = time.monotonic()
            self._reset_times = [
                t for t in self._reset_times if now - t < self.RESET_WINDOW_S
            ]
            if len(self._reset_times) >= self.MAX_RESETS_PER_WINDOW:
                return
            self._reset_times.append(now)
            log.warning(
                "serving engine loop died (%s); resetting device state and "
                "restarting (%d/%d resets in window)",
                self._error, len(self._reset_times), self.MAX_RESETS_PER_WINDOW,
            )
            loop = asyncio.get_running_loop()
            try:
                await loop.run_in_executor(self._executor, self._reset_engine)
            except Exception as exc:  # noqa: BLE001 - rebuild failed: stay dead
                log.exception("engine reset failed; staying down")
                self._error = exc
                return
            self._error = None
            self._task = None  # the caller's generate() starts a fresh loop

    # ------------------------------------------------------------------
    # supervisor (SupervisorPolicy; docs/ROBUSTNESS.md)
    # ------------------------------------------------------------------
    async def _supervise(self) -> None:
        """Watchdog task: woken by a serve-loop death (error or stall),
        performs the supervised restart.  Runs for the engine's lifetime so
        recovery is PROACTIVE — in-flight work is requeued immediately, not
        lazily when the next caller happens to notice."""
        while not self._closed:
            await self._supervise_wakeup.wait()
            self._supervise_wakeup.clear()
            if self._closed:
                return
            if self._error is None:
                continue
            try:
                await self._supervised_restart()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - the watchdog must outlive one bad restart
                log.exception("supervised engine restart itself failed")

    def _audit_leaks(self) -> dict:
        """Post-reset invariant check: every slot free, every non-prefix
        page back in the pool.  A non-empty result means reset() has a
        reclamation bug — surfaced as podmortem_supervisor_leak_total and
        in the black-box dump rather than silently shrinking capacity."""
        generator = self.generator
        leaks: dict = {}
        free = len(generator.free_slots())
        if free != generator.max_slots:
            leaks["slots"] = generator.max_slots - free
        allocator = getattr(generator, "allocator", None)
        if allocator is not None:
            expected = allocator.num_pages - 1 - generator.prefix_held_pages
            if allocator.available != expected:
                leaks["pages"] = expected - allocator.available
        return leaks

    def _dump_blackbox(self, reason: str, extra: dict) -> None:
        """Black-box flight-recorder dump for a supervisor event — a
        synthetic one-span trace (there is no ambient analysis trace on
        the engine's own watchdog) carrying the restart context."""
        recorder = self.recorder
        if recorder is None:
            try:
                from ..obs import RECORDER as recorder
            except Exception:  # noqa: BLE001 - forensics must never block recovery
                return
        try:
            # the stall's preceding timeline: the last step records BEFORE
            # the reset wipes the clock (obs.view --steps renders them)
            if "steps" not in extra:
                steps = self.generator.step_clock.ring.records(last=32)
                if steps:
                    extra = {**extra, "steps": [r.to_dict() for r in steps]}
        except Exception:  # noqa: BLE001 - forensics must never block recovery
            pass
        try:
            from ..obs import Tracer

            tracer = Tracer(recorder=recorder)
            with tracer.trace(
                "engine.supervisor", attributes={"reason": reason}
            ) as root:
                pass
            recorder.black_box(root.trace_id, reason, extra)
        except Exception:  # noqa: BLE001 - forensics must never block recovery
            log.warning("supervisor black-box dump failed", exc_info=True)

    def _collect_survivors(self) -> "tuple[list[_Request], int]":
        """Gather every in-flight request (admitted, in hand, queued) for
        requeueing; requests already requeued ``max_requeues`` times are
        failed now.  Returns (requeue list, gaveup count)."""
        assert self._supervisor is not None
        requests: list[_Request] = []
        for slot_id, request in self._pending.items():
            callback = self._partial_cbs.get(slot_id)
            if callback is not None:
                # re-arm streaming: the old slot id dies with the engine
                # state, the re-admitted request gets a fresh one
                self._partial_by_future[request.future] = callback[0]
            requests.append(request)
        self._pending.clear()
        self._partial_cbs.clear()
        self._partial_sent.clear()
        requests.extend(self._inflight)
        self._inflight.clear()
        while not self._queue.empty():
            requests.append(self._unwrap(self._queue.get_nowait()))
        retry: list[_Request] = []
        gaveup = 0
        for request in requests:
            if request.future.done():
                self._partial_by_future.pop(request.future, None)
            elif request.requeues >= self._supervisor.max_requeues:
                self._partial_by_future.pop(request.future, None)
                failure = RuntimeError(
                    "request failed after a supervised engine restart "
                    f"(requeued {request.requeues}x)"
                )
                failure.__cause__ = self._error
                request.future.set_exception(failure)
                self.generator.metrics.incr("supervisor_gaveup")
                gaveup += 1
            else:
                retry.append(request)
        return retry, gaveup

    def _fail_survivors(self, retry: "list[_Request]", why: str) -> int:
        failed = 0
        for request in retry:
            if request.future.done():
                continue
            self._partial_by_future.pop(request.future, None)
            failure = RuntimeError(why)
            failure.__cause__ = self._error
            request.future.set_exception(failure)
            self.generator.metrics.incr("supervisor_gaveup")
            failed += 1
        return failed

    def _give_up_restart(
        self, retry: "list[_Request]", gaveup: int, *,
        reason: str, cause: str, message: str, outcome: str,
    ) -> None:
        """Terminal exit of a supervised restart: fail the survivors, mark
        the engine given-up, drain stragglers that enqueued DURING the
        restart (after survivor collection emptied the queue — no serve
        loop is left to consume them), and leave a black-box dump."""
        gaveup += self._fail_survivors(retry, message)
        self._restarting = []
        self._gave_up = True
        self._fail_outstanding(RuntimeError(message))
        self._dump_blackbox(reason, {
            "cause": cause, "gaveup": gaveup, "requeued": 0,
            "outcome": outcome,
        })

    async def _supervised_restart(self) -> None:
        """The supervisor's recovery sequence: collect survivors, retire a
        stalled decode thread, reset device state (bounded resets per
        window — a persistent fault must surface, not thrash), audit
        slot/page leaks, restart the loop, requeue survivors once with
        their residual deadlines, and leave a black-box dump behind."""
        policy = self._supervisor
        assert policy is not None
        loop = asyncio.get_running_loop()
        restart_t0 = time.monotonic()
        stalled = self._stalled
        reason = "engine-stall" if stalled else "engine-error"
        cause = str(self._error)
        # the stall's preceding step timeline, captured BEFORE the device
        # reset wipes the step clock with the rest of decode state
        try:
            step_tail = [
                r.to_dict()
                for r in self.generator.step_clock.ring.records(last=32)
            ]
        except Exception:  # noqa: BLE001 - forensics must never block recovery
            step_tail = []
        retry, gaveup = self._collect_survivors()
        # parked here until requeued/failed: if close() interrupts this
        # restart, _fail_outstanding still reaches these futures
        self._restarting = retry
        if stalled:
            # the wedged worker thread cannot be interrupted: ABANDON its
            # executor and give the orphan a bounded grace to come back —
            # in the common case (a transient runtime hiccup) it returns
            # and the reset below runs with no concurrent mutator; in a
            # true device hang we proceed under it after the grace (the
            # reset rebuilds all decode state anyway)
            import concurrent.futures
            import threading

            old = self._executor
            self._executor = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="tpu-decode"
            )
            # a DEDICATED daemon thread performs the blocking join:
            # parking old.shutdown(wait=True) on the shared default
            # executor would permanently consume one of its threads every
            # time the wedged decode thread never returns
            joiner = threading.Thread(
                target=lambda: old.shutdown(wait=True),
                name="tpu-decode-reaper", daemon=True,
            )
            joiner.start()
            await loop.run_in_executor(None, joiner.join, policy.join_grace_s)
            if joiner.is_alive():
                log.error(
                    "stalled decode thread still wedged after %.1fs; "
                    "resetting device state under it", policy.join_grace_s,
                )
            self._stalled = False
        now = time.monotonic()
        self._reset_times = [
            t for t in self._reset_times if now - t < self.RESET_WINDOW_S
        ]
        if len(self._reset_times) >= self.MAX_RESETS_PER_WINDOW:
            self._give_up_restart(
                retry, gaveup, reason=reason, cause=cause,
                message="serving engine down: supervisor reset budget exhausted",
                outcome="reset-budget-exhausted",
            )
            log.error("engine supervisor giving up: %d resets within %.0fs",
                      self.MAX_RESETS_PER_WINDOW, self.RESET_WINDOW_S)
            return
        self._reset_times.append(now)
        try:
            await loop.run_in_executor(self._executor, self._reset_engine)
        except Exception as exc:  # noqa: BLE001 - rebuild failed: stay down
            log.exception("supervised engine reset failed; staying down")
            self._error = exc
            self._give_up_restart(
                retry, gaveup, reason=reason, cause=cause,
                message="serving engine down: device-state reset failed",
                outcome="reset-failed",
            )
            return
        leaks = self._audit_leaks()
        if leaks:
            self.generator.metrics.incr("supervisor_leak")
            log.error("post-reset leak audit failed: %s", leaks)
        self._error = None
        self._task = None
        await self.start()
        self._restarting = []
        for request in retry:
            request.requeues += 1
            self.generator.metrics.incr("supervisor_requeue")
            # requeues re-enter ABOVE the normal priority lanes (they were
            # already admitted once) and outside the bounded low lane (its
            # slot was released when the entry was first popped); their
            # deadline is an absolute instant, so the residual budget
            # carries through the restart automatically
            await self._queue.put(
                (-max(request.priority, 1), next(self._seq), request)
            )
        self.generator.metrics.incr("supervisor_restart")
        # restart-to-ready: device reset through loop restart + requeue.
        # With the AOT cache the reset's program rebuilds deserialize
        # instead of recompiling, which is what keeps this in seconds
        ready_s = time.monotonic() - restart_t0
        self.generator.metrics.set_gauge(
            "supervisor_restart_ready_seconds", round(ready_s, 3)
        )
        aot = getattr(self.generator, "_aot", None)
        self._dump_blackbox(reason, {
            "cause": cause,
            "requeued": len(retry),
            "gaveup": gaveup,
            "leaks": leaks,
            "resets_in_window": len(self._reset_times),
            "restart_ready_s": round(ready_s, 3),
            "aot_cache": aot.stats() if aot is not None else "off",
            "steps": step_tail,
        })
        log.warning(
            "supervised engine restart (%s) ready in %.2fs: %d requeued, "
            "%d failed, leaks=%s",
            reason, ready_s, len(retry), gaveup, leaks or "none",
        )

    def _on_partial_from_worker(self, slot_id: int, token_ids: list) -> None:
        """Generator hook (decode worker thread) -> event-loop callback."""
        entry = self._partial_cbs.get(slot_id)
        if entry is None or self._loop is None:
            return
        callback, future = entry
        if future.done():  # streaming client cancelled; slot drains unheard
            return
        self._loop.call_soon_threadsafe(
            self._deliver_partial, slot_id, callback, future, token_ids
        )

    def _deliver_partial(
        self, key: int, callback: Any, future: "asyncio.Future",
        token_ids: list,
    ) -> None:
        """Loop-side partial delivery with a per-request order guard.

        The worker's ``future.done()`` check races cancellation, and a
        supervised restart can interleave a dead registration's queued
        snapshots with the requeued request's fresh ones (same wave-mode
        slot key).  Re-checking here — and delivering only snapshots
        that strictly EXTEND what this key's stream already saw — makes
        the stream per-request monotonic in token order regardless of
        how commits and cancellations interleave."""
        if future.done() or self._partial_cbs.get(key, (None, None))[1] is not future:
            return
        if len(token_ids) <= self._partial_sent.get(key, 0):
            return  # stale snapshot: would rewind the stream
        self._partial_sent[key] = len(token_ids)
        callback(token_ids)

    def load_report(self):
        """This replica's load, in the shape the data-plane router's shed
        decision reads (``operator_tpu/router/health.py: ReplicaLoad``):
        queue pressure, the admission roofline's own per-token estimate
        (so the router's residual-fit check agrees with what THIS replica
        would clamp a deadline to), and whether the supervisor gave up.
        Cheap loop-side reads — approximate under concurrent decode is
        fine, the router treats it as feedback, not truth.  Served on
        ``GET /healthz`` (serving/httpserver.py) next to the replica id."""
        from ..router.health import ReplicaLoad

        if self._sched is not None:
            # _pending holds EVERY handed-off request (admitted rows AND
            # scheduler-queued ones), so counting _pending next to
            # sched.queue_depth would tally queued requests twice and
            # make this replica look ~2x as loaded as a wave-mode twin
            queue_depth = self._queue.qsize() + self._sched.queue_depth
            inflight = len(self._inflight) + self._sched.num_active
        else:
            queue_depth = self._queue.qsize()
            inflight = len(self._inflight) + len(self._pending)
        # step-timing summary (obs/steptrace.py): the measured decode MFU,
        # host-gap fraction and occupancy the operator's /fleet view rolls
        # up across replicas — None until steps have been recorded
        summary = self.generator.step_clock.summary()
        fractions = summary.get("fractions") or {}
        # KV economy (serving/kvstore.py): page headroom + prefix hit
        # rate for the router's informed-affinity choice, plus a bounded
        # block-hash inventory so a failover can prefer a survivor that
        # already holds the prompt's blocks (the peer index)
        kv_pages_free = 0
        kv_pages_total = 0
        allocator = getattr(self.generator, "allocator", None)
        if allocator is not None:
            kv_pages_free = allocator.available
            kv_pages_total = allocator.num_pages - 1
        prefix_hit_rate = None
        prefix_lookups = 0
        kv_blocks = None
        kvstore = getattr(self._sched, "_kvstore", None)
        if kvstore is not None:
            prefix_hit_rate = kvstore.hit_rate()
            prefix_lookups = kvstore.lookups
            kv_blocks = kvstore.inventory()
        return ReplicaLoad(
            queue_depth=queue_depth,
            inflight=inflight,
            decode_token_s=self.generator.decode_token_estimate_s(),
            gave_up=self._gave_up,
            decode_mfu=summary.get("decode_mfu"),
            host_gap_frac=fractions.get("host_gap"),
            occupancy=summary.get("occupancy_avg"),
            steps=summary.get("steps") or 0,
            slo_attainment=self._slo_board.attainment(),
            goodput_tokens_s=self._slo_board.goodput_tokens_s(),
            slo_completed=self._slo_board.completed,
            slo_classes=self._slo_board.per_class(),
            kv_pages_free=kv_pages_free,
            kv_pages_total=kv_pages_total,
            prefix_hit_rate=prefix_hit_rate,
            prefix_lookups=prefix_lookups,
            kv_blocks=kv_blocks,
            role=self.replica_role,
            shed=(
                self.generator.metrics.labeled_total("shed")
                if hasattr(self.generator.metrics, "labeled_total") else 0
            ),
            degraded=(
                self.generator.metrics.labeled_total("degraded")
                if hasattr(self.generator.metrics, "labeled_total") else 0
            ),
        )

    async def _fabric_prefetch(
        self,
        prompt: str,
        params: Optional[SamplingParams],
        resume_tokens: Optional[list],
    ) -> None:
        """Admission-time fabric prefetch (operator_tpu/fabric/fetch.py).

        Tokenizes exactly the way the scheduler's enqueue will (same
        truncation budget, same resume suffix) so the probed block
        hashes line up with the prefix match that follows.  The cheap
        gates run FIRST — no host pool to land pages in, or an index
        with no holders at all, must cost the request nothing (the
        tokenize is duplicate CPU work the enqueue repeats).  The
        tokenize itself and all store access run on the decode executor:
        the event loop never touches the store (the scheduler mutates it
        from that same thread), and long prompts never stall other
        connections here.  Never raises — every failure mode is a silent
        fall-through to the recompute the request was going to do
        anyway."""
        from .types import prompt_budget

        store = getattr(self._sched, "_kvstore", None)
        if store is None:
            return
        pool = getattr(store, "host_pool", None)
        if pool is None or getattr(pool, "capacity_bytes", 0) <= 0:
            return  # nowhere to land a fetched page
        try:
            if self.fabric.index.empty():
                return  # no holders anywhere: nothing to fetch
            g = self.generator
            p = params or SamplingParams()

            def tokenize() -> Optional[list]:
                ids = g.tokenizer.encode(prompt)
                budget = prompt_budget(g.max_seq, p.max_tokens)
                if resume_tokens:
                    if len(resume_tokens) >= budget:
                        return None  # enqueue will reject it
                    return g._truncate_prompt(
                        ids, budget - len(resume_tokens)
                    ) + list(resume_tokens)
                return g._truncate_prompt(ids, budget)

            tokens = await asyncio.get_running_loop().run_in_executor(
                self._executor, tokenize
            )
            if tokens is None:
                return
            residual = None
            if p.deadline is not None:
                residual = p.deadline - g._clock()
            await self.fabric.prefetch(
                tokens, store=store, budget_s=residual,
                executor=self._executor,
            )
        except asyncio.CancelledError:
            raise
        except Exception:
            log.debug("fabric prefetch failed; recompute covers it",
                      exc_info=True)

    def kv_block_bytes(self, hash_hex: str) -> Optional[bytes]:
        """Serve one KV block out of the host pool for a fabric peer
        (``GET /kv/blocks/{hash}`` — serving/httpserver.py).  Host numpy
        in, wire bytes out: no device touch, no scheduler involvement.
        Returns None when the block is not pooled here (the peer treats
        that 404 as index-eviction feedback)."""
        from ..fabric.wire import encode_block

        metrics = self.generator.metrics
        store = getattr(self._sched, "_kvstore", None)
        pool = getattr(store, "host_pool", None)
        try:
            block_hash = bytes.fromhex(hash_hex)
        except ValueError:
            return None
        entry = pool.get(block_hash) if pool is not None else None
        if entry is None:
            metrics.incr("fabric_serve_miss", exemplar=hash_hex)
            return None
        metrics.incr("fabric_serve_hit", exemplar=hash_hex)
        return encode_block(block_hash, entry[0], entry[1])

    async def start(self) -> None:
        if self._task is None:
            self._loop = asyncio.get_running_loop()
            self._task = asyncio.create_task(self._run(), name="serving-engine")
        if self._supervisor is not None and self._supervise_task is None:
            self._supervise_task = asyncio.create_task(
                self._supervise(), name="serving-supervisor"
            )
        if self.fabric_poller is not None and self._fabric_poll_task is None:
            self._fabric_poll_task = asyncio.create_task(
                self.fabric_poller.run(), name="fabric-peer-poll"
            )

    async def close(self) -> None:
        self._closed = True
        # the peer poller is pure index plumbing — first down, nothing
        # depends on it
        poll_task, self._fabric_poll_task = self._fabric_poll_task, None
        if poll_task is not None:
            poll_task.cancel()
            try:
                await poll_task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001 - already torn down
                pass
        # wake an idle watchdog so it observes _closed and exits.  A
        # watchdog MID-RESTART is awaited (bounded) rather than cancelled:
        # cancelling between survivor collection and the device-state
        # reset would leave slots/pages allocated forever and the
        # already-submitted reset racing this shutdown on the executor
        self._supervise_wakeup.set()
        supervise, self._supervise_task = self._supervise_task, None
        if supervise is not None:
            grace = 5.0 + (
                self._supervisor.join_grace_s
                if self._supervisor is not None else 0.0
            )
            try:
                await asyncio.wait_for(supervise, timeout=grace)
            except (asyncio.TimeoutError, asyncio.CancelledError):
                pass  # wedged restart: wait_for already cancelled it
        # AFTER the watchdog settles — a restart in flight during the
        # wait above re-creates self._task via start()
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        self._task = None
        self._fail_outstanding(asyncio.CancelledError("serving engine closed"))
        self._executor.shutdown(wait=False)

    def _fail_outstanding(self, exc: BaseException) -> None:
        """Resolve every in-flight and queued future so callers never hang."""
        self._partial_cbs.clear()
        self._partial_sent.clear()
        self._partial_by_future.clear()
        for request in self._restarting:  # supervisor interrupted mid-recovery
            if not request.future.done():
                request.future.set_exception(exc)
        self._restarting = []
        for request in self._pending.values():
            if not request.future.done():
                request.future.set_exception(exc)
        self._pending.clear()
        for request in self._inflight:  # popped but not yet admitted
            if not request.future.done():
                request.future.set_exception(exc)
        self._inflight.clear()
        while not self._queue.empty():
            request = self._unwrap(self._queue.get_nowait())
            if not request.future.done():
                request.future.set_exception(exc)

    async def precompile(self, level: str = "serving") -> dict:
        """Run the warmup compile on the decode worker thread
        (single-threaded executor: serialised with every other generator
        op).  Call before serving traffic — readiness should gate on it
        (operator/app.py warmup).  In continuous-scheduler mode there is
        no program grid: exactly ONE mixed program compiles, whatever
        the workload (docs/SERVING.md)."""
        loop = asyncio.get_running_loop()
        if self._sched is not None:
            sched = self._sched

            def _warm() -> dict:
                if level == "off":
                    return {"level": level, "programs": 0, "seconds": 0.0}
                started = time.perf_counter()
                sched.precompile()
                out = {
                    "level": level, "programs": 1,
                    "seconds": round(time.perf_counter() - started, 2),
                }
                aot = getattr(self.generator, "_aot", None)
                if aot is not None:
                    out["aot"] = aot.stats()
                return out

            return await loop.run_in_executor(self._executor, _warm)
        return await loop.run_in_executor(
            self._executor, lambda: self.generator.precompile_grid(level)
        )

    async def add_prefix(self, text: str) -> int:
        """Register a shared prompt prefix (generator.add_shared_prefix)
        on the decode worker: safe while serving — registration only
        allocates pages and updates the cache functionally.  Programs for
        the new prefix's buckets compile in-band on their first waves
        (restart to fold them into the warmup grid)."""
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._executor, lambda: self.generator.add_shared_prefix(text)
        )

    async def ensure_guided(self, spec: tuple) -> None:
        """Build (and cache) the automaton for a guided spec; raises
        ValueError on bad specs or unsupported engine configs.

        The build (regex NFA + subset construction, seconds for a novel
        spec) runs on the loop's default executor — NOT inline (it would
        stall every HTTP connection) and NOT on the dedicated decode
        thread (it would delay decode steps queued behind it);
        ``_guided_lock`` makes the cache safe across threads.  The inline
        probe keeps cache-hit submits (the common case: validation
        already built the spec) off the shared executor, where one slow
        novel build would queue them.  Concurrent callers with the same
        novel spec piggyback on ONE in-flight build (shielded, so a
        cancelled waiter never kills the build for the others) instead of
        occupying one executor thread each.  The single entry point for
        both submit (generate) and HTTP validate paths, so build
        scheduling can never diverge between them."""
        if self.generator._automaton_cached(spec):
            return
        build = self._guided_builds.get(spec)
        if build is None:
            build = asyncio.get_running_loop().run_in_executor(
                None, self.generator._ensure_automaton, spec
            )
            self._guided_builds[spec] = build

            def _done(fut: "asyncio.Future") -> None:
                self._guided_builds.pop(spec, None)
                if not fut.cancelled():
                    fut.exception()  # retrieved even with zero waiters left

            build.add_done_callback(_done)
        await asyncio.shield(build)

    async def generate(
        self,
        prompt: str,
        params: Optional[SamplingParams] = None,
        *,
        on_partial: Optional[Any] = None,
        priority: int = 0,
        resume_tokens: Optional[list] = None,
    ) -> GenerationResult:
        """Generate; ``on_partial(token_ids_so_far)`` (if given) fires on the
        event loop after each decode block while the request is generating —
        the streaming feed for the completion API (serving/httpserver.py).

        ``priority`` orders ADMISSION only (higher first, FIFO within a
        class): the operator pipeline uses 10 so external API callers on the
        shared engine can never starve incident analysis.  Already-admitted
        and backpressured-in-hand requests are not preempted.

        ``resume_tokens`` resumes a failed-over stream mid-token: the
        already-generated ids are re-prefilled verbatim after the prompt
        (cheap under the prefix cache) and the result carries ONLY the
        continuation — the caller owns stitching checkpoint + result.
        Continuous scheduler mode only."""
        if self._closed:
            raise RuntimeError("serving engine is closed")
        if self._gave_up:
            # the reset budget is a RATE limit, not a death sentence: once
            # the window has drained, the next caller may revive the engine
            # (the unsupervised path already recovers this way via lazy
            # _try_recover).  Staying _gave_up forever with green probes
            # would brick the AI leg until a human deletes the pod.
            now = time.monotonic()
            in_window = [
                t for t in self._reset_times
                if now - t < self.RESET_WINDOW_S
            ]
            if len(in_window) < self.MAX_RESETS_PER_WINDOW and not self._closed:
                if self._error is not None:
                    await self._try_recover()
                # revalidate after the recovery await: a concurrent failure
                # may have re-armed _error/_gave_up while we suspended —
                # only revive from a state observed AFTER the await
                if self._gave_up and self._error is None:
                    self._gave_up = False
            if self._gave_up:
                raise RuntimeError(
                    "serving engine is down (supervisor reset budget exhausted)"
                ) from self._error
        if self._supervisor is None:
            # unsupervised: lazy recovery on the next caller (pre-supervisor
            # semantics).  Supervised engines restart proactively — a death
            # observed here is mid-restart, and the queue survives it.
            if self._error is not None:
                await self._try_recover()
            if self._error is not None:
                raise RuntimeError("serving engine loop died") from self._error
        # reject unknown adapters at SUBMIT time: a bad name surfacing as a
        # ValueError inside the serve loop's admit would fail the whole
        # co-batched wave and kill the loop — one misconfigured AIProvider CR
        # must never take down serving for everyone
        adapter = (params.adapter if params is not None else None)
        if adapter is not None and adapter not in getattr(
            self.generator, "_adapter_ids", {}
        ):
            raise ValueError(
                f"unknown LoRA adapter {adapter!r}; registered: "
                f"{getattr(self.generator, 'adapter_names', [])}"
            )
        if params is not None and params.guided_choice is not None \
                and params.guided_regex is not None:
            raise ValueError("guided_choice and guided_regex are mutually exclusive")
        if self._sched is not None and params is not None and (
            params.guided_choice is not None
            or params.guided_regex is not None
            or params.adapter is not None
        ):
            # the mixed-phase program has no guided/LoRA path yet: refuse
            # at SUBMIT (to this caller) rather than inside the serve loop
            raise ValueError(
                "guided decoding and LoRA adapters are not supported in "
                "continuous scheduler mode (sched_mode=continuous)"
            )
        if resume_tokens and self._sched is None:
            raise ValueError(
                "token-level streaming resume requires the continuous "
                "scheduler (sched_mode=continuous)"
            )
        if params is not None and params.deadline is not None:
            # fail-fast at submit: a budget that cannot fit ONE decoded
            # token must not consume a queue slot, a prefill, or KV pages.
            # Truncation is NOT applied here — admission re-runs the policy
            # with post-queue-wait residue and owns the clamp.
            _, outcome = self.generator.deadline_policy(params)
            if outcome == "rejected":
                self.generator.metrics.incr("admission_deadline_rejected")
                raise DeadlineExceeded(
                    "deadline budget cannot fit any decoded output "
                    f"(remaining {max(0.0, params.deadline - self.generator._clock()):.3f}s)"
                )
        guided_spec = self.generator._guided_spec(params)
        if guided_spec is not None:
            # builds+caches the automaton; raises ValueError here (to THIS
            # caller) on bad specs or unsupported engine configs
            await self.ensure_guided(guided_spec)
        if self.fabric is not None and self._sched is not None:
            # fleet KV fabric: pull the prompt's missing prefix blocks
            # from a peer's host pool BEFORE admission so the scheduler's
            # prefix match restores them instead of recomputing.  Best
            # effort, residual-budget clamped — a failed fetch degrades
            # to the ordinary recompute with at most the fetch budget
            # spent, never an error to this caller.
            await self._fabric_prefetch(prompt, params, resume_tokens)
        if self._task is None:
            await self.start()
        future: asyncio.Future = asyncio.get_running_loop().create_future()
        if on_partial is not None:
            self._partial_by_future[future] = on_partial
        # one obs span per engine request (joins the ambient analysis /
        # HTTP trace; detached no-op outside one): the queue-wait vs
        # compute split below is how a decode stall becomes attributable
        # — the result's prefill/decode times are chip-side, the rest of
        # the wall time was spent waiting for a slot/pages/the low lane
        submitted = time.perf_counter()
        # per-class SLO accounting (obs/sloledger.py SLOBoard): every
        # submit is counted, and the finally guarantees exactly one
        # settle per submit — a cancelled/errored request is a miss, so
        # /healthz attainment can never read better than reality
        slo_cls = (params.slo_class if params is not None else None) or "default"
        self._slo_board.submitted(slo_cls)
        slo_settled = False
        try:
            with obs_span("engine.generate", priority=priority) as span_:
                if priority <= 0:
                    await self._low_lane.acquire()  # released when the entry is popped
                await self._queue.put((
                    -priority, next(self._seq),
                    _Request(
                        prompt, params or SamplingParams(), future, priority,
                        submitted=submitted,
                        resume_tokens=(
                            list(resume_tokens) if resume_tokens else None
                        ),
                    ),
                ))
                # the put may have landed after close()/loop-death drained the
                # queue; _closed/_error were set before the drain, so re-checking
                # here closes that window.  A supervised engine's queue SURVIVES
                # a loop death (the supervisor requeues, new arrivals wait), so
                # only _gave_up is terminal there.
                dead = self._closed or self._gave_up or (
                    self._error is not None and self._supervisor is None
                )
                if dead and not future.done():
                    self._partial_by_future.pop(future, None)
                    future.set_exception(RuntimeError("serving engine is closed"))
                result = await future
                # span timings are COPIED from the result, whose decode/queue
                # numbers are derived from the step clock + measured admission
                # wait — the span and the step records share one source of
                # truth and cannot disagree (the old wall-minus-compute
                # inference could).  The same values feed the latency
                # histograms (docs/METRICS.md "Histograms").
                metrics = self.generator.metrics
                metrics.observe("queue_wait_milliseconds", result.queue_wait_ms)
                metrics.observe(
                    "ttft_milliseconds", result.queue_wait_ms + result.prefill_ms
                )
                if result.completion_tokens > 0:
                    metrics.observe(
                        "token_latency_milliseconds",
                        result.decode_ms / result.completion_tokens,
                    )
                # attained = finished with output inside its own deadline;
                # deadline-free requests attain by completing at all
                attained = result.finish_reason != "deadline" and (
                    params is None or params.deadline is None
                    or self.generator._clock() <= params.deadline
                )
                self._slo_board.finished(
                    slo_cls, attained=attained,
                    tokens=result.completion_tokens,
                )
                slo_settled = True
                span_.set(
                    queue_wait_ms=round(result.queue_wait_ms, 3),
                    prefill_ms=round(result.prefill_ms, 3),
                    decode_ms=round(result.decode_ms, 3),
                    prompt_tokens=result.prompt_tokens,
                    completion_tokens=result.completion_tokens,
                    finish_reason=result.finish_reason,
                )
                return result
        finally:
            if not slo_settled:
                self._slo_board.finished(slo_cls, attained=False, tokens=0)

    # ------------------------------------------------------------------
    async def _run(self) -> None:
        try:
            await self._serve()
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # generator/device failure: fail fast, loudly
            log.exception("serving engine loop died")
            self._error = exc
            if self._supervisor is not None and not self._closed:
                # keep the in-flight requests: the supervisor resets the
                # engine and requeues them (once) instead of failing them
                self._supervise_wakeup.set()
            else:
                self._fail_outstanding(exc)

    def _sweep_batch(self, batch: "list[_Request]") -> None:
        """Drop requests whose callers vanished while QUEUED — no point
        tokenizing, granting pages, and prefilling a dead request ahead
        of live ones.  Deadline-carrying entries that EXPIRED while
        queued are failed here for the same reason: their budget is gone
        before any chip time was spent.  In-place (batch aliases
        ``_inflight``)."""
        now = self.generator._clock()
        live = []
        for request in batch:
            future = request.future
            if future.done():
                self._partial_by_future.pop(future, None)
                continue
            deadline = request.params.deadline
            if deadline is not None and deadline <= now:
                self._partial_by_future.pop(future, None)
                self.generator.metrics.incr("admission_deadline_rejected")
                future.set_exception(DeadlineExceeded(
                    "deadline expired while queued for admission"
                ))
                continue
            live.append(request)
        batch[:] = live

    async def _serve_sched(self) -> None:
        """The continuous-batching serve loop (serving/sched/): every
        popped request is handed to the scheduler immediately — admission
        is token-level inside :meth:`Scheduler.step`, so there is no
        admission window, no wave formation, and no backpressure retry
        machinery here; ``_pending`` is keyed by scheduler req id."""
        loop = asyncio.get_running_loop()
        sched = self._sched
        assert sched is not None
        # the scheduler's host queue is unbounded: cap the handoff so
        # overflow stays in THIS bounded priority queue (max_queue via
        # the low lane keeps gating external callers, and a late
        # high-priority arrival can still jump the un-drained tail)
        handoff = max(2 * self.generator.max_slots, 16)
        while not self._closed:
            batch = self._inflight
            if not batch and sched.total_work == 0 and self._queue.empty():
                # fully idle: block until a request arrives
                batch.append(self._unwrap(await self._queue.get()))
            while (
                not self._queue.empty()
                and sched.queue_depth + len(batch) < handoff
            ):
                batch.append(self._unwrap(self._queue.get_nowait()))
            if batch:
                self._sweep_batch(batch)
            if batch:
                requests = list(batch)

                def _enqueue_all(requests=requests):
                    out = []
                    for request in requests:
                        try:
                            out.append((request, sched.enqueue(
                                request.prompt, request.params,
                                submitted=request.submitted or None,
                                priority=request.priority,
                                resume_tokens=request.resume_tokens,
                            ), None))
                        except Exception as exc:  # noqa: BLE001 - per-request verdict
                            out.append((request, None, exc))
                    return out
                enqueued = await loop.run_in_executor(
                    self._executor, _enqueue_all
                )
                batch.clear()
                for request, req_id, exc in enqueued:
                    if exc is not None:
                        self._partial_by_future.pop(request.future, None)
                        if not request.future.done():
                            request.future.set_exception(exc)
                        continue
                    self._pending[req_id] = request
                    callback = self._partial_by_future.pop(
                        request.future, None
                    )
                    if callback is not None:
                        self._partial_cbs[req_id] = (callback, request.future)
                        self._partial_sent.pop(req_id, None)
            if sched.total_work:
                # reclaim rows whose callers are gone (disconnects):
                # per-token recycling frees their slot + pages THIS step
                cancelled = [
                    (req_id, request)
                    for req_id, request in self._pending.items()
                    if request.future.cancelled()
                ]
                if cancelled:
                    await loop.run_in_executor(
                        self._executor,
                        lambda: [sched.cancel(r) for r, _ in cancelled],
                    )
                    for req_id, request in cancelled:
                        # identity revalidation after the executor await:
                        # only reap the entry we observed — the id may have
                        # been reaped elsewhere while the cancel ran
                        if self._pending.get(req_id) is not request:
                            continue
                        self._pending.pop(req_id, None)
                        self._partial_cbs.pop(req_id, None)
                        self._partial_sent.pop(req_id, None)
            if sched.total_work:
                step_call = loop.run_in_executor(self._executor, sched.step)
                if self._supervisor is not None:
                    # same stall watchdog as the wave loop: one mixed
                    # dispatch making no progress within the budget means
                    # the device is wedged, not merely slow
                    try:
                        outcomes = await asyncio.wait_for(
                            step_call, self._supervisor.stall_timeout_s
                        )
                    except asyncio.TimeoutError:
                        self._stalled = True
                        raise EngineStalled(
                            f"mixed dispatch made no progress in "
                            f"{self._supervisor.stall_timeout_s:.1f}s"
                        ) from None
                else:
                    outcomes = await step_call
                for outcome in outcomes:
                    self._partial_cbs.pop(outcome.req_id, None)
                    self._partial_sent.pop(outcome.req_id, None)
                    request = self._pending.pop(outcome.req_id, None)
                    if request is None or request.future.done():
                        continue
                    if outcome.error is not None:
                        request.future.set_exception(outcome.error)
                    else:
                        request.future.set_result(outcome.result)
            await asyncio.sleep(0)

    async def _serve(self) -> None:
        if self._sched is not None:
            return await self._serve_sched()
        loop = asyncio.get_running_loop()
        while not self._closed:
            # requests live in self._inflight between queue pop and slot
            # admission so cancellation/crash cleanup can always see them
            batch = self._inflight
            leftover = bool(batch)  # backpressured from an earlier round
            if not batch and self.generator.num_active == 0 and self._queue.empty():
                # fully idle: block until a request arrives (never while
                # backpressured requests are already waiting in hand)
                batch.append(self._unwrap(await self._queue.get()))
            total_free = len(self.generator.free_slots())
            stalled = self._page_stalled(batch)
            if (
                len(batch) < total_free
                and not stalled
                and (not self._queue.empty() or (batch and not leftover))
            ):
                # tiny window lets concurrent arrivals share one prefill
                # (32 events -> one prefill, BASELINE config 4).  Skipped
                # when the batch is page-stalled leftovers with no fresh
                # arrivals: sleeping then would throttle decode for every
                # active sequence exactly when the engine is most loaded
                await asyncio.sleep(self.admission_wait_s)
                while len(batch) < total_free and not self._queue.empty():
                    batch.append(self._unwrap(self._queue.get_nowait()))
            if batch:
                self._sweep_batch(batch)
            if batch and not stalled:
                admitted = await self._admit(batch)
                # paged backpressure: requests beyond the KV free list stay
                # in _inflight and retry as decode frees pages
                # graftlint: disable=GL011 reason=_serve is the engine's sole consumer task; _inflight is its working set and the cleanup paths (close/crash) only run after this loop has exited
                self._inflight = batch[admitted:]
                allocator = getattr(self.generator, "allocator", None)
                # record a stall only while active sequences hold pages —
                # their release is the retry trigger; with nothing active
                # (e.g. after an oversized head was failed) retry freely
                self._stalled_avail = (
                    allocator.available
                    if (self._inflight and allocator is not None
                        and self.generator.num_active > 0)
                    else None
                )

            if self.generator.num_active:
                # reclaim slots whose callers are gone (disconnects /
                # timeouts): an abandoned request must not decode to
                # max_tokens holding a slot and its KV pages
                cancelled = [
                    (slot_id, request)
                    for slot_id, request in self._pending.items()
                    if request.future.cancelled()
                ]
                if cancelled:
                    freed = await loop.run_in_executor(
                        self._executor,
                        lambda: [self.generator.cancel(s) for s, _ in cancelled],
                    )
                    for (slot_id, request), reclaimed in zip(cancelled, freed):
                        # a chunk-prefilling (reserved) slot can't be
                        # cancelled mid-job: KEEP its future so the sweep
                        # catches it once the wave activates.  Identity
                        # revalidation after the executor await: slots are
                        # reused, so only reap the entry we observed — a
                        # freed slot re-admitted while cancel ran must not
                        # lose its fresh future
                        if reclaimed and self._pending.get(slot_id) is request:
                            self._pending.pop(slot_id, None)
                            self._partial_cbs.pop(slot_id, None)
                            self._partial_sent.pop(slot_id, None)
            if self.generator.num_active:
                step_call = loop.run_in_executor(
                    self._executor, self.generator.step
                )
                if self._supervisor is not None:
                    # stall watchdog: a step that outlives the budget means
                    # the device (not the host) is wedged.  The worker
                    # thread cannot be interrupted — it is ABANDONED (the
                    # supervisor swaps executors) and the loop dies into
                    # the supervised-restart path.
                    try:
                        finished = await asyncio.wait_for(
                            step_call, self._supervisor.stall_timeout_s
                        )
                    except asyncio.TimeoutError:
                        self._stalled = True
                        raise EngineStalled(
                            f"decode step made no progress in "
                            f"{self._supervisor.stall_timeout_s:.1f}s"
                        ) from None
                else:
                    finished = await step_call
                for slot_id, result in finished:
                    self._partial_cbs.pop(slot_id, None)
                    self._partial_sent.pop(slot_id, None)
                    request = self._pending.pop(slot_id, None)
                    if request is not None and not request.future.done():
                        result.queue_wait_ms = request.queue_wait_ms
                        request.future.set_result(result)
            await asyncio.sleep(0)

    async def _admit(self, batch: "list[_Request]") -> int:
        """Admit as much of ``batch`` as fits; returns the admitted count."""
        prompts = [request.prompt for request in batch]
        params = [request.params for request in batch]
        # queue wait ends when admission (prefill included) begins
        admitted_t = time.perf_counter()
        try:
            admit_call = asyncio.get_running_loop().run_in_executor(
                self._executor, lambda: self.generator.admit(prompts, params)
            )
            if self._supervisor is not None:
                # the batched prefill is device work too — a wedge here is
                # the same fault class the step watchdog guards, and the
                # largest single dispatch; without a bound it would hang
                # the serve loop (and every caller) forever
                try:
                    slot_ids = await asyncio.wait_for(
                        admit_call, self._supervisor.stall_timeout_s
                    )
                except asyncio.TimeoutError:
                    self._stalled = True
                    raise EngineStalled(
                        f"batched prefill made no progress in "
                        f"{self._supervisor.stall_timeout_s:.1f}s"
                    ) from None
            else:
                slot_ids = await admit_call
        except OversizedRequest as exc:
            # only the head request is impossible; fail it alone and let
            # the rest retry next round
            future = batch[0].future
            self._partial_by_future.pop(future, None)
            if not future.done():
                future.set_exception(exc)
            return 1
        except BaseException as exc:
            if self._supervisor is not None and not isinstance(
                exc, asyncio.CancelledError
            ):
                # leave the batch in _inflight: the loop death this raise
                # becomes is supervised, and the restart requeues them
                raise
            # the batch futures are out of the queue but not yet in
            # _pending — fail them here or their callers hang forever
            for request in batch:
                self._partial_by_future.pop(request.future, None)
                if not request.future.done():
                    request.future.set_exception(exc)
            raise
        for slot_id, request in zip(slot_ids, batch):
            if request.submitted:
                request.queue_wait_ms = max(
                    0.0, (admitted_t - request.submitted) * 1e3
                )
            self._pending[slot_id] = request
            callback = self._partial_by_future.pop(request.future, None)
            if callback is not None:
                # future travels with the callback so the worker-side hook
                # can drop deltas once the streaming client is gone
                self._partial_cbs[slot_id] = (callback, request.future)
                self._partial_sent.pop(slot_id, None)
        return len(slot_ids)
