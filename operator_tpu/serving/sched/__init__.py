"""Continuous-batching scheduler: ragged mixed prefill+decode waves.

The explicit **schedule → dispatch → commit** serving loop (PAPERS.md:
*xLLM*, arxiv 2510.14686) over the ragged mixed-phase program
(``ops/ragged_attention.py``; PAPERS.md: *Ragged Paged Attention*,
arxiv 2604.15464).  See :mod:`.scheduler` for the loop and
``docs/SERVING.md`` for the design.
"""

from .scheduler import Scheduler
from .types import SchedConfig, StepPlan

__all__ = ["Scheduler", "SchedConfig", "StepPlan"]
