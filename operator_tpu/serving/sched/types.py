"""Scheduler data types: row state and the per-step ragged wave plan.

Split from :mod:`.scheduler` so tests (and the determinism assertion:
a fixed arrival trace must produce a byte-identical plan sequence) can
inspect plans without importing the dispatch machinery.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from ..types import SamplingParams


@dataclass(frozen=True)
class SchedConfig:
    """Continuous-scheduler knobs (OperatorConfig ``sched_*``).

    ``chunk`` bounds the prefill tokens ONE row may contribute to a step
    (Sarathi-style chunking: a storm of long prompts can stall in-flight
    decodes for at most one chunk's compute per step).  ``token_budget``
    is the flat token axis of the mixed program — decode rows take one
    token each off the top, prefill chunks fill the remainder; it must
    be >= ``max_slots`` so a full decode batch can never be starved
    (enforced at construction)."""

    chunk: int = 64
    token_budget: int = 0  # 0 = auto: max(chunk, max_slots)
    #: bounded in-flight dispatch queue (decode-ahead pipelining): step
    #: N+1 is planned from predicted row state and dispatched while step
    #: N's sampled tokens are still on device; 1 = synchronous commit
    pipeline_depth: int = 1
    #: prompt-lookup self-speculation (sched/draft.py): greedy rows
    #: verify up to ``spec_lookup_k`` draft tokens per step as one
    #: q_count=k+1 row; 0 / spec_decode off = plain one-token decode
    spec_decode: bool = False
    spec_lookup_k: int = 4


@dataclass
class _Row:
    """One live row of the running wave: a request at an arbitrary
    prefill-chunk or decode position."""

    req_id: int
    slot: int
    tokens: list[int]  # full (truncated) prompt token ids
    params: SamplingParams
    pages: list[int]
    pos: int = 0  # prompt tokens already written to the KV pages
    generated: list[int] = field(default_factory=list)
    submitted: float = 0.0  # perf_counter at admission
    started: float = 0.0  # perf_counter when the prompt completed
    prefill_ms: float = 0.0  # accumulated chunk compute share
    chunked: bool = False  # took more than one step of prefill
    queue_wait_ms: float = 0.0  # measured submit -> admission wall
    #: step-clock decode cumulative (StepRing.decode_cum_ms) when the
    #: prompt completed — _finish derives decode_ms as the delta, so the
    #: span timing and the step records share one source of truth
    decode_cum0: float = 0.0
    # --- decode-ahead pipelining: uncommitted in-flight deltas.  The
    # authoritative fields above advance only at commit; planning reads
    # the PREDICTED state (authoritative + pending) so step N+1 can be
    # dispatched while step N's tokens are still on device. ---
    #: prompt tokens dispatched but not yet committed (prefill chunks)
    pend_pos: int = 0
    #: tokens sampled on device but not yet committed (chained decodes
    #: + a finishing chunk's first sample); their ids never left the
    #: device — the next dispatch chains them via ``from_prev``
    pend_gen: int = 0
    #: a speculation verify round is in flight: the row must not be
    #: re-planned until its commit lands (the accepted count — and so
    #: the row's true length — is unknowable on the host until then)
    pend_spec: bool = False
    # --- prefix cache (serving/kvstore.py) ---
    #: prompt tokens served from cached blocks at admission: the row's
    #: first ``cached_len // page_size`` table entries are STORE-OWNED
    #: read-only pages (never in ``pages``, never written — suffix
    #: prefill starts at ``pos = cached_len`` in a row-owned page)
    cached_len: int = 0
    #: block hashes this row holds references on (acquired at admission
    #: + blocks it donated at prefill completion); released on finish
    cached_hashes: list[bytes] = field(default_factory=list)

    @property
    def prompt_len(self) -> int:
        return len(self.tokens)

    @property
    def decoding(self) -> bool:
        return self.pos >= self.prompt_len

    @property
    def kv_len(self) -> int:
        """Tokens currently valid in this row's pages."""
        if not self.decoding:
            return self.pos
        # the freshest sampled token has not been written yet; every
        # earlier one has (prompt + generated[:-1])
        return self.prompt_len + max(0, len(self.generated) - 1)

    # -- predicted state (authoritative + in-flight deltas) ------------

    @property
    def pred_pos(self) -> int:
        return self.pos + self.pend_pos

    @property
    def pred_decoding(self) -> bool:
        return self.pred_pos >= self.prompt_len

    @property
    def pred_gen(self) -> int:
        return len(self.generated) + self.pend_gen

    @property
    def pred_kv(self) -> int:
        """Pages' valid length once every in-flight dispatch lands."""
        if not self.pred_decoding:
            return self.pred_pos
        return self.prompt_len + max(0, self.pred_gen - 1)


@dataclass
class RowWork:
    """One row's share of a step: ``count`` tokens starting at flat
    offset ``start``.  ``kind`` distinguishes a speculation verify row
    ("verify") from plain work; otherwise it is forensics only — the
    program does not distinguish phases.  Positions are FROZEN at plan
    time (``pos0``): under pipelining the row's authoritative state may
    advance between this plan's dispatch and its commit, so the work
    item must carry everything dispatch packs."""

    slot: int
    req_id: int
    start: int  # flat offset of the row's first token this step
    count: int
    kind: str  # "prefill" | "finish" | "decode" | "verify"
    #: absolute position of the row's first token this step (prefill:
    #: the predicted prompt offset; decode/verify: the predicted kv len)
    pos0: int = 0
    #: draft tokens riding a verify row (count == 1 + spec_len)
    spec_len: int = 0
    drafts: tuple = ()
    #: the row's input token is the previous dispatch's on-device sample
    #: (chained decode) — the packed id is a placeholder the program
    #: replaces with its carried ``latest`` buffer
    from_prev: bool = False


@dataclass
class StepPlan:
    """The ragged wave one dispatch serves; ``trace()`` is the stable
    serialisation the determinism test replays."""

    work: list[RowWork] = field(default_factory=list)
    tokens_planned: int = 0
    decode_rows: int = 0
    prefill_rows: int = 0
    deferred_decode: int = 0  # decode-ready rows left out (stall signal)
    admitted: list[int] = field(default_factory=list)  # req ids admitted NOW
    #: prompt tokens rows admitted THIS step reused from the prefix
    #: cache (spared prefill compute; rides into StepRecord.cached_tokens)
    cached_tokens: int = 0

    def trace(self) -> tuple:
        return tuple(
            (w.slot, w.req_id, w.start, w.count, w.kind, w.pos0,
             w.spec_len, w.drafts, w.from_prev)
            for w in self.work
        )


@dataclass
class StepOutcome:
    """One finished request: the result (or the admission-time error)
    the engine resolves its future with."""

    req_id: int
    result: Optional[Any] = None  # GenerationResult
    error: Optional[BaseException] = None
