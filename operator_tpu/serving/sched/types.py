"""Scheduler data types: row state and the per-step ragged wave plan.

Split from :mod:`.scheduler` so tests (and the determinism assertion:
a fixed arrival trace must produce a byte-identical plan sequence) can
inspect plans without importing the dispatch machinery.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from ..types import SamplingParams


@dataclass(frozen=True)
class SchedConfig:
    """Continuous-scheduler knobs (OperatorConfig ``sched_*``).

    ``chunk`` bounds the prefill tokens ONE row may contribute to a step
    (Sarathi-style chunking: a storm of long prompts can stall in-flight
    decodes for at most one chunk's compute per step).  ``token_budget``
    is the flat token axis of the mixed program — decode rows take one
    token each off the top, prefill chunks fill the remainder; it must
    be >= ``max_slots`` so a full decode batch can never be starved
    (enforced at construction)."""

    chunk: int = 64
    token_budget: int = 0  # 0 = auto: max(chunk, max_slots)


@dataclass
class _Row:
    """One live row of the running wave: a request at an arbitrary
    prefill-chunk or decode position."""

    req_id: int
    slot: int
    tokens: list[int]  # full (truncated) prompt token ids
    params: SamplingParams
    pages: list[int]
    pos: int = 0  # prompt tokens already written to the KV pages
    generated: list[int] = field(default_factory=list)
    submitted: float = 0.0  # perf_counter at admission
    started: float = 0.0  # perf_counter when the prompt completed
    prefill_ms: float = 0.0  # accumulated chunk compute share
    chunked: bool = False  # took more than one step of prefill
    queue_wait_ms: float = 0.0  # measured submit -> admission wall
    #: step-clock decode cumulative (StepRing.decode_cum_ms) when the
    #: prompt completed — _finish derives decode_ms as the delta, so the
    #: span timing and the step records share one source of truth
    decode_cum0: float = 0.0

    @property
    def prompt_len(self) -> int:
        return len(self.tokens)

    @property
    def decoding(self) -> bool:
        return self.pos >= self.prompt_len

    @property
    def kv_len(self) -> int:
        """Tokens currently valid in this row's pages."""
        if not self.decoding:
            return self.pos
        # the freshest sampled token has not been written yet; every
        # earlier one has (prompt + generated[:-1])
        return self.prompt_len + max(0, len(self.generated) - 1)


@dataclass
class RowWork:
    """One row's share of a step: ``count`` tokens starting at flat
    offset ``start`` (``kind`` is forensics only — the program does not
    distinguish phases)."""

    slot: int
    req_id: int
    start: int  # flat offset of the row's first token this step
    count: int
    kind: str  # "prefill" | "finish" | "decode"


@dataclass
class StepPlan:
    """The ragged wave one dispatch serves; ``trace()`` is the stable
    serialisation the determinism test replays."""

    work: list[RowWork] = field(default_factory=list)
    tokens_planned: int = 0
    decode_rows: int = 0
    prefill_rows: int = 0
    deferred_decode: int = 0  # decode-ready rows left out (stall signal)
    admitted: list[int] = field(default_factory=list)  # req ids admitted NOW

    def trace(self) -> tuple:
        return tuple(
            (w.slot, w.req_id, w.start, w.count, w.kind) for w in self.work
        )


@dataclass
class StepOutcome:
    """One finished request: the result (or the admission-time error)
    the engine resolves its future with."""

    req_id: int
    result: Optional[Any] = None  # GenerationResult
    error: Optional[BaseException] = None
