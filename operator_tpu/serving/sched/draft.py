"""Prompt-lookup draft model: n-gram self-speculation from the request's
own context.

Incident-analysis prompts are highly templated — the same log lines,
field names and remediation phrasing recur inside one request — so the
cheapest possible draft model works unusually well here: match the tail
n-gram of (prompt + generated so far) against an earlier occurrence in
the same context and propose the tokens that followed it (the
prompt-lookup decoding trick; xLLM runs the same idea inside its async
scheduler).  There is no second model, no extra device program and no
training: the draft is host-side list matching, and the mixed ragged
program verifies the proposal as one ``q_count = k + 1`` row
(sched/mixed.py).  Greedy output is byte-identical by construction —
the commit accepts exactly the prefix the target model would have
produced one token at a time (sched/scheduler.py ``_commit``).

Deterministic by construction: same context, same proposal — the
acceptance-rate determinism test rides on this.
"""

from __future__ import annotations

__all__ = ["PromptLookupDraft"]


class PromptLookupDraft:
    """Stateless n-gram lookup over a request's own token context.

    ``propose`` scans for the most recent earlier occurrence of the
    context's tail n-gram (longest ``ngram`` first, down to 1) and
    returns up to ``k`` continuation tokens.  An empty return means "no
    draft": the scheduler falls back to a plain one-token decode row for
    that step, so a miss costs nothing but this scan (measured and
    reported as ``draft_overhead_ms`` by bench.py).
    """

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1) -> None:
        self.max_ngram = max(1, int(max_ngram))
        self.min_ngram = max(1, min(int(min_ngram), self.max_ngram))

    def propose(self, context: list, k: int) -> list:
        """Up to ``k`` draft tokens continuing ``context``, or ``[]``."""
        if k <= 0 or len(context) < self.min_ngram + 1:
            return []
        for n in range(self.max_ngram, self.min_ngram - 1, -1):
            if len(context) <= n:
                continue
            tail = context[-n:]
            # rightmost earlier occurrence wins: recent context is the
            # best predictor of what a templated generation does next
            for i in range(len(context) - n - 1, -1, -1):
                if context[i : i + n] == tail:
                    # i + n <= len(context) - 1, so at least one
                    # continuation token always exists here
                    return list(context[i + n : i + n + k])
        return []
