"""The mixed-phase dispatch program: ONE compiled step for the whole
ragged wave.

The scheduler packs every row's work for a step — one token per decode
row, up to ``chunk`` prompt tokens per prefill row — into a FLAT token
axis of static length ``t_budget`` (right-padded with trash tokens), so
the transformer trunk (projections, MLP, norms: all per-token) runs at
exactly the wave's token count regardless of how it splits between
phases.  Attention is the only op that needs row structure: the flat
q tokens are re-packed per row into ``[B, chunk]`` and handed to the
ragged paged-attention kernel (``ops/ragged_attention.py``), whose
causal mask makes a decode row the ``q_count == 1`` special case of a
prefill chunk.  KV for the step is scattered into the paged cache
BEFORE attention, so the kernel is a pure page read.

Exactly ONE program compiles per engine (static ``t_budget`` / ``chunk``
/ ``max_slots``): there is no bucket grid to warm, no per-shape compile
to hit mid-run — the property the warmup-grid machinery exists to
approximate for the wave engine, the mixed program has by construction.

Unsupported here (the wave engine keeps them): guided decoding and LoRA
adapters are refused at submit (serving/engine.py + Scheduler.enqueue);
mesh sharding makes build_serving_engine fall back to wave mode; and
shared-prefix KV reuse simply does not apply — every prompt prefills in
full, so provider.py skips prefix priming in continuous mode rather
than holding pages the program would never read.
"""

from __future__ import annotations

from typing import Any

from ...models.llama import (
    _PROJ_BIAS,
    apply_rope,
    rms_norm,
    rope_frequencies,
)
from ...models.quant import mm

__all__ = ["make_mixed_fn"]


def make_mixed_fn(generator: Any, t_budget: int, chunk: int,
                  spec_width: int = 1):
    """Compile the mixed-step program for ``generator`` (paged, no mesh).

    Signature of the returned jitted function::

        fn(params, paged, ids, rows, pos, valid, in_row,
           q_start, q_count, kv_len, latest, from_prev,
           sample_start, spec_len, rng, temp, top_p)
        -> (new_paged, toks [B, W], accept [B], latest_out [B], rng)

    Flat inputs (length ``t_budget``): ``ids`` token ids, ``rows`` the
    owning slot per token, ``pos`` absolute positions, ``valid`` live
    mask (padding tokens write to the trash page), ``in_row`` each
    token's index within its row's chunk, ``from_prev`` tokens whose id
    is the PREVIOUS dispatch's on-device sample for that slot (decode-
    ahead chaining: the host dispatched this step before the last step's
    token ever crossed to it, so the program substitutes its own carried
    ``latest`` buffer).  Per-slot inputs (length ``max_slots``):
    ``q_start`` the flat offset of the slot's first token, ``q_count``
    its token count this step (0 = not scheduled), ``kv_len`` the pages'
    valid length AFTER this step's writes assuming every draft is
    accepted (rows not scheduled keep their current length),
    ``sample_start`` the flat offset of the slot's first SAMPLED
    position, ``spec_len`` the slot's draft-token count this step
    (0 = plain row).

    ``W = spec_width`` positions are sampled per slot, starting at
    ``sample_start``: a plain row samples only its last valid logit
    (``toks[b, 0]``); a speculation verify row of ``q_count = 1 + k``
    tokens (committed last token + k prompt-lookup drafts) samples ALL
    ``k + 1`` of them, and ``accept[b]`` is the length of the longest
    draft prefix the samples confirm — standard speculative-decoding
    acceptance, so the commit takes ``accept[b] + 1`` tokens
    (``toks[b, :accept[b] + 1]``) and greedy output is byte-identical to
    one-token decoding by construction.  The returned cache's lengths
    are corrected on device to ``kv_len - (spec_len - accept)``: the
    rejected drafts' KV writes land but are never readable.
    ``latest_out[b]`` carries each slot's freshest sampled token for the
    next dispatch's chaining (passthrough when the slot sat this step
    out).
    """
    jax, jnp = generator._jax, generator._jnp
    config = generator.config
    b_slots = generator.max_slots
    inv_freq = rope_frequencies(config)
    lax = jax.lax
    width = max(1, int(spec_width))

    def mixed_fn(params, paged, ids, rows, pos, valid, in_row,
                 q_start, q_count, kv_len, latest, from_prev,
                 sample_start, spec_len, rng, temp, top_p):
        from ...ops.paged_attention import PagedKVCache
        from ...ops.ragged_attention import ragged_paged_attention

        page_size = paged.page_size
        # decode-ahead chaining: a token flagged from_prev takes its id
        # from the carried per-slot latest-sample buffer instead of the
        # host-packed placeholder — the sampled id never visits the host
        eff_ids = jnp.where(from_prev, latest[rows], ids)
        x = jnp.take(params["embed"], eff_ids, axis=0)[None]  # [1, T, H]
        positions = pos[None]  # [1, T]
        # flat -> per-row packing indices for the attention re-pack
        pack_idx = jnp.clip(
            q_start[:, None] + jnp.arange(chunk, dtype=jnp.int32)[None, :],
            0, t_budget - 1,
        )  # [B, chunk]
        # per-token page/slot targets (invalid tokens -> trash page 0)
        page_ids = jnp.where(
            valid, paged.page_table[rows, pos // page_size], 0
        )
        page_slots = jnp.where(valid, pos % page_size, 0)

        def layer_step(carry, scanned):
            x = carry
            weights = scanned["w"]
            attn_in = rms_norm(x, weights["ln_attn"], config.rms_norm_eps)

            def proj(h_in, name):
                y = mm(h_in, weights[name])
                bias = _PROJ_BIAS.get(name)
                if bias is not None and bias in weights:
                    y = y + weights[bias].astype(y.dtype)
                return y

            q = proj(attn_in, "wq").reshape(
                1, t_budget, config.num_heads, config.head_dim
            )
            k = proj(attn_in, "wk").reshape(
                1, t_budget, config.num_kv_heads, config.head_dim
            )
            v = proj(attn_in, "wv").reshape(
                1, t_budget, config.num_kv_heads, config.head_dim
            )
            q = apply_rope(q, positions, inv_freq)
            k = apply_rope(k, positions, inv_freq)
            # scatter this step's K/V into the pages FIRST — the ragged
            # kernel then reads a cache that already holds every token a
            # causal query may attend to (its own included)
            k_pages = scanned["k"].at[page_ids, page_slots].set(
                k[0].astype(scanned["k"].dtype)
            )
            v_pages = scanned["v"].at[page_ids, page_slots].set(
                v[0].astype(scanned["v"].dtype)
            )
            q_pack = q[0][pack_idx]  # [B, chunk, QH, D]
            attn_pack = ragged_paged_attention(
                q_pack.astype(k_pages.dtype), k_pages, v_pages,
                paged.page_table, kv_len, q_count,
                sliding_window=config.sliding_window,
            )
            attn = attn_pack[rows, in_row]  # back to flat [T, QH, D]
            x = x + proj(attn.astype(x.dtype).reshape(1, t_budget, -1), "wo")
            mlp_in = rms_norm(x, weights["ln_mlp"], config.rms_norm_eps)
            gate = jax.nn.silu(proj(mlp_in, "w_gate"))
            up = proj(mlp_in, "w_up")
            x = x + proj(gate * up, "w_down")
            return x, {"k": k_pages, "v": v_pages}

        scanned_in = {
            "w": params["layers"], "k": paged.k_pages, "v": paged.v_pages,
        }
        x, pages_out = lax.scan(layer_step, x, scanned_in)

        x = rms_norm(x, params["ln_final"], config.rms_norm_eps)
        # only each slot's sampled positions need logit rows: gather them
        # before the head matmul so the [vocab] projection runs at
        # [B * W], not [T].  A plain row samples one position (its last
        # valid token); a verify row samples its committed token AND
        # every draft, in chunk order
        samp_idx = jnp.clip(
            sample_start[:, None] + jnp.arange(width, dtype=jnp.int32)[None],
            0, t_budget - 1,
        )  # [B, W]
        x_samp = x[0][samp_idx]  # [B, W, H]
        head = (
            params["embed"].T if config.tie_embeddings else params["lm_head"]
        )
        logits = jnp.einsum(
            "bwh,hv->bwv", x_samp, head, preferred_element_type=jnp.float32
        )
        flat_toks, rng = generator._sample(
            logits.reshape(b_slots * width, -1), rng,
            jnp.repeat(temp, width), jnp.repeat(top_p, width),
        )
        toks = flat_toks.reshape(b_slots, width)
        if width > 1:
            # longest matching draft prefix: draft j (flat position
            # sample_start + 1 + j) is confirmed iff the sample AT the
            # position BEFORE it predicted exactly it, and every earlier
            # draft was confirmed (cumprod)
            draft_idx = jnp.clip(
                sample_start[:, None] + 1
                + jnp.arange(width - 1, dtype=jnp.int32)[None],
                0, t_budget - 1,
            )  # [B, W-1]
            drafts = eff_ids[draft_idx]
            confirmed = (toks[:, : width - 1] == drafts) & (
                jnp.arange(width - 1, dtype=jnp.int32)[None]
                < spec_len[:, None]
            )
            accept = jnp.sum(
                jnp.cumprod(confirmed.astype(jnp.int32), axis=1), axis=1
            )
        else:
            accept = jnp.zeros((b_slots,), jnp.int32)
        # rejected drafts wrote KV the row must never read again: shrink
        # the committed lengths on device (spec_len - accept positions)
        new_lengths = kv_len - (spec_len - accept)
        # per-slot freshest sample for the next dispatch's chaining:
        # toks[b, accept[b]] is the last ACCEPTED token (== toks[b, 0]
        # for plain rows); slots that sat out keep their carried value
        fresh = jnp.take_along_axis(
            toks, jnp.clip(accept, 0, width - 1)[:, None], axis=1
        )[:, 0]
        latest_out = jnp.where(q_count > 0, fresh, latest)
        new_paged = PagedKVCache(
            k_pages=pages_out["k"], v_pages=pages_out["v"],
            page_table=paged.page_table, lengths=new_lengths,
        )
        return new_paged, toks, accept, latest_out, rng

    assert b_slots <= t_budget, (b_slots, t_budget)
    return jax.jit(mixed_fn, donate_argnums=(1,))
