"""The mixed-phase dispatch program: ONE compiled step for the whole
ragged wave.

The scheduler packs every row's work for a step — one token per decode
row, up to ``chunk`` prompt tokens per prefill row — into a FLAT token
axis of static length ``t_budget`` (right-padded with trash tokens), so
the transformer trunk (projections, MLP, norms: all per-token) runs at
exactly the wave's token count regardless of how it splits between
phases.  Attention is the only op that needs row structure: the flat
q tokens are re-packed per row into ``[B, chunk]`` and handed to the
ragged paged-attention kernel (``ops/ragged_attention.py``), whose
causal mask makes a decode row the ``q_count == 1`` special case of a
prefill chunk.  KV for the step is scattered into the paged cache
BEFORE attention, so the kernel is a pure page read.

Exactly ONE program compiles per engine (static ``t_budget`` / ``chunk``
/ ``max_slots``): there is no bucket grid to warm, no per-shape compile
to hit mid-run — the property the warmup-grid machinery exists to
approximate for the wave engine, the mixed program has by construction.

Unsupported here (the wave engine keeps them): guided decoding and LoRA
adapters are refused at submit (serving/engine.py + Scheduler.enqueue);
mesh sharding makes build_serving_engine fall back to wave mode; and
shared-prefix KV reuse simply does not apply — every prompt prefills in
full, so provider.py skips prefix priming in continuous mode rather
than holding pages the program would never read.
"""

from __future__ import annotations

from typing import Any

from ...models.llama import (
    _PROJ_BIAS,
    apply_rope,
    rms_norm,
    rope_frequencies,
)
from ...models.quant import mm

__all__ = ["make_mixed_fn"]


def make_mixed_fn(generator: Any, t_budget: int, chunk: int):
    """Compile the mixed-step program for ``generator`` (paged, no mesh).

    Signature of the returned jitted function::

        fn(params, paged, ids, rows, pos, valid, in_row,
           q_start, q_count, kv_len, rng, temp, top_p)
        -> (new_paged, next_tokens [B], rng)

    Flat inputs (length ``t_budget``): ``ids`` token ids, ``rows`` the
    owning slot per token, ``pos`` absolute positions, ``valid`` live
    mask (padding tokens write to the trash page), ``in_row`` each
    token's index within its row's chunk.  Per-slot inputs (length
    ``max_slots``): ``q_start`` the flat offset of the slot's first
    token, ``q_count`` its token count this step (0 = not scheduled),
    ``kv_len`` the pages' valid length AFTER this step's writes (rows
    not scheduled keep their current length).  ``next_tokens[b]``
    samples the slot's last valid logit — meaningful only for decode
    rows and prompt-completing prefill rows; the scheduler's commit
    phase ignores the rest.
    """
    jax, jnp = generator._jax, generator._jnp
    config = generator.config
    b_slots = generator.max_slots
    inv_freq = rope_frequencies(config)
    lax = jax.lax

    def mixed_fn(params, paged, ids, rows, pos, valid, in_row,
                 q_start, q_count, kv_len, rng, temp, top_p):
        from ...ops.paged_attention import PagedKVCache
        from ...ops.ragged_attention import ragged_paged_attention

        page_size = paged.page_size
        x = jnp.take(params["embed"], ids, axis=0)[None]  # [1, T, H]
        positions = pos[None]  # [1, T]
        # flat -> per-row packing indices for the attention re-pack
        pack_idx = jnp.clip(
            q_start[:, None] + jnp.arange(chunk, dtype=jnp.int32)[None, :],
            0, t_budget - 1,
        )  # [B, chunk]
        # per-token page/slot targets (invalid tokens -> trash page 0)
        page_ids = jnp.where(
            valid, paged.page_table[rows, pos // page_size], 0
        )
        page_slots = jnp.where(valid, pos % page_size, 0)

        def layer_step(carry, scanned):
            x = carry
            weights = scanned["w"]
            attn_in = rms_norm(x, weights["ln_attn"], config.rms_norm_eps)

            def proj(h_in, name):
                y = mm(h_in, weights[name])
                bias = _PROJ_BIAS.get(name)
                if bias is not None and bias in weights:
                    y = y + weights[bias].astype(y.dtype)
                return y

            q = proj(attn_in, "wq").reshape(
                1, t_budget, config.num_heads, config.head_dim
            )
            k = proj(attn_in, "wk").reshape(
                1, t_budget, config.num_kv_heads, config.head_dim
            )
            v = proj(attn_in, "wv").reshape(
                1, t_budget, config.num_kv_heads, config.head_dim
            )
            q = apply_rope(q, positions, inv_freq)
            k = apply_rope(k, positions, inv_freq)
            # scatter this step's K/V into the pages FIRST — the ragged
            # kernel then reads a cache that already holds every token a
            # causal query may attend to (its own included)
            k_pages = scanned["k"].at[page_ids, page_slots].set(
                k[0].astype(scanned["k"].dtype)
            )
            v_pages = scanned["v"].at[page_ids, page_slots].set(
                v[0].astype(scanned["v"].dtype)
            )
            q_pack = q[0][pack_idx]  # [B, chunk, QH, D]
            attn_pack = ragged_paged_attention(
                q_pack.astype(k_pages.dtype), k_pages, v_pages,
                paged.page_table, kv_len, q_count,
                sliding_window=config.sliding_window,
            )
            attn = attn_pack[rows, in_row]  # back to flat [T, QH, D]
            x = x + proj(attn.astype(x.dtype).reshape(1, t_budget, -1), "wo")
            mlp_in = rms_norm(x, weights["ln_mlp"], config.rms_norm_eps)
            gate = jax.nn.silu(proj(mlp_in, "w_gate"))
            up = proj(mlp_in, "w_up")
            x = x + proj(gate * up, "w_down")
            return x, {"k": k_pages, "v": v_pages}

        scanned_in = {
            "w": params["layers"], "k": paged.k_pages, "v": paged.v_pages,
        }
        x, pages_out = lax.scan(layer_step, x, scanned_in)

        x = rms_norm(x, params["ln_final"], config.rms_norm_eps)
        # only each slot's LAST valid token needs a logit row: gather it
        # before the head matmul so the [vocab] projection runs at [B],
        # not [T]
        last_flat = jnp.clip(q_start + jnp.maximum(q_count, 1) - 1,
                             0, t_budget - 1)
        x_last = x[0][last_flat]  # [B, H]
        head = (
            params["embed"].T if config.tie_embeddings else params["lm_head"]
        )
        logits = jnp.einsum(
            "bh,hv->bv", x_last, head, preferred_element_type=jnp.float32
        )
        next_tokens, rng = generator._sample(logits, rng, temp, top_p)
        new_paged = PagedKVCache(
            k_pages=pages_out["k"], v_pages=pages_out["v"],
            page_table=paged.page_table, lengths=kv_len,
        )
        return new_paged, next_tokens, rng

    assert b_slots <= t_budget, (b_slots, t_budget)
    return jax.jit(mixed_fn, donate_argnums=(1,))
