"""The continuous-batching scheduler: schedule → dispatch → commit.

Replaces the wave engine's implicit phase machinery (batched prefill
dispatches + fixed decode blocks, serving/engine.py) with an explicit
per-step loop over ONE ragged mixed-phase program:

- **schedule** (:meth:`Scheduler._schedule`) — form this step's ragged
  wave: every decode row contributes its next token (or a prompt-lookup
  speculation verify chunk, below), every prefill row contributes its
  next chunk (Sarathi-style: at most ``chunk`` tokens, so a prompt
  storm stalls in-flight decodes for at most one chunk's compute per
  step), and queued requests are admitted into the RUNNING wave the
  moment a slot + pages free up — token-level admission, no block
  boundary, no admission window;
- **dispatch** (:meth:`Scheduler._dispatch`) — pack the wave onto the
  flat token axis and run the one compiled mixed program
  (``sched/mixed.py`` + ``ops/ragged_attention.py``), WITHOUT waiting
  for it;
- **commit** (:meth:`Scheduler._commit_oldest`) — fetch a dispatched
  step's sampled tokens (the step's ONE host sync), advance rows, and
  recycle a finished row's slot and KV pages THIS step — not
  ``decode_block - 1`` junk tokens later — so the next step's admission
  can reuse them.

**Decode-ahead pipelining** (``pipeline_depth`` > 1, the wave engine's
in-flight-blocks discipline transplanted): dispatch and commit are
decoupled through a bounded in-flight queue, so step N+1 is planned
from PREDICTED row state (``_Row.pred_*``: authoritative + in-flight
deltas) and dispatched while step N's sampled tokens are still on
device.  A chained decode row's input id never visits the host — the
program substitutes its carried per-slot ``latest`` sample buffer
(``from_prev``) — so only accepted token ids ever cross the host
boundary, at commit, asynchronously.  The replan path is conservative:
a commit that invalidates a prediction (finish, cancel) releases the
row immediately, later in-flight work for it commits as a no-op
(``podmortem_sched_pipeline_voided_total``), and admission only ever
consumes authoritatively-freed slots and pages.  Stale KV writes from
voided work are safe by construction: device execution is serialised by
the donated paged-cache dependency, so a re-granted page's new owner
writes every position it will ever read AFTER the voided write lands.

**Prompt-lookup self-speculation** (``spec_decode``, sched/draft.py): a
greedy decode row with no in-flight work proposes up to
``spec_lookup_k`` draft tokens from its own prompt+generated context
and verifies them as ONE ``q_count = k + 1`` row; the commit accepts
the longest sample-confirmed prefix (``accept + 1`` tokens per host
round-trip), byte-identical to one-token greedy decoding by
construction.

**Block-hash prefix caching** (``kvstore=``, serving/kvstore.py): at
admission the request's longest cached block chain is matched and those
STORE-OWNED device pages are mapped into the row's page table read-only
(refcounted); the row's ``pos`` starts at ``cached_len``, so only the
uncached suffix prefills — the ragged program already handles arbitrary
per-row q_count, a hit is just a shorter chunk.  At prefill completion
the row donates its full prompt blocks' pages to the store (ownership
transfer, no copy).  When admission needs pages, LRU refcount-zero
blocks are evicted; with a host pool (ops/kv_transfer.py) the page's KV
is gathered on device at eviction (no sync) and fetched to host inside
the commit step's existing sync window, restorable later with one DMA.
Greedy output is byte-identical cache-on vs cache-off: KV vectors are
per-token projections, independent of how the prompt was chunked.

Counters (docs/METRICS.md): ``podmortem_sched_admitted_midwave_total``,
``podmortem_sched_chunked_prefill_total``,
``podmortem_sched_recycled_slot_total``,
``podmortem_sched_stall_free_step_total``,
``podmortem_sched_stall_step_total``,
``podmortem_sched_pipeline_dispatch_ahead_total``,
``podmortem_sched_pipeline_voided_total``,
``podmortem_spec_rounds_total``, ``podmortem_spec_proposed_total``,
``podmortem_spec_accepted_total``, ``podmortem_spec_rest_total``,
``podmortem_kv_hit_total``, ``podmortem_kv_miss_total``,
``podmortem_kv_evict_total``, ``podmortem_kv_offload_total``,
``podmortem_kv_restore_total``,
``podmortem_kv_prefill_tokens_saved_total``.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import time
from collections import deque
from typing import Any, Optional

import numpy as np

from ..types import (
    DeadlineExceeded,
    GenerationResult,
    OversizedRequest,
    SamplingParams,
    ShedLowValue,
    _Slot,
    pages_needed,
    prompt_budget,
)
from .draft import PromptLookupDraft
from .types import RowWork, StepOutcome, StepPlan, _Row

log = logging.getLogger(__name__)

__all__ = ["Scheduler"]


@dataclasses.dataclass
class _InFlight:
    """One dispatched-but-uncommitted step: the plan and the device-side
    result references (token samples + per-slot accepted-draft counts)
    the commit will fetch."""

    plan: StepPlan
    toks: Any  # device [B, W] sampled token ids
    accept: Any  # device [B] accepted-draft counts
    dispatch_t: float
    started: float = 0.0
    held_rows: int = 0


class Scheduler:
    """Continuous-batching scheduler over a paged :class:`BatchedGenerator`.

    Requires paged KV and no mesh (the mixed program has no SPMD rule
    yet); guided decoding and LoRA requests are refused at submit — the
    ServingEngine routes them to the wave path or fails them loudly.
    """

    def __init__(
        self,
        generator: Any,
        *,
        chunk: int = 64,
        token_budget: int = 0,
        pipeline_depth: int = 1,
        spec_decode: bool = False,
        spec_lookup_k: int = 4,
        kvstore: Optional[Any] = None,
        queue_limit: int = 0,
        overload_policy: Optional[Any] = None,
        fabric_mirror: bool = False,
        audit_hook: Optional[Any] = None,
    ) -> None:
        if not getattr(generator, "paged", False):
            raise ValueError("the continuous scheduler requires paged KV")
        if kvstore is not None and kvstore.page_size != generator.page_size:
            raise ValueError(
                f"kvstore page_size={kvstore.page_size} != generator "
                f"page_size={generator.page_size}: block hashes would not "
                f"align with KV pages"
            )
        if getattr(generator, "mesh", None) is not None:
            raise ValueError(
                "the continuous scheduler does not support mesh sharding yet"
            )
        self.generator = generator
        self.chunk = max(1, min(chunk, generator.max_seq))
        self.t_budget = token_budget or max(self.chunk, generator.max_slots)
        if self.t_budget < generator.max_slots:
            # a full decode batch must always fit one step, or decode
            # rows would be starved by construction
            raise ValueError(
                f"sched token_budget={self.t_budget} < max_slots="
                f"{generator.max_slots}: a full decode batch would not fit"
            )
        if self.chunk > self.t_budget:
            raise ValueError(
                f"sched chunk={self.chunk} > token_budget={self.t_budget}"
            )
        #: bounded in-flight dispatch queue; 1 = synchronous (each step
        #: commits the dispatch it just issued, the pre-pipelining loop)
        self.depth = max(1, int(pipeline_depth))
        # a verify row is one q_count = 1 + k chunk: it must fit the
        # attention re-pack ([B, chunk]) and leave budget for peers
        k = int(spec_lookup_k) if spec_decode else 0
        self.spec_k = max(0, min(k, self.chunk - 1, self.t_budget - 1))
        #: sampled positions per slot in the mixed program (static)
        self.width = 1 + self.spec_k
        self._draft = PromptLookupDraft() if self.spec_k else None
        self._draft_ms = 0.0
        #: dispatched steps whose tokens are still on device, oldest
        #: first; bounded by ``depth``
        self._inflight: deque = deque()
        #: device [B] carry of each slot's freshest sampled token — the
        #: chaining buffer ``from_prev`` decode rows read in-program
        self._latest = None
        self._host_syncs = 0
        self._decode_committed = 0
        self.metrics = generator.metrics
        #: ``hook(req_id, token_ids_so_far)`` after each step for rows
        #: still generating — the streaming feed (ServingEngine marshals
        #: it onto the event loop).  Called from the decode worker.
        self.partial_hook: Optional[Any] = None
        # (req_id, tokens, params, submitted, priority) — admission order
        # is priority class first, then earliest deadline (EDF) within a
        # class, then FIFO (_edf_head)
        self._queue: deque = deque()
        self._rows: dict[int, _Row] = {}  # req_id -> row, insertion order
        self._next_req = itertools.count(1)
        self._kv_shadow = np.zeros((generator.max_slots,), np.int32)
        self._staged_tables: list[tuple[int, np.ndarray]] = []
        #: block-hash prefix cache (serving/kvstore.py); None = off
        self._kvstore = kvstore
        #: evicted blocks gathered on device but not yet fetched to the
        #: host pool: (hash, k_dev, v_dev) — drained inside the commit
        #: step's existing host-sync window (_drain_offload)
        self._pending_offload: list[tuple[bytes, Any, Any]] = []
        #: KV fabric mirror (operator_tpu/fabric/): copy newly-donated
        #: prompt blocks into the host pool at prefill completion so
        #: peers can fetch them over GET /kv/blocks/{hash} before
        #: eviction would have spilled them.  Gathers are eager device
        #: slices at registration; the fetch drains inside the commit
        #: step's host-sync window next to _drain_offload.
        self._fabric_mirror = bool(fabric_mirror)
        self._pending_mirror: list[tuple[bytes, Any, Any]] = []
        self._fn = None
        # host-side stats the bench reads (stats())
        self.steps = 0
        self.occupancy_sum = 0.0
        self.stall_steps = 0
        #: set to a list to record every step's ``StepPlan.trace()`` —
        #: the determinism test replays a fixed arrival trace and
        #: asserts the schedule is byte-identical
        self.plan_log: Optional[list] = None
        #: ``hook(self)`` after each step's commit window — the game-day
        #: invariant auditor's commit-barrier probe point (chaos/
        #: invariants.py checks page conservation against
        #: :meth:`page_accounting` here, while rows still hold pages)
        self.audit_hook: Optional[Any] = audit_hook
        #: queue eviction (router/value.py): when the submit queue holds
        #: ``queue_limit`` entries, enqueue sheds the LOWEST-VALUE
        #: non-protected request instead of growing without bound.
        #: 0 = unbounded (the pre-overload-control behaviour).
        self.queue_limit = max(0, int(queue_limit))
        self.overload_policy = overload_policy
        # queued requests evicted by value between steps; drained into
        # the next step()'s outcomes so callers get a terminal error
        self._evicted: list[StepOutcome] = []

    # ------------------------------------------------------------------
    # submit side
    # ------------------------------------------------------------------

    def enqueue(
        self,
        prompt: str,
        params: Optional[SamplingParams] = None,
        *,
        submitted: Optional[float] = None,
        priority: int = 0,
        resume_tokens: Optional[list[int]] = None,
    ) -> int:
        """Tokenise + queue one request; returns its req id.  Raises
        :class:`OversizedRequest` when the request can never fit the KV
        pool, ``ValueError`` for features the mixed program does not
        serve (guided decoding, LoRA).  ``submitted`` carries the
        caller's original perf_counter submit stamp (ServingEngine), so
        queue wait covers the engine handoff too, not just this queue.
        ``priority`` orders admission (higher class first); WITHIN a
        class the queue is earliest-deadline-first, so an urgent late
        arrival overtakes an earlier request with slack (_edf_head).
        ``resume_tokens`` is the token-level failover path (streaming
        resume, router/resume.py): already-generated token ids appended
        VERBATIM after the prompt, so the survivor re-prefills
        prompt+generated-so-far — cheap under the prefix cache — and the
        result's token_ids carry only the continuation."""
        g = self.generator
        params = params or SamplingParams()
        if params.guided_choice is not None or params.guided_regex is not None:
            raise ValueError(
                "guided decoding is not supported by the continuous "
                "scheduler (sched_mode=continuous); use the wave engine"
            )
        if params.adapter is not None:
            raise ValueError(
                "LoRA adapters are not supported by the continuous "
                "scheduler (sched_mode=continuous); use the wave engine"
            )
        ids = g.tokenizer.encode(prompt)
        # same truncation budget + middle-drop as the wave path's admit()
        budget = prompt_budget(g.max_seq, params.max_tokens)
        if resume_tokens:
            # resumed stream: the generated suffix must survive VERBATIM
            # (the caller already streamed those tokens), so truncation
            # may only eat the prompt part
            if len(resume_tokens) >= budget:
                raise OversizedRequest(
                    f"resume checkpoint of {len(resume_tokens)} tokens "
                    f"leaves no prompt budget (budget {budget})"
                )
            tokens = (
                g._truncate_prompt(ids, budget - len(resume_tokens))
                + list(resume_tokens)
            )
        else:
            tokens = g._truncate_prompt(ids, budget)
        pool = g.allocator.num_pages - 1 - g.prefix_held_pages
        if self._pages_needed(tokens, params) > pool:
            raise OversizedRequest(
                f"request needs {self._pages_needed(tokens, params)} KV "
                f"pages, cache holds {pool}"
            )
        req_id = next(self._next_req)
        if (
            self.queue_limit
            and self.overload_policy is not None
            and len(self._queue) >= self.queue_limit
        ):
            # queue at its limit: shed the lowest-value request — which
            # may be the arrival itself — instead of growing unboundedly
            self._evict_lowest_value(req_id, params)
        self._queue.append((
            req_id, tokens, params,
            submitted if submitted is not None else time.perf_counter(),
            priority,
        ))
        return req_id

    def _request_value(self, params: SamplingParams, now: float):
        """Score one request with the shared value model (residual
        deadline on the generator's injectable clock — no wall clock,
        GL007)."""
        residual = (
            None if params.deadline is None else params.deadline - now
        )
        return self.overload_policy.model.value(
            slo_class=params.slo_class,
            residual_s=residual,
            recall_p=params.recall_p,
        )

    def _evict_lowest_value(
        self, incoming_id: int, incoming: SamplingParams
    ) -> None:
        """Shed-lowest-value-first queue eviction: score every queued
        request plus the arrival, drop the minimum non-protected one.
        A queued victim surfaces as a :class:`ShedLowValue` StepOutcome
        at the next step; the arrival itself losing raises straight to
        the caller.  All-protected queues grow instead (the ladder never
        sheds a class below its attainment target)."""
        now = self.generator._clock()
        pressure = len(self._queue) + len(self._rows)
        candidates = [(str(incoming_id), self._request_value(incoming, now))]
        by_id = {}
        for entry in self._queue:
            value = self._request_value(entry[2], now)
            candidates.append((str(entry[0]), value))
            by_id[str(entry[0])] = entry
        victim = self.overload_policy.pick_eviction(candidates)
        if victim is None:
            return  # every candidate protected: let the queue grow
        rid, value = victim
        self.overload_policy.record_eviction(
            rid, value, pressure=pressure, site="sched",
        )
        self.metrics.incr("sched_queue_evicted")
        if rid == str(incoming_id):
            raise ShedLowValue(
                f"request shed at enqueue: value score "
                f"{round(value.score, 6)} is the queue minimum at "
                f"pressure {pressure}"
            )
        entry = by_id[rid]
        self._queue.remove(entry)
        self._evicted.append(StepOutcome(entry[0], error=ShedLowValue(
            f"queued request evicted by higher-value arrival at "
            f"pressure {pressure}"
        )))

    def cancel(self, req_id: int) -> bool:
        """Drop a queued request or reclaim a live row's slot/pages now."""
        for i, entry in enumerate(self._queue):
            if entry[0] == req_id:
                del self._queue[i]
                return True
        row = self._rows.get(req_id)
        if row is None:
            return False
        self._release_row(row)
        return True

    @property
    def num_active(self) -> int:
        return len(self._rows)

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def total_work(self) -> int:
        return len(self._rows) + len(self._queue)

    def stats(self) -> dict:
        """Step-level occupancy/stall/pipelining stats (bench.py)."""
        proposed = self.metrics.counter("spec_proposed")
        accepted = self.metrics.counter("spec_accepted")
        rounds = self.metrics.counter("spec_rounds")
        return {
            "steps": self.steps,
            "batch_occupancy_avg": round(
                self.occupancy_sum / self.steps, 4
            ) if self.steps else None,
            "decode_stall_steps": self.stall_steps,
            "admitted_midwave": self.metrics.counter("sched_admitted_midwave"),
            "chunked_prefills": self.metrics.counter("sched_chunked_prefill"),
            "recycled_slots": self.metrics.counter("sched_recycled_slot"),
            # decode-ahead + speculation: the headline is generated
            # tokens committed per host round-trip — 1.0 is the old
            # synchronous one-token loop's ceiling
            "pipeline_depth": self.depth,
            "dispatch_ahead": self.metrics.counter(
                "sched_pipeline_dispatch_ahead"
            ),
            "voided_work": self.metrics.counter("sched_pipeline_voided"),
            "host_syncs": self._host_syncs,
            "decode_tokens_committed": self._decode_committed,
            "decode_tokens_per_host_sync": round(
                self._decode_committed / self._host_syncs, 4
            ) if self._host_syncs else None,
            "spec_decode": {
                "enabled": self._draft is not None,
                "lookup_k": self.spec_k,
                "rest_rounds": self.metrics.counter("spec_rest"),
                "verify_rounds": rounds,
                "drafts_proposed": proposed,
                "drafts_accepted": accepted,
                "acceptance_rate": round(accepted / proposed, 4)
                if proposed else None,
                "mean_accepted_per_round": round(
                    accepted / rounds, 4
                ) if rounds else None,
                "draft_overhead_ms": round(self._draft_ms, 3),
            },
            "kv_economy": (
                {
                    **self._kvstore.stats(),
                    "evictions": self.metrics.counter("kv_evict"),
                    "offloads": self.metrics.counter("kv_offload"),
                    "restores": self.metrics.counter("kv_restore"),
                    "prefill_tokens_saved": self.metrics.counter(
                        "kv_prefill_tokens_saved"
                    ),
                    "offload_pending": len(self._pending_offload),
                    "mirrored": self.metrics.counter("fabric_mirror"),
                    "mirror_pending": len(self._pending_mirror),
                }
                if self._kvstore is not None else None
            ),
        }

    def reset(self) -> None:
        """Drop every row and queued request (the supervised-restart /
        recovery path: the generator rebuilds device state separately
        and the engine has already collected the in-flight futures).
        In-flight dispatches are abandoned unfetched — their device
        buffers died with the reset device state."""
        self._queue.clear()
        self._rows.clear()
        self._kv_shadow[:] = 0
        self._staged_tables.clear()
        self._inflight.clear()
        self._latest = None
        self._pending_offload.clear()  # gathered buffers died with the device state
        self._pending_mirror.clear()
        if self._kvstore is not None:
            # every device page is gone (the generator rebuilds its
            # allocator); host-pool copies survive and stay restorable
            self._kvstore.reset()

    def spill_cache(self) -> int:
        """Evict every refcount-zero cached block off device — to the
        host pool when one is configured, else dropped.  Returns the
        number of blocks spilled.  The deterministic hook the bench and
        tests use to exercise the restored-from-host lane, and an
        operator's pre-burst page reclaim."""
        if self._kvstore is None:
            return 0
        count = len(self._kvstore.evictable())
        if count:
            self._evict_blocks(count)
        return count

    def precompile(self) -> None:
        """Compile the one mixed program before serving (an empty wave
        drives the full trace: the program's shapes are workload-
        independent by construction)."""
        entry = self._dispatch(StepPlan())
        np.asarray(entry.toks)  # block: precompile must finish warm

    # ------------------------------------------------------------------
    # the loop
    # ------------------------------------------------------------------

    def step(self) -> list[StepOutcome]:
        """One scheduler round: plan + dispatch the next ragged wave
        from predicted row state, then commit dispatched steps down to
        the pipeline bound.  Returns every request that reached a
        terminal state (result or admission error).

        ``depth == 1`` degenerates to the original synchronous loop —
        the dispatch just issued commits before the call returns.  At
        ``depth >= 2`` the dispatch for step N+1 is issued BEFORE step
        N's commit, so the host gap between commit N-1 and dispatch N+1
        collapses to ~0: the chip always has a queued wave."""
        g = self.generator
        if g.fault_plan is not None:
            # chaos seam, same site as the wave engine's step so stall /
            # device-error scenarios drive both loops identically
            g.fault_plan.apply("engine.step", active=self.num_active)
        outcomes: list[StepOutcome] = []
        if self._evicted:
            # value-based queue evictions since the last step surface as
            # terminal ShedLowValue outcomes here
            outcomes.extend(self._evicted)
            self._evicted.clear()
        plan = self._schedule(outcomes)
        held_rows = len(self._rows)  # snapshot BEFORE commit recycles
        if self.plan_log is not None:
            self.plan_log.append(plan.trace())
        if plan.work:
            started = time.perf_counter()
            with g._annotation(
                "podmortem.sched_step",
                [row.params for row in self._rows.values()],
            ):
                entry = self._dispatch(plan)
            entry.started = started
            entry.held_rows = held_rows
            if self._inflight:
                self.metrics.incr("sched_pipeline_dispatch_ahead")
            self._inflight.append(entry)
            # step accounting at dispatch: occupancy is HELD slots over
            # capacity (rows at any phase — the same "slots occupied"
            # definition the wave engine's batch_occupancy stage uses,
            # so bench.py compares like with like); a stall step is one
            # where a decode-ready row got NO token — the schedule never
            # defers decodes while token_budget >= max_slots, so the
            # counter is the proof of the property, not a mechanism
            self.steps += 1
            occupancy = held_rows / g.max_slots
            self.occupancy_sum += occupancy
            self.metrics.record("sched_occupancy", occupancy * 100.0)
            if plan.deferred_decode:
                self.stall_steps += 1
                self.metrics.incr("sched_stall_step")
            else:
                self.metrics.incr("sched_stall_free_step")
        elif not self._inflight:
            return outcomes
        # commit down to the pipeline bound (depth - 1 stays in flight
        # across calls); with nothing to dispatch, drain one entry per
        # round — progress is guaranteed (a plan can only be empty while
        # rows/queue exist if their work is already in flight) and the
        # serve loop stays responsive to cancellation between commits
        while len(self._inflight) > self.depth - 1 or (
            self._inflight and not plan.work
        ):
            self._commit_oldest(outcomes)
            if not plan.work:
                break
        if self.audit_hook is not None:
            # commit barrier: every page granted, cached, offloaded or
            # freed this step has settled — the point where fleet-wide
            # conservation invariants must hold exactly
            self.audit_hook(self)
        return outcomes

    # -- audit ---------------------------------------------------------

    def page_accounting(self) -> dict:
        """Snapshot of where every KV page is right now — the terms of
        the page-conservation invariant the game-day auditor checks at
        commit barriers:

        ``available + row_pages + store_pages + prefix_pages == total``

        (page 0 is the reserved trash page, hence ``num_pages - 1``).
        ``row_pages`` are grants held by live rows, ``store_pages`` are
        device pages pinned by the prefix cache, ``prefix_pages`` are
        the generator's system-prefix hold."""
        g = self.generator
        return {
            "available": g.allocator.available,
            "row_pages": sum(len(row.pages) for row in self._rows.values()),
            "store_pages": (
                self._kvstore.device_pages_held
                if self._kvstore is not None
                else 0
            ),
            "prefix_pages": g.prefix_held_pages,
            "total": g.allocator.num_pages - 1,
        }

    # -- schedule ------------------------------------------------------

    def _pages_needed(self, tokens: list, params: SamplingParams) -> int:
        g = self.generator
        return pages_needed(
            len(tokens), params.max_tokens, g.max_seq, g.page_size
        )

    # -- prefix cache (serving/kvstore.py) -----------------------------

    def _match_prefix(self, tokens: list, need: int) -> list:
        """Match + acquire the longest AFFORDABLE cached block chain for
        ``tokens``.  Host-resident blocks are restored into fresh
        store-owned pages (one DMA each); LRU refcount-zero blocks are
        evicted when the row grant + restores would not fit.  Returns
        device-resident blocks with refs held; the chain shrinks from
        the tail until it fits, possibly to nothing."""
        g = self.generator
        store = self._kvstore
        chain = store.match(tokens)
        if not chain:
            return []
        store.acquire(chain)
        # a chain entry that lost both its device page and its host copy
        # ends the usable prefix (match() already breaks on those; this
        # guards the race where the host pool dropped it since)
        usable = []
        for blk in chain:
            if blk.page >= 0 or store.restorable(blk.hash):
                usable.append(blk)
            else:
                break
        if len(usable) < len(chain):
            store.release([b.hash for b in chain[len(usable) :]])
        while usable:
            restores = sum(1 for b in usable if b.page < 0)
            required = (need - len(usable)) + restores
            deficit = required - g.allocator.available
            if deficit > 0:
                self._evict_blocks(deficit)
            if required <= g.allocator.available:
                break
            dropped = usable.pop()
            store.release([dropped.hash])
        for blk in usable:
            if blk.page < 0:
                self._restore_block(blk)
        return usable

    def _evict_blocks(self, count: int) -> None:
        """Evict up to ``count`` LRU refcount-zero blocks from device.
        With a host pool, each victim's page is GATHERED into fresh
        device buffers first (an enqueued device-side copy, no sync —
        ordering guarantees the gather reads the page before any new
        owner's writes land) and queued for the commit-side offload
        drain; without one the block is simply forgotten."""
        from ...ops import kv_transfer

        g = self.generator
        store = self._kvstore
        pool = store.host_pool
        for blk in store.evict_lru(count):
            # capture the page BEFORE mark_offloaded/forget clear it on
            # the shared entry — releasing after would return -1 to the
            # free list (a leak plus a poisoned allocation)
            page = blk.page
            if pool is not None and pool.has(blk.hash):
                store.mark_offloaded(blk.hash)  # host copy already there
            elif pool is not None and pool.capacity_bytes > 0:
                k_dev, v_dev = kv_transfer.gather_page(g.paged_cache, page)
                self._pending_offload.append((blk.hash, k_dev, v_dev))
                store.pending_offload.add(blk.hash)
                store.mark_offloaded(blk.hash)
            else:
                store.forget(blk.hash)
            g.allocator.release([page])

    def _restore_block(self, blk: Any) -> None:
        """Bring an off-device block back: one freshly-allocated
        store-owned page + one DMA (from the pending-offload device
        buffers when the drain hasn't run yet, else from the host
        pool) — table writes + a page copy, never recompute."""
        from ...ops import kv_transfer

        g = self.generator
        store = self._kvstore
        page = g.allocator.allocate(1)[0]
        entry = None
        if blk.hash in store.pending_offload:
            for i, (h, k_dev, v_dev) in enumerate(self._pending_offload):
                if h == blk.hash:
                    entry = (k_dev, v_dev)
                    del self._pending_offload[i]
                    break
            store.pending_offload.discard(blk.hash)
        if entry is None:
            entry = store.host_pool.get(blk.hash)
        g.paged_cache = kv_transfer.restore_page(
            g.paged_cache, page, entry[0], entry[1]
        )
        blk.page = page
        self.metrics.incr("kv_restore")

    def _drain_offload(self) -> None:
        """Fetch gathered eviction buffers to the host pool — called
        ONLY inside the commit step's existing host-sync window, so the
        device→host readback overlaps the sync the loop already pays."""
        from ...ops import kv_transfer

        store = self._kvstore
        pool = store.host_pool
        for h, k_dev, v_dev in self._pending_offload:
            if h not in store.pending_offload:
                continue  # restored from these buffers meanwhile
            store.pending_offload.discard(h)
            dropped = pool.put(h, *kv_transfer.fetch_page(k_dev, v_dev))
            if dropped is None:
                store.forget(h)  # pool refused: the block is gone
                continue
            self.metrics.incr("kv_offload")
            for old in dropped:
                # LRU-dropped host copies: forget any index entry that
                # has no device page left either
                entry = store.get(old)
                if entry is not None and entry.page < 0:
                    store.forget(old)
        self._pending_offload.clear()

    def _drain_mirror(self) -> None:
        """Fetch mirror-gathered prompt blocks to the host pool — same
        discipline as _drain_offload: called ONLY inside the commit
        step's host-sync window.  Unlike offload the device page stays
        resident; a refused put just means peers cannot fetch it."""
        from ...ops import kv_transfer

        store = self._kvstore
        pool = store.host_pool
        for h, k_dev, v_dev in self._pending_mirror:
            if pool.has(h):
                continue  # offload drain or a peer fetch beat us to it
            dropped = pool.put(h, *kv_transfer.fetch_page(k_dev, v_dev))
            if dropped is None:
                continue  # pool refused; the block stays device-only
            self.metrics.incr("fabric_mirror")
            for old in dropped:
                entry = store.get(old)
                if entry is not None and entry.page < 0:
                    store.forget(old)
        self._pending_mirror.clear()

    def _register_row_blocks(self, row: _Row) -> None:
        """Prefill completed: donate the row's FULL prompt blocks to the
        store (ownership transfer of the device pages — no copy).  Only
        full blocks are immutable by construction (generation writes at
        positions >= prompt_len, past the last full prompt block), and
        the row keeps a reference on each donated block until release."""
        from ...ops import kv_transfer
        from ..kvstore import block_hashes

        g = self.generator
        store = self._kvstore
        ps = g.page_size
        pool = store.host_pool
        mirror = (
            self._fabric_mirror
            and pool is not None
            and pool.capacity_bytes > 0
        )
        k_full = row.prompt_len // ps
        c0 = row.cached_len // ps
        if k_full <= c0:
            return
        hashes = block_hashes(row.tokens[: k_full * ps], ps)
        transferred: set[int] = set()
        for j in range(c0, k_full):
            h = hashes[j]
            entry = store.get(h)
            page = row.pages[j - c0]
            if entry is not None and entry.page >= 0:
                # a concurrent identical prompt registered first: keep
                # the row-owned duplicate page, no transfer
                continue
            store.insert(
                h,
                hashes[j - 1] if j else None,
                row.tokens[j * ps : (j + 1) * ps],
                page,
                refs=1,
            )
            store.pending_offload.discard(h)
            transferred.add(j - c0)
            row.cached_hashes.append(h)
            if mirror and not pool.has(h):
                # eager device slice now (no sync); the host fetch waits
                # for the commit window's _drain_mirror
                k_dev, v_dev = kv_transfer.gather_page(g.paged_cache, page)
                self._pending_mirror.append((h, k_dev, v_dev))
        if transferred:
            row.pages = [
                p for i, p in enumerate(row.pages) if i not in transferred
            ]

    def _sweep_expired(self, outcomes: list[StepOutcome]) -> None:
        """Fail EVERY queued request whose deadline already expired —
        the whole queue, every step, regardless of capacity.  Checking
        only at admission would leave an expired caller hanging until a
        slot (and the head's pages) freed, where the wave path's sweep
        fails it on every loop round."""
        if not self._queue:
            return
        now = self.generator._clock()
        live = deque()
        for entry in self._queue:
            params = entry[2]
            if params.deadline is not None and params.deadline <= now:
                self.metrics.incr("admission_deadline_rejected")
                outcomes.append(StepOutcome(entry[0], error=DeadlineExceeded(
                    "deadline expired while queued for admission"
                )))
            else:
                live.append(entry)
        self._queue = live

    def _edf_head(self) -> int:
        """Index of the next request to admit: highest priority class
        first, earliest deadline within the class (EDF), FIFO among
        deadline-free peers.  Deadline-free requests sort AFTER any
        deadline in their class but are never skipped past — admission
        still stops (does not skip ahead) when the chosen head's pages
        don't fit, so a starved large request keeps its turn."""
        best = 0
        best_key = None
        for i, entry in enumerate(self._queue):
            params, priority = entry[2], entry[4]
            deadline = (
                params.deadline if params.deadline is not None
                else float("inf")
            )
            key = (-priority, deadline, i)
            if best_key is None or key < best_key:
                best, best_key = i, key
        return best

    def _admit_queued(
        self, outcomes: list[StepOutcome]
    ) -> tuple[list[int], int]:
        """Token-level admission: pull queued requests into free slots
        while pages last.  Runs at the top of EVERY step, so an arrival
        joins the running wave at the next step boundary — never waits
        for a decode block or an admission window.  Returns the admitted
        req ids and the total prompt tokens they reused from the prefix
        cache (StepPlan.cached_tokens)."""
        g = self.generator
        self._sweep_expired(outcomes)
        admitted: list[int] = []
        cached_total = 0
        while self._queue:
            free = g.free_slots()
            if not free:
                break
            head = self._edf_head()
            req_id, tokens, params, submitted, _ = self._queue[head]
            clamped, outcome = g.deadline_policy(
                params, pressure=len(self._queue) + len(self._rows)
            )
            if outcome == "shed":
                # overload ladder: lowest value at admission under storm
                del self._queue[head]
                self.metrics.incr("admission_shed")
                outcomes.append(StepOutcome(req_id, error=ShedLowValue(
                    "request shed at admission: lowest value under "
                    "overload (router/value.py ladder)"
                )))
                continue
            if outcome == "rejected":
                # expired between the check above and the policy's clock
                # read: minimal one-token clamp, same as the wave path's
                # _deadline_clamp_wave
                clamped = dataclasses.replace(
                    params, max_tokens=1, deadline_clamped=True
                )
                outcome = "truncated"
            if outcome == "truncated":
                self.metrics.incr("admission_deadline_truncated")
            need = self._pages_needed(tokens, clamped)
            # prefix-cache match: the longest affordable cached block
            # chain replaces the head of the row's grant (store-owned
            # read-only pages; refs held until the row releases)
            picked: list = []
            if self._kvstore is not None:
                picked = self._match_prefix(tokens, need)
            grant_need = need - len(picked)
            if grant_need > g.allocator.available and self._kvstore is not None:
                # the free list is short but the store may be sitting on
                # refcount-zero cached pages — reclaim those first (LRU,
                # spilled to host when a pool exists).  Without this an
                # idle engine whose pool is fully cached would deadlock:
                # nothing decoding means nothing ever frees a page.
                self._evict_blocks(grant_need - g.allocator.available)
            if grant_need > g.allocator.available:
                # backpressure: decode frees pages, retry next step
                if picked:
                    self._kvstore.release([b.hash for b in picked])
                break
            del self._queue[head]
            grant = g.allocator.allocate(grant_need)
            slot = free[0]
            row = _Row(
                req_id=req_id, slot=slot, tokens=tokens, params=clamped,
                pages=grant, submitted=submitted,
            )
            if picked:
                # cached blocks ARE the prompt head: prefill starts at
                # cached_len (always inside a row-owned page — the match
                # is capped one token short of the prompt, so no row
                # ever appends into a shared page: the no-CoW rule)
                row.cached_len = len(picked) * g.page_size
                row.cached_hashes = [b.hash for b in picked]
                row.pos = row.cached_len
                self._kv_shadow[slot] = row.cached_len
                cached_total += row.cached_len
                self.metrics.incr(
                    "kv_prefill_tokens_saved", row.cached_len
                )
            self._rows[req_id] = row
            # measured submit -> admission wall: the span's queue_wait_ms
            # and the sched_queue_wait gauge read the SAME number
            row.queue_wait_ms = max(
                0.0, (time.perf_counter() - submitted) * 1e3
            )
            self.metrics.record("sched_queue_wait", row.queue_wait_ms)
            # mirror into the generator's slot table so free_slots /
            # num_active / the supervisor's leak audit see one truth
            slot_obj = _Slot()
            slot_obj.active = True
            slot_obj.prompt_len = len(tokens)
            slot_obj.params = clamped
            slot_obj.pages = grant
            g.slots[slot] = slot_obj
            # stage the row's page table for the next dispatch: cached
            # store-owned pages first, then the row's own grant
            row_table = np.zeros((g.pages_per_seq,), np.int32)
            if picked:
                row_table[: len(picked)] = [b.page for b in picked]
            row_table[len(picked) : len(picked) + len(grant)] = grant
            self._staged_tables.append((slot, row_table))
            admitted.append(req_id)
            if len(self._rows) > 1:
                self.metrics.incr("sched_admitted_midwave")
        return admitted, cached_total

    def _schedule(self, outcomes: list[StepOutcome]) -> StepPlan:
        """Plan the next ragged wave from PREDICTED row state (``pred_*``
        = authoritative + in-flight deltas), so a plan can be built while
        earlier dispatches are still on device.  The conservative-replan
        rule is structural: a row with an in-flight verify round
        (``pend_spec``) is skipped entirely — its true length is
        unknowable until commit — and commit-side voiding (_commit skips
        work whose row vanished) covers finish/cancel races."""
        g = self.generator
        plan = StepPlan()
        plan.admitted, plan.cached_tokens = self._admit_queued(outcomes)
        budget = self.t_budget
        cursor = 0
        # decode rows first — one token each (plus drafts), NEVER
        # deferred (the whole point: a prefill storm cannot starve an
        # in-flight decode).  A row predicted to have hit max_tokens or
        # the sequence cap sits out: its in-flight tokens already cover
        # the request, and commit will finish it.
        decode_ready = [
            (req_id, row) for req_id, row in self._rows.items()
            if not row.pend_spec
            and row.pred_decoding
            and row.pred_gen < row.params.max_tokens
            and row.pred_kv + 1 < g.max_seq
        ]
        for i, (req_id, row) in enumerate(decode_ready):
            if cursor >= budget:  # unreachable while budget >= max_slots
                plan.deferred_decode += 1
                continue
            greedy = (
                self._draft is not None and row.params.temperature <= 0.0
            )
            # speculation REST (how speculation composes with depth >= 2
            # pipelining): a greedy row with a chained token in flight
            # can never draft — the proposal needs its committed text —
            # so when a probe of the STALE context finds an n-gram hit,
            # the row sits this round out; its in-flight commit lands
            # meanwhile and the NEXT round verifies k drafts in one
            # dispatch.  Rest is bounded (the in-flight queue drains
            # within ``depth`` rounds) and taken only on a probe hit, so
            # draft-miss rows keep the 1-token/step pipelined chain.
            if (
                greedy
                and row.pend_gen > 0
                and row.pend_pos == 0
                and row.decoding
            ):
                t0 = time.perf_counter()
                probe = self._draft.propose(
                    row.tokens + row.generated, self.spec_k
                )
                dms = (time.perf_counter() - t0) * 1e3
                self._draft_ms += dms
                self.metrics.observe("spec_draft_milliseconds", dms)
                if probe:
                    self.metrics.incr("spec_rest")
                    continue
            # speculation: greedy rows with NO in-flight work (the draft
            # needs the committed text, and the verify row needs the
            # committed last token as its input id) try a prompt-lookup
            # proposal.  Draft width is capped so the row cannot overrun
            # max_tokens, the sequence cap, or the peers' reserved
            # one-token budget slots (rows_after).
            k_eff = 0
            drafts: tuple = ()
            rows_after = len(decode_ready) - i - 1
            if (
                greedy
                and row.pend_gen == 0
                and row.pend_pos == 0
                and row.decoding
                and row.generated
            ):
                cap = min(
                    self.spec_k,
                    row.params.max_tokens - len(row.generated) - 1,
                    g.max_seq - 1 - row.kv_len,
                    budget - cursor - 1 - rows_after,
                )
                if cap > 0:
                    t0 = time.perf_counter()
                    proposed = self._draft.propose(
                        row.tokens + row.generated, cap
                    )
                    dms = (time.perf_counter() - t0) * 1e3
                    self._draft_ms += dms
                    self.metrics.observe("spec_draft_milliseconds", dms)
                    if proposed:
                        drafts = tuple(proposed)
                        k_eff = len(drafts)
            plan.work.append(RowWork(
                row.slot, req_id, cursor, 1 + k_eff,
                "verify" if k_eff else "decode",
                pos0=row.pred_kv, spec_len=k_eff, drafts=drafts,
                from_prev=row.pend_gen > 0,
            ))
            cursor += 1 + k_eff
            plan.decode_rows += 1
        # prefill chunks fill the remaining budget, FIFO by admission
        for req_id, row in self._rows.items():
            if row.pend_spec or row.pred_decoding:
                continue
            remaining = budget - cursor
            count = min(self.chunk, row.prompt_len - row.pred_pos, remaining)
            if count <= 0:
                continue
            kind = (
                "finish" if row.pred_pos + count >= row.prompt_len
                else "prefill"
            )
            plan.work.append(RowWork(
                row.slot, req_id, cursor, count, kind, pos0=row.pred_pos,
            ))
            cursor += count
            plan.prefill_rows += 1
        plan.tokens_planned = cursor
        return plan

    # -- dispatch ------------------------------------------------------

    def _get_fn(self):
        if self._fn is None:
            from .mixed import make_mixed_fn

            log.info(
                "compiling mixed-step program t_budget=%d chunk=%d slots=%d"
                " width=%d pipeline_depth=%d",
                self.t_budget, self.chunk, self.generator.max_slots,
                self.width, self.depth,
            )
            self._fn = self.generator._aot_wrap(
                f"mixed_t{self.t_budget}_c{self.chunk}_w{self.width}",
                make_mixed_fn(
                    self.generator, self.t_budget, self.chunk,
                    spec_width=self.width,
                ),
            )
        return self._fn

    def _dispatch(self, plan: StepPlan) -> _InFlight:
        """Pack the plan onto the flat token axis and ISSUE the one mixed
        program; commits the returned cache/rng/latest handles and
        returns the in-flight entry WITHOUT syncing — the sampled tokens
        stay on device until ``_commit_oldest`` fetches them (the
        pipelining point: at depth >= 2 the next plan is dispatched
        before this fetch happens)."""
        g = self.generator
        jnp = g._jnp
        t, b = self.t_budget, g.max_slots
        ids = np.zeros((t,), np.int32)
        rows = np.zeros((t,), np.int32)
        pos = np.zeros((t,), np.int32)
        valid = np.zeros((t,), bool)
        in_row = np.zeros((t,), np.int32)
        from_prev = np.zeros((t,), bool)
        q_start = np.zeros((b,), np.int32)
        q_count = np.zeros((b,), np.int32)
        sample_start = np.zeros((b,), np.int32)
        spec_len = np.zeros((b,), np.int32)
        temp = np.zeros((b,), np.float32)
        top_p = np.ones((b,), np.float32)
        kv_len = self._kv_shadow.copy()
        for work in plan.work:
            row = self._rows[work.req_id]
            span = slice(work.start, work.start + work.count)
            if work.kind == "decode":
                # a chained row's input id is the PREVIOUS dispatch's
                # on-device sample: pack a placeholder, the program
                # substitutes its carried latest[slot]
                ids[work.start] = 0 if work.from_prev else row.generated[-1]
                pos[work.start] = work.pos0
                from_prev[work.start] = work.from_prev
            elif work.kind == "verify":
                # committed last token + k prompt-lookup drafts, one
                # contiguous chunk of absolute positions
                ids[span] = [row.generated[-1], *work.drafts]
                pos[span] = np.arange(
                    work.pos0, work.pos0 + work.count, dtype=np.int32
                )
            else:  # prefill / finish
                ids[span] = row.tokens[work.pos0 : work.pos0 + work.count]
                pos[span] = np.arange(
                    work.pos0, work.pos0 + work.count, dtype=np.int32
                )
            rows[span] = work.slot
            valid[span] = True
            in_row[span] = np.arange(work.count, dtype=np.int32)
            q_start[work.slot] = work.start
            q_count[work.slot] = work.count
            # first sampled position: the last NON-draft token (a verify
            # row samples it and every draft after it)
            sample_start[work.slot] = (
                work.start + work.count - 1 - work.spec_len
            )
            spec_len[work.slot] = work.spec_len
            # optimistic: every draft accepted; the program corrects the
            # committed lengths on device (kv_len - (spec_len - accept))
            kv_len[work.slot] = work.pos0 + work.count
            temp[work.slot] = row.params.temperature
            top_p[work.slot] = row.params.top_p
        paged = g.paged_cache
        if self._staged_tables:
            from ...ops.paged_attention import PagedKVCache

            idx = jnp.asarray(
                [slot for slot, _ in self._staged_tables], jnp.int32
            )
            tables = jnp.asarray(
                np.stack([tab for _, tab in self._staged_tables]), jnp.int32
            )
            paged = PagedKVCache(
                k_pages=paged.k_pages, v_pages=paged.v_pages,
                page_table=paged.page_table.at[idx].set(tables),
                lengths=paged.lengths,
            )
            self._staged_tables.clear()
        if self._latest is None:
            self._latest = jnp.zeros((b,), jnp.int32)
        dispatch_t = time.perf_counter()
        new_paged, toks, accept, latest, rng = self._get_fn()(
            g.params, paged,
            jnp.asarray(ids), jnp.asarray(rows), jnp.asarray(pos),
            jnp.asarray(valid), jnp.asarray(in_row),
            jnp.asarray(q_start), jnp.asarray(q_count), jnp.asarray(kv_len),
            self._latest, jnp.asarray(from_prev),
            jnp.asarray(sample_start), jnp.asarray(spec_len),
            g._rng, jnp.asarray(temp), jnp.asarray(top_p),
        )
        g.paged_cache = new_paged
        g._rng = rng
        self._latest = latest
        # shadow holds the OPTIMISTIC lengths (all drafts accepted) so
        # the next plan's packing is consistent with pred_kv; a verify
        # commit re-anchors the slot from the row's authoritative state
        # when drafts were rejected
        self._kv_shadow = kv_len
        # NO block/fetch here: the commit side owns the step's one host
        # sync (GL001: host loop code, not jit-reachable).  Record the
        # in-flight deltas planning reads as pred_* until commit.
        for work in plan.work:
            row = self._rows[work.req_id]
            if work.kind == "decode":
                row.pend_gen += 1
            elif work.kind == "verify":
                row.pend_spec = True
            elif work.kind == "finish":
                row.pend_pos += work.count
                row.pend_gen += 1  # the chunk's first sampled token
            else:  # prefill
                row.pend_pos += work.count
        return _InFlight(
            plan=plan, toks=toks, accept=accept, dispatch_t=dispatch_t,
        )

    # -- commit --------------------------------------------------------

    def _release_row(self, row: _Row) -> None:
        """Recycle the row's slot + pages NOW.  The freed pages may be
        granted to a new row this very step: the dead row's stale page
        table entries are never read again (its shadow kv length is 0,
        so the ragged kernel walks zero pages) and are overwritten by
        staging at the slot's next admission — no trash-page indirection
        needed, unlike the wave engine's always-dispatch-all-slots
        decode block."""
        g = self.generator
        if self._kvstore is not None and row.cached_hashes:
            # drop the row's references on shared/donated blocks (the
            # pages themselves stay with the store until LRU eviction)
            self._kvstore.release(row.cached_hashes)
            row.cached_hashes = []
        g.allocator.release(row.pages)
        g.slots[row.slot] = _Slot()
        self._kv_shadow[row.slot] = 0
        self._rows.pop(row.req_id, None)
        self.metrics.incr("sched_recycled_slot")

    def _finish(self, row: _Row, reason: str) -> GenerationResult:
        g = self.generator
        eos = g.tokenizer.eos_id
        ids = [t for t in row.generated if t != eos]
        if reason == "length" and row.params.deadline_clamped:
            reason = "deadline"
        elif reason == "length" and row.params.degraded:
            # overload-truncated depth, not a deadline miss: the ladder
            # reduced max_tokens, so hitting it IS the degraded outcome
            reason = "degraded"
        # decode wall from the step clock's monotonic cumulative, not a
        # wall-clock delta: the SAME records /metrics and black-box dumps
        # carry, so the span and the step timeline cannot disagree
        decode_ms = 0.0
        if row.started:
            decode_ms = max(
                0.0, g.step_clock.decode_cum_ms - row.decode_cum0
            )
        result = GenerationResult(
            text=g.tokenizer.decode(ids),
            token_ids=ids,
            prompt_tokens=row.prompt_len,
            completion_tokens=len(ids),
            finish_reason=reason,
            prefill_ms=row.prefill_ms,
            decode_ms=decode_ms,
            queue_wait_ms=row.queue_wait_ms,
        )
        self._release_row(row)
        return result

    def _commit_oldest(self, outcomes: list[StepOutcome]) -> None:
        """Fetch + commit the oldest in-flight dispatch: the step's ONE
        host sync.  Step-clock record lands BEFORE the row commits — a
        prompt completing this step then stamps decode_cum0 with this
        step already counted, so its decode window is exactly the steps
        it decoded in."""
        g = self.generator
        entry = self._inflight.popleft()
        plan = entry.plan
        # the sync was always here (np.asarray); block_until_ready in
        # front only SPLITS it into device compute vs token-id transfer
        # — no new sync point (GL001: host loop code, not jit-reachable)
        try:
            entry.toks.block_until_ready()
        except AttributeError:
            pass  # already a host array (fake-jax tests)
        t_ready = time.perf_counter()
        toks = np.asarray(entry.toks)
        accept = np.asarray(entry.accept)
        if self._pending_offload:
            # the step just paid its host sync: piggyback the offload
            # fetches on it (device→host page copies overlap the token
            # readback window instead of opening a new sync point)
            self._drain_offload()
        if self._pending_mirror:
            self._drain_mirror()
        fetch_t = time.perf_counter()
        self._host_syncs += 1
        device_ms = max(0.0, (t_ready - entry.dispatch_t) * 1e3)
        xfer_ms = max(0.0, (fetch_t - t_ready) * 1e3)
        if plan.decode_rows and plan.prefill_rows:
            kind = "mixed"
        elif plan.decode_rows:
            kind = "decode"
        else:
            kind = "prefill"
        # prospective accepted-token count so MFU attribution stays
        # honest under speculation: a verify row lands accept+1 tokens,
        # not the q_count it was billed for (voided rows land zero)
        accepted = 0
        for work in plan.work:
            if work.req_id not in self._rows:
                continue
            if work.kind == "verify":
                accepted += int(accept[work.slot]) + 1
            elif work.kind in ("decode", "finish"):
                accepted += 1
        g.step_clock.observe(
            kind=kind,
            tokens=plan.tokens_planned,
            slots=entry.held_rows,
            host_gap_ms=g.step_clock.host_gap_ms(entry.dispatch_t),
            device_ms=device_ms,
            sample_xfer_ms=xfer_ms,
            commit_t=fetch_t,
            accepted=accepted,
            cached_tokens=(
                plan.cached_tokens if self._kvstore is not None else None
            ),
        )
        elapsed_ms = (fetch_t - entry.started) * 1e3
        outcomes.extend(self._commit(plan, toks, accept, elapsed_ms))
        if plan.decode_rows and not plan.prefill_rows:
            # wall time per pure-decode round only: the admission
            # roofline reads p50(decode_step) as seconds-per-token
            # (decode_token_estimate_s), and a mixed step's wall includes
            # up to `chunk` prefill tokens' compute — folding that in
            # would make deadline clamping over-truncate every admission
            self.metrics.record("decode_step", elapsed_ms)

    def _push_token(self, row: _Row, token: int) -> Optional[str]:
        """Append one committed token; returns the finish reason when
        the row just reached a terminal state."""
        g = self.generator
        eos = g.tokenizer.eos_id
        row.generated.append(token)
        if row.params.stop_on_eos and eos is not None and token == eos:
            return "stop"
        if len(row.generated) >= row.params.max_tokens:
            return "length"
        if row.kv_len + 1 >= g.max_seq:
            # the NEXT decode token would write past the sequence cap
            return "length"
        return None

    def _commit(
        self, plan: StepPlan, toks: np.ndarray, accept: np.ndarray,
        elapsed_ms: float,
    ) -> list[StepOutcome]:
        outcomes: list[StepOutcome] = []
        g = self.generator
        # the step's compute is attributed to its rows by token share —
        # good enough for the prefill/decode split the spans surface
        share = elapsed_ms / max(1, plan.tokens_planned)
        for work in plan.work:
            row = self._rows.get(work.req_id)
            if row is None:
                # cancelled/finished between dispatch and commit: the
                # prediction this work was planned from is void.  Slot
                # and pages were reclaimed at release; the stale KV
                # writes land in pages whose next owner overwrites its
                # own positions before reading them.
                self.metrics.incr("sched_pipeline_voided")
                continue
            finished: Optional[str] = None
            if work.kind in ("prefill", "finish"):
                row.pos += work.count
                row.pend_pos -= work.count
                row.prefill_ms += share * work.count
                if not row.decoding:
                    # mid-prompt chunk: more prefill next step
                    if not row.chunked:
                        row.chunked = True
                        self.metrics.incr("sched_chunked_prefill")
                    continue
                # prompt completed THIS step: the sampled token is the
                # row's first generated token (wave-engine semantics:
                # the prefill-sampled token counts toward max_tokens)
                row.started = time.perf_counter()
                row.decode_cum0 = g.step_clock.decode_cum_ms
                row.pend_gen -= 1
                row.generated = []
                if self._kvstore is not None:
                    # the prompt's KV is complete and immutable: donate
                    # its full blocks' pages to the prefix cache
                    self._register_row_blocks(row)
                self.metrics.record("prefill", row.prefill_ms)
                finished = self._push_token(row, int(toks[work.slot, 0]))
                self._decode_committed += 1
            elif work.kind == "decode":
                row.pend_gen -= 1
                finished = self._push_token(row, int(toks[work.slot, 0]))
                self._decode_committed += 1
            else:  # verify
                row.pend_spec = False
                a = int(accept[work.slot])
                self.metrics.incr("spec_rounds")
                self.metrics.incr("spec_proposed", work.spec_len)
                self.metrics.incr("spec_accepted", a)
                for j in range(a + 1):
                    finished = self._push_token(row, int(toks[work.slot, j]))
                    self._decode_committed += 1
                    if finished is not None:
                        break
                if finished is None:
                    # rejected drafts left the shadow optimistic: re-
                    # anchor the slot to the row's authoritative length
                    # so the next dispatch packs true positions
                    self._kv_shadow[row.slot] = row.kv_len
            if finished is not None:
                outcomes.append(
                    StepOutcome(work.req_id, result=self._finish(row, finished))
                )
            elif (
                self.partial_hook is not None
                and row.decoding
                and row.generated
            ):
                # list COPY: the hook crosses into the event-loop thread
                self.partial_hook(row.req_id, list(row.generated))
        return outcomes
