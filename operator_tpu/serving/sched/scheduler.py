"""The continuous-batching scheduler: schedule → dispatch → commit.

Replaces the wave engine's implicit phase machinery (batched prefill
dispatches + fixed decode blocks, serving/engine.py) with an explicit
per-step loop over ONE ragged mixed-phase program:

- **schedule** (:meth:`Scheduler._schedule`) — form this step's ragged
  wave: every decode row contributes its one next token, every prefill
  row contributes its next chunk (Sarathi-style: at most ``chunk``
  tokens, so a prompt storm stalls in-flight decodes for at most one
  chunk's compute per step), and queued requests are admitted into the
  RUNNING wave the moment a slot + pages free up — token-level
  admission, no block boundary, no admission window;
- **dispatch** (:meth:`Scheduler._dispatch`) — pack the wave onto the
  flat token axis and run the one compiled mixed program
  (``sched/mixed.py`` + ``ops/ragged_attention.py``);
- **commit** (:meth:`Scheduler._commit`) — fetch the step's sampled
  tokens (the ONE host sync), advance rows, and recycle a finished
  row's slot and KV pages THIS step — not ``decode_block - 1`` junk
  tokens later — so the next step's admission can reuse them.

The scheduler is synchronous and single-threaded by design (the
``BatchedGenerator`` discipline: the ServingEngine serialises calls on
its decode worker); it owns the host-side row state and drives the
generator's page allocator, slot table and paged cache.  Deadline
policy, prompt truncation and the chaos seam are the generator's own
(``AdmissionMixin`` / ``fault_plan``) so wave and continuous modes can
never diverge on admission semantics.

Counters (docs/METRICS.md): ``podmortem_sched_admitted_midwave_total``,
``podmortem_sched_chunked_prefill_total``,
``podmortem_sched_recycled_slot_total``,
``podmortem_sched_stall_free_step_total``,
``podmortem_sched_stall_step_total``.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import time
from collections import deque
from typing import Any, Optional

import numpy as np

from ..types import (
    DeadlineExceeded,
    GenerationResult,
    OversizedRequest,
    SamplingParams,
    _Slot,
    pages_needed,
    prompt_budget,
)
from .types import RowWork, StepOutcome, StepPlan, _Row

log = logging.getLogger(__name__)

__all__ = ["Scheduler"]


class Scheduler:
    """Continuous-batching scheduler over a paged :class:`BatchedGenerator`.

    Requires paged KV and no mesh (the mixed program has no SPMD rule
    yet); guided decoding and LoRA requests are refused at submit — the
    ServingEngine routes them to the wave path or fails them loudly.
    """

    def __init__(
        self,
        generator: Any,
        *,
        chunk: int = 64,
        token_budget: int = 0,
    ) -> None:
        if not getattr(generator, "paged", False):
            raise ValueError("the continuous scheduler requires paged KV")
        if getattr(generator, "mesh", None) is not None:
            raise ValueError(
                "the continuous scheduler does not support mesh sharding yet"
            )
        self.generator = generator
        self.chunk = max(1, min(chunk, generator.max_seq))
        self.t_budget = token_budget or max(self.chunk, generator.max_slots)
        if self.t_budget < generator.max_slots:
            # a full decode batch must always fit one step, or decode
            # rows would be starved by construction
            raise ValueError(
                f"sched token_budget={self.t_budget} < max_slots="
                f"{generator.max_slots}: a full decode batch would not fit"
            )
        if self.chunk > self.t_budget:
            raise ValueError(
                f"sched chunk={self.chunk} > token_budget={self.t_budget}"
            )
        self.metrics = generator.metrics
        #: ``hook(req_id, token_ids_so_far)`` after each step for rows
        #: still generating — the streaming feed (ServingEngine marshals
        #: it onto the event loop).  Called from the decode worker.
        self.partial_hook: Optional[Any] = None
        # (req_id, tokens, params, submitted, priority) — admission order
        # is priority class first, then earliest deadline (EDF) within a
        # class, then FIFO (_edf_head)
        self._queue: deque = deque()
        self._rows: dict[int, _Row] = {}  # req_id -> row, insertion order
        self._next_req = itertools.count(1)
        self._kv_shadow = np.zeros((generator.max_slots,), np.int32)
        self._staged_tables: list[tuple[int, np.ndarray]] = []
        self._fn = None
        # host-side stats the bench reads (stats())
        self.steps = 0
        self.occupancy_sum = 0.0
        self.stall_steps = 0
        #: set to a list to record every step's ``StepPlan.trace()`` —
        #: the determinism test replays a fixed arrival trace and
        #: asserts the schedule is byte-identical
        self.plan_log: Optional[list] = None
        # step-clock stamps _dispatch leaves for step() to observe
        # (serving/perf.py): dispatch start, device/xfer split, fetch end
        self._dispatch_t = 0.0
        self._device_ms = 0.0
        self._xfer_ms = 0.0
        self._fetch_t = 0.0

    # ------------------------------------------------------------------
    # submit side
    # ------------------------------------------------------------------

    def enqueue(
        self,
        prompt: str,
        params: Optional[SamplingParams] = None,
        *,
        submitted: Optional[float] = None,
        priority: int = 0,
    ) -> int:
        """Tokenise + queue one request; returns its req id.  Raises
        :class:`OversizedRequest` when the request can never fit the KV
        pool, ``ValueError`` for features the mixed program does not
        serve (guided decoding, LoRA).  ``submitted`` carries the
        caller's original perf_counter submit stamp (ServingEngine), so
        queue wait covers the engine handoff too, not just this queue.
        ``priority`` orders admission (higher class first); WITHIN a
        class the queue is earliest-deadline-first, so an urgent late
        arrival overtakes an earlier request with slack (_edf_head)."""
        g = self.generator
        params = params or SamplingParams()
        if params.guided_choice is not None or params.guided_regex is not None:
            raise ValueError(
                "guided decoding is not supported by the continuous "
                "scheduler (sched_mode=continuous); use the wave engine"
            )
        if params.adapter is not None:
            raise ValueError(
                "LoRA adapters are not supported by the continuous "
                "scheduler (sched_mode=continuous); use the wave engine"
            )
        ids = g.tokenizer.encode(prompt)
        # same truncation budget + middle-drop as the wave path's admit()
        tokens = g._truncate_prompt(
            ids, prompt_budget(g.max_seq, params.max_tokens)
        )
        pool = g.allocator.num_pages - 1 - g.prefix_held_pages
        if self._pages_needed(tokens, params) > pool:
            raise OversizedRequest(
                f"request needs {self._pages_needed(tokens, params)} KV "
                f"pages, cache holds {pool}"
            )
        req_id = next(self._next_req)
        self._queue.append((
            req_id, tokens, params,
            submitted if submitted is not None else time.perf_counter(),
            priority,
        ))
        return req_id

    def cancel(self, req_id: int) -> bool:
        """Drop a queued request or reclaim a live row's slot/pages now."""
        for i, entry in enumerate(self._queue):
            if entry[0] == req_id:
                del self._queue[i]
                return True
        row = self._rows.get(req_id)
        if row is None:
            return False
        self._release_row(row)
        return True

    @property
    def num_active(self) -> int:
        return len(self._rows)

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def total_work(self) -> int:
        return len(self._rows) + len(self._queue)

    def stats(self) -> dict:
        """Step-level occupancy/stall stats (bench.py reporting)."""
        return {
            "steps": self.steps,
            "batch_occupancy_avg": round(
                self.occupancy_sum / self.steps, 4
            ) if self.steps else None,
            "decode_stall_steps": self.stall_steps,
            "admitted_midwave": self.metrics.counter("sched_admitted_midwave"),
            "chunked_prefills": self.metrics.counter("sched_chunked_prefill"),
            "recycled_slots": self.metrics.counter("sched_recycled_slot"),
        }

    def reset(self) -> None:
        """Drop every row and queued request (the supervised-restart /
        recovery path: the generator rebuilds device state separately
        and the engine has already collected the in-flight futures)."""
        self._queue.clear()
        self._rows.clear()
        self._kv_shadow[:] = 0
        self._staged_tables.clear()

    def precompile(self) -> None:
        """Compile the one mixed program before serving (an empty wave
        drives the full trace: the program's shapes are workload-
        independent by construction)."""
        self._dispatch(StepPlan())

    # ------------------------------------------------------------------
    # the loop
    # ------------------------------------------------------------------

    def step(self) -> list[StepOutcome]:
        """One schedule → dispatch → commit round; returns every request
        that reached a terminal state (result or admission error)."""
        g = self.generator
        if g.fault_plan is not None:
            # chaos seam, same site as the wave engine's step so stall /
            # device-error scenarios drive both loops identically
            g.fault_plan.apply("engine.step", active=self.num_active)
        outcomes: list[StepOutcome] = []
        plan = self._schedule(outcomes)
        held_rows = len(self._rows)  # snapshot BEFORE commit recycles
        if self.plan_log is not None:
            self.plan_log.append(plan.trace())
        if not plan.work:
            return outcomes
        started = time.perf_counter()
        with g._annotation(
            "podmortem.sched_step",
            [row.params for row in self._rows.values()],
        ):
            toks = self._dispatch(plan)
        elapsed_ms = (time.perf_counter() - started) * 1e3
        # step-clock record BEFORE commit: a prompt completing this step
        # then stamps decode_cum0 with this step already counted, so its
        # decode window is exactly the steps it decoded in
        if plan.decode_rows and plan.prefill_rows:
            kind = "mixed"
        elif plan.decode_rows:
            kind = "decode"
        else:
            kind = "prefill"
        g.step_clock.observe(
            kind=kind,
            tokens=plan.tokens_planned,
            slots=held_rows,
            host_gap_ms=g.step_clock.host_gap_ms(self._dispatch_t),
            device_ms=self._device_ms,
            sample_xfer_ms=self._xfer_ms,
            commit_t=self._fetch_t,
        )
        outcomes.extend(self._commit(plan, toks, elapsed_ms))
        # step accounting: occupancy is HELD slots over capacity (rows at
        # any phase — the same "slots occupied" definition the wave
        # engine's batch_occupancy stage uses, so bench.py compares like
        # with like); a stall step is one where a decode-ready row got
        # NO token — the schedule never defers decodes while
        # token_budget >= max_slots, so the counter is the proof of the
        # property, not a mechanism
        self.steps += 1
        occupancy = held_rows / g.max_slots
        self.occupancy_sum += occupancy
        self.metrics.record("sched_occupancy", occupancy * 100.0)
        if plan.decode_rows and not plan.prefill_rows:
            # wall time per one-token decode round, PURE decode steps
            # only: the admission roofline reads p50(decode_step) as
            # seconds-per-token (decode_token_estimate_s), and a mixed
            # step's wall includes up to `chunk` prefill tokens' compute
            # — folding that in would inflate the estimate ~chunk-fold
            # and make deadline clamping over-truncate every admission
            self.metrics.record("decode_step", elapsed_ms)
        if plan.deferred_decode:
            self.stall_steps += 1
            self.metrics.incr("sched_stall_step")
        else:
            self.metrics.incr("sched_stall_free_step")
        return outcomes

    # -- schedule ------------------------------------------------------

    def _pages_needed(self, tokens: list, params: SamplingParams) -> int:
        g = self.generator
        return pages_needed(
            len(tokens), params.max_tokens, g.max_seq, g.page_size
        )

    def _sweep_expired(self, outcomes: list[StepOutcome]) -> None:
        """Fail EVERY queued request whose deadline already expired —
        the whole queue, every step, regardless of capacity.  Checking
        only at admission would leave an expired caller hanging until a
        slot (and the head's pages) freed, where the wave path's sweep
        fails it on every loop round."""
        if not self._queue:
            return
        now = self.generator._clock()
        live = deque()
        for entry in self._queue:
            params = entry[2]
            if params.deadline is not None and params.deadline <= now:
                self.metrics.incr("admission_deadline_rejected")
                outcomes.append(StepOutcome(entry[0], error=DeadlineExceeded(
                    "deadline expired while queued for admission"
                )))
            else:
                live.append(entry)
        self._queue = live

    def _edf_head(self) -> int:
        """Index of the next request to admit: highest priority class
        first, earliest deadline within the class (EDF), FIFO among
        deadline-free peers.  Deadline-free requests sort AFTER any
        deadline in their class but are never skipped past — admission
        still stops (does not skip ahead) when the chosen head's pages
        don't fit, so a starved large request keeps its turn."""
        best = 0
        best_key = None
        for i, entry in enumerate(self._queue):
            params, priority = entry[2], entry[4]
            deadline = (
                params.deadline if params.deadline is not None
                else float("inf")
            )
            key = (-priority, deadline, i)
            if best_key is None or key < best_key:
                best, best_key = i, key
        return best

    def _admit_queued(self, outcomes: list[StepOutcome]) -> list[int]:
        """Token-level admission: pull queued requests into free slots
        while pages last.  Runs at the top of EVERY step, so an arrival
        joins the running wave at the next step boundary — never waits
        for a decode block or an admission window."""
        g = self.generator
        self._sweep_expired(outcomes)
        admitted: list[int] = []
        while self._queue:
            free = g.free_slots()
            if not free:
                break
            head = self._edf_head()
            req_id, tokens, params, submitted, _ = self._queue[head]
            clamped, outcome = g.deadline_policy(params)
            if outcome == "rejected":
                # expired between the check above and the policy's clock
                # read: minimal one-token clamp, same as the wave path's
                # _deadline_clamp_wave
                clamped = dataclasses.replace(
                    params, max_tokens=1, deadline_clamped=True
                )
                outcome = "truncated"
            if outcome == "truncated":
                self.metrics.incr("admission_deadline_truncated")
            need = self._pages_needed(tokens, clamped)
            if need > g.allocator.available:
                break  # backpressure: decode frees pages, retry next step
            del self._queue[head]
            grant = g.allocator.allocate(need)
            slot = free[0]
            row = _Row(
                req_id=req_id, slot=slot, tokens=tokens, params=clamped,
                pages=grant, submitted=submitted,
            )
            self._rows[req_id] = row
            # measured submit -> admission wall: the span's queue_wait_ms
            # and the sched_queue_wait gauge read the SAME number
            row.queue_wait_ms = max(
                0.0, (time.perf_counter() - submitted) * 1e3
            )
            self.metrics.record("sched_queue_wait", row.queue_wait_ms)
            # mirror into the generator's slot table so free_slots /
            # num_active / the supervisor's leak audit see one truth
            slot_obj = _Slot()
            slot_obj.active = True
            slot_obj.prompt_len = len(tokens)
            slot_obj.params = clamped
            slot_obj.pages = grant
            g.slots[slot] = slot_obj
            # stage the row's page table for the next dispatch
            row_table = np.zeros((g.pages_per_seq,), np.int32)
            row_table[: len(grant)] = grant
            self._staged_tables.append((slot, row_table))
            admitted.append(req_id)
            if len(self._rows) > 1:
                self.metrics.incr("sched_admitted_midwave")
        return admitted

    def _schedule(self, outcomes: list[StepOutcome]) -> StepPlan:
        plan = StepPlan()
        plan.admitted = self._admit_queued(outcomes)
        budget = self.t_budget
        cursor = 0
        # decode rows first — one token each, NEVER deferred (the whole
        # point: a prefill storm cannot starve an in-flight decode)
        for req_id, row in self._rows.items():
            if not row.decoding:
                continue
            if cursor >= budget:  # unreachable while budget >= max_slots
                plan.deferred_decode += 1
                continue
            plan.work.append(RowWork(row.slot, req_id, cursor, 1, "decode"))
            cursor += 1
            plan.decode_rows += 1
        # prefill chunks fill the remaining budget, FIFO by admission
        for req_id, row in self._rows.items():
            if row.decoding:
                continue
            remaining = budget - cursor
            count = min(self.chunk, row.prompt_len - row.pos, remaining)
            if count <= 0:
                continue
            kind = (
                "finish" if row.pos + count >= row.prompt_len else "prefill"
            )
            plan.work.append(RowWork(row.slot, req_id, cursor, count, kind))
            cursor += count
            plan.prefill_rows += 1
        plan.tokens_planned = cursor
        return plan

    # -- dispatch ------------------------------------------------------

    def _get_fn(self):
        if self._fn is None:
            from .mixed import make_mixed_fn

            log.info(
                "compiling mixed-step program t_budget=%d chunk=%d slots=%d",
                self.t_budget, self.chunk, self.generator.max_slots,
            )
            self._fn = self.generator._aot_wrap(
                f"mixed_t{self.t_budget}_c{self.chunk}",
                make_mixed_fn(self.generator, self.t_budget, self.chunk),
            )
        return self._fn

    def _dispatch(self, plan: StepPlan) -> np.ndarray:
        """Pack the plan onto the flat token axis and run the one mixed
        program; commits the returned cache/rng and returns the sampled
        tokens ([B] host array — the step's ONE device sync)."""
        g = self.generator
        jnp = g._jnp
        t, b = self.t_budget, g.max_slots
        ids = np.zeros((t,), np.int32)
        rows = np.zeros((t,), np.int32)
        pos = np.zeros((t,), np.int32)
        valid = np.zeros((t,), bool)
        in_row = np.zeros((t,), np.int32)
        q_start = np.zeros((b,), np.int32)
        q_count = np.zeros((b,), np.int32)
        temp = np.zeros((b,), np.float32)
        top_p = np.ones((b,), np.float32)
        kv_len = self._kv_shadow.copy()
        for work in plan.work:
            row = self._rows[work.req_id]
            span = slice(work.start, work.start + work.count)
            if row.decoding:
                ids[work.start] = row.generated[-1]
                pos[work.start] = row.kv_len
            else:
                ids[span] = row.tokens[row.pos : row.pos + work.count]
                pos[span] = np.arange(
                    row.pos, row.pos + work.count, dtype=np.int32
                )
            rows[span] = work.slot
            valid[span] = True
            in_row[span] = np.arange(work.count, dtype=np.int32)
            q_start[work.slot] = work.start
            q_count[work.slot] = work.count
            kv_len[work.slot] = int(pos[work.start + work.count - 1]) + 1
            temp[work.slot] = row.params.temperature
            top_p[work.slot] = row.params.top_p
        paged = g.paged_cache
        if self._staged_tables:
            from ...ops.paged_attention import PagedKVCache

            idx = jnp.asarray(
                [slot for slot, _ in self._staged_tables], jnp.int32
            )
            tables = jnp.asarray(
                np.stack([tab for _, tab in self._staged_tables]), jnp.int32
            )
            paged = PagedKVCache(
                k_pages=paged.k_pages, v_pages=paged.v_pages,
                page_table=paged.page_table.at[idx].set(tables),
                lengths=paged.lengths,
            )
            self._staged_tables.clear()
        self._dispatch_t = time.perf_counter()
        new_paged, next_tokens, rng = self._get_fn()(
            g.params, paged,
            jnp.asarray(ids), jnp.asarray(rows), jnp.asarray(pos),
            jnp.asarray(valid), jnp.asarray(in_row),
            jnp.asarray(q_start), jnp.asarray(q_count), jnp.asarray(kv_len),
            g._rng, jnp.asarray(temp), jnp.asarray(top_p),
        )
        g.paged_cache = new_paged
        g._rng = rng
        self._kv_shadow = kv_len
        # the step's ONE host sync was always here (np.asarray); the
        # block_until_ready in front only SPLITS it into device compute
        # vs token-id transfer — no new sync point (GL001: host loop
        # code, not jit-reachable)
        try:
            next_tokens.block_until_ready()
        except AttributeError:
            pass  # already a host array (fake-jax tests)
        t_ready = time.perf_counter()
        out = np.asarray(next_tokens)
        self._fetch_t = time.perf_counter()
        self._device_ms = max(0.0, (t_ready - self._dispatch_t) * 1e3)
        self._xfer_ms = max(0.0, (self._fetch_t - t_ready) * 1e3)
        return out

    # -- commit --------------------------------------------------------

    def _release_row(self, row: _Row) -> None:
        """Recycle the row's slot + pages NOW.  The freed pages may be
        granted to a new row this very step: the dead row's stale page
        table entries are never read again (its shadow kv length is 0,
        so the ragged kernel walks zero pages) and are overwritten by
        staging at the slot's next admission — no trash-page indirection
        needed, unlike the wave engine's always-dispatch-all-slots
        decode block."""
        g = self.generator
        g.allocator.release(row.pages)
        g.slots[row.slot] = _Slot()
        self._kv_shadow[row.slot] = 0
        self._rows.pop(row.req_id, None)
        self.metrics.incr("sched_recycled_slot")

    def _finish(self, row: _Row, reason: str) -> GenerationResult:
        g = self.generator
        eos = g.tokenizer.eos_id
        ids = [t for t in row.generated if t != eos]
        if reason == "length" and row.params.deadline_clamped:
            reason = "deadline"
        # decode wall from the step clock's monotonic cumulative, not a
        # wall-clock delta: the SAME records /metrics and black-box dumps
        # carry, so the span and the step timeline cannot disagree
        decode_ms = 0.0
        if row.started:
            decode_ms = max(
                0.0, g.step_clock.decode_cum_ms - row.decode_cum0
            )
        result = GenerationResult(
            text=g.tokenizer.decode(ids),
            token_ids=ids,
            prompt_tokens=row.prompt_len,
            completion_tokens=len(ids),
            finish_reason=reason,
            prefill_ms=row.prefill_ms,
            decode_ms=decode_ms,
            queue_wait_ms=row.queue_wait_ms,
        )
        self._release_row(row)
        return result

    def _commit(
        self, plan: StepPlan, toks: np.ndarray, elapsed_ms: float
    ) -> list[StepOutcome]:
        outcomes: list[StepOutcome] = []
        g = self.generator
        eos = g.tokenizer.eos_id
        # the step's compute is attributed to its rows by token share —
        # good enough for the prefill/decode split the spans surface
        share = elapsed_ms / max(1, plan.tokens_planned)
        for work in plan.work:
            row = self._rows.get(work.req_id)
            if row is None:
                continue  # cancelled between dispatch and commit
            token = int(toks[work.slot])
            if not row.decoding:
                row.pos += work.count
                row.prefill_ms += share * work.count
                if not row.decoding:
                    # mid-prompt chunk: more prefill next step
                    if not row.chunked:
                        row.chunked = True
                        self.metrics.incr("sched_chunked_prefill")
                    continue
                # prompt completed THIS step: the sampled token is the
                # row's first generated token (wave-engine semantics:
                # the prefill-sampled token counts toward max_tokens)
                row.started = time.perf_counter()
                row.decode_cum0 = g.step_clock.decode_cum_ms
                row.generated = [token]
                self.metrics.record("prefill", row.prefill_ms)
            else:
                row.generated.append(token)
            finished = None
            if row.params.stop_on_eos and eos is not None and token == eos:
                finished = "stop"
            elif len(row.generated) >= row.params.max_tokens:
                finished = "length"
            elif row.kv_len + 1 >= g.max_seq:
                # the NEXT decode token would write past the sequence
                # cap; synchronous stepping needs a one-token margin only
                finished = "length"
            if finished is not None:
                outcomes.append(
                    StepOutcome(work.req_id, result=self._finish(row, finished))
                )
            elif (
                self.partial_hook is not None
                and row.decoding
                and row.generated
            ):
                # list COPY: the hook crosses into the event-loop thread
                self.partial_hook(row.req_id, list(row.generated))
        return outcomes
