"""Guided (constrained) decoding: choice automata as device state.

``SamplingParams(guided_choice=(...))`` restricts a request's output to
one of the given strings.  The constraint is a token-trie automaton whose
transition table rides the decode scan as DEVICE state — the TPU-native
shape for constrained decoding: logits are masked and the automaton steps
inside the jitted decode block, so the engine's no-host-sync decode
design (decode_block, decode-ahead pipelining) is untouched.

Mechanics:

- each choice is tokenized (its canonical encoding; no BOS) and inserted
  into a trie; ``transition[state, token]`` is the child state or -1
  (forbidden).  Completing a choice lands in a state where only EOS is
  allowed, so generation ends exactly at the choice boundary.
- automaton 0 is the IDENTITY (every token allowed, state stays 0):
  unconstrained slots ride the same program with zero effect.
- per-slot ``(automaton, state)`` vectors live on device; the sampler
  masks ``logits`` with the gathered transition row and the sampled
  token indexes the next state.  Shapes are bucketed (automata count,
  state count) so XLA compiles a handful of guided programs.

``guided_regex`` rides the same machinery with a DFA in place of the
trie (serving/regex_dfa.py).  Both work on sharded meshes (the tables
are committed replicated once, not re-broadcast per block) and with
chunked prefill (the automaton activates when the final chunk admits
the slot).  The engine enforces ``eos_id`` support at SUBMIT time.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..models.tokenizer import Tokenizer


@dataclass(frozen=True)
class ChoiceAutomaton:
    """Token-trie over a tuple of choice strings."""

    transition: np.ndarray  # [num_states, vocab] int32; -1 = forbidden
    num_states: int
    choices: tuple


def build_choice_automaton(
    choices: tuple, tokenizer: Tokenizer, vocab_size: int
) -> ChoiceAutomaton:
    """Trie over each choice's canonical token sequence.

    State 0 is the start.  After a full choice only EOS is allowed
    (self-looping, so pipelined junk steps past EOS stay trapped).
    """
    if not choices:
        raise ValueError("guided_choice needs at least one choice")
    eos = tokenizer.eos_id
    if eos is None or not 0 <= int(eos) < vocab_size:
        raise ValueError("guided decoding needs a tokenizer with an eos id")
    paths = []
    for choice in choices:
        ids = tokenizer.encode(choice, add_bos=False)
        if not ids:
            raise ValueError(f"choice {choice!r} tokenizes to nothing")
        if any(not 0 <= t < vocab_size for t in ids):
            raise ValueError(f"choice {choice!r} has out-of-vocab tokens")
        paths.append(ids)

    # trie construction over dicts, then flattened to the table
    nodes: list[dict] = [{}]  # state -> {token: child_state}
    accept: list[bool] = [False]
    for ids in paths:
        state = 0
        for token in ids:
            child = nodes[state].get(token)
            if child is None:
                nodes.append({})
                accept.append(False)
                child = len(nodes) - 1
                nodes[state][token] = child
            state = child
        accept[state] = True

    num_states = len(nodes)
    # same product cap as the regex path (regex_dfa.py): the table is
    # [num_states, vocab] int32 and gets padded/stacked again by the
    # engine — an unbounded choice set against a 150k vocab would
    # allocate gigabytes on the host and upload them to device
    if num_states * vocab_size > 16_000_000:
        raise ValueError(
            f"guided_choice automaton table would be {num_states} states x "
            f"{vocab_size} vocab = {num_states * vocab_size} entries, above "
            f"the 16M cap — use fewer or shorter choices"
        )
    transition = np.full((num_states, vocab_size), -1, np.int32)
    for state, edges in enumerate(nodes):
        for token, child in edges.items():
            transition[state, token] = child
        if accept[state]:
            transition[state, eos] = state  # EOS-only, self-looping
    return ChoiceAutomaton(
        transition=transition, num_states=num_states, choices=tuple(choices)
    )


def identity_automaton(vocab_size: int) -> ChoiceAutomaton:
    """Automaton 0: everything allowed, state stays 0 (unconstrained)."""
    return ChoiceAutomaton(
        transition=np.zeros((1, vocab_size), np.int32),
        num_states=1,
        choices=(),
    )


def stack_automata(
    automata: list, vocab_size: int, *, state_pad: int
) -> np.ndarray:
    """[n_automata, state_pad, vocab] with -1 padding rows (unreachable)."""
    out = np.full((len(automata), state_pad, vocab_size), -1, np.int32)
    for i, automaton in enumerate(automata):
        out[i, : automaton.num_states] = automaton.transition
    return out


__all__ = [
    "ChoiceAutomaton",
    "build_choice_automaton",
    "identity_automaton",
    "stack_automata",
]
