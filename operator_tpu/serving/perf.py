"""Analytic per-token flops model + the serving engines' step clock.

The step clock (obs/steptrace.py) records *where* a decode step's wall
time goes; this module turns those records into *how fast the chip ran*:
an analytic flops-per-token model derived from the model config alone
(no device counters needed), a per-dtype peak-TFLOPs table, and the
:class:`StepClock` both engine loops record through.

Everything here is host-side orchestration: nothing is reachable from a
``jax.jit``/``pallas_call`` entry point, and the clock's only device
interaction is timing a sync the loop was about to perform anyway
(GL001 verifies this in CI — the narrow graftlint pass covers this
module and the instrumented loops).
"""

from __future__ import annotations

import os
import time
from typing import Any, Optional

from ..obs.steptrace import StepRecord, StepRing, attribution
from ..utils.timing import MetricsRegistry

#: per-dtype dense peak, TFLOP/s, for a single v5e chip (the deploy
#: target; override with PEAK_TFLOPS / BENCH_PEAK_TFLOPS for other
#: generations).  int8 runs through the MXU at twice the bf16 rate.
_PEAK_TFLOPS = {
    "bf16": 197.0,
    "bfloat16": 197.0,
    "int8": 394.0,
    "float32": 98.5,
    "f32": 98.5,
}


def matmul_param_count(config: Any) -> int:
    """Weights that participate in a matmul during one token's forward
    pass, analytically from the config (attention projections + MLP per
    layer, plus the LM head — which multiplies even when tied to the
    embedding).  Norm scales and the embedding GATHER move no MACs, so
    they are excluded; ``param_count(params)`` counts them and is the
    storage number, not the compute number."""
    h = config.hidden_size
    q = config.num_heads * config.head_dim
    kv = config.num_kv_heads * config.head_dim
    attn = h * q + 2 * h * kv + q * h  # wq, wk, wv, wo
    mlp = 3 * h * config.intermediate_size  # gate, up, down
    return config.num_layers * (attn + mlp) + h * config.vocab_size


def flops_per_token(config: Any, dtype: str = "bf16") -> float:
    """~2 FLOPs per matmul weight per generated token (multiply +
    accumulate; attention-score flops are negligible at serving sequence
    lengths).  ``dtype`` does not change the MAC count — it selects the
    peak (``peak_tflops``) the achieved number is divided by."""
    del dtype  # the MAC count is dtype-independent; kept for the API shape
    return 2.0 * matmul_param_count(config)


def peak_tflops(dtype: str = "bf16") -> float:
    """Chip peak for the serving dtype; ``PEAK_TFLOPS`` (or the bench's
    ``BENCH_PEAK_TFLOPS``) overrides for non-v5e hardware."""
    env = os.environ.get("PEAK_TFLOPS") or os.environ.get("BENCH_PEAK_TFLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    return _PEAK_TFLOPS.get(str(dtype).lower(), _PEAK_TFLOPS["bf16"])


class StepClock:
    """Per-step recorder both serving loops write through.

    Owns the bounded :class:`StepRing`, stamps host-gap boundaries
    (previous commit → next dispatch), attaches the model's analytic
    flops/token so every record carries its achieved MFU, and feeds the
    step histograms (``podmortem_step_duration_milliseconds`` /
    ``podmortem_step_host_gap_milliseconds``).  All methods run on the
    decode worker thread; reads (summary, ring) are lock-protected by
    the ring itself."""

    def __init__(
        self,
        *,
        capacity: Optional[int] = None,
        flops_per_token: Optional[float] = None,
        peak_tflops: Optional[float] = None,
        max_slots: int = 1,
        metrics: Optional[MetricsRegistry] = None,
    ) -> None:
        self.ring = StepRing(capacity)
        self.flops_per_token = flops_per_token
        self.peak_tflops = peak_tflops
        self.max_slots = max(1, int(max_slots))
        self.metrics = metrics
        #: end of the previous step's commit (perf_counter); None right
        #: after construction/reset — the first step has no host gap
        self._last_commit: Optional[float] = None

    def host_gap_ms(self, dispatch_t: float) -> float:
        """Host think-time between the previous commit and ``dispatch_t``
        (0.0 for the first step after construction or reset)."""
        if self._last_commit is None:
            return 0.0
        return max(0.0, (dispatch_t - self._last_commit) * 1e3)

    def observe(
        self,
        *,
        kind: str,
        tokens: int,
        slots: int,
        host_gap_ms: float,
        device_ms: float,
        sample_xfer_ms: float,
        commit_t: Optional[float] = None,
        accepted: Optional[int] = None,
        cached_tokens: Optional[int] = None,
    ) -> StepRecord:
        """Record one step and stamp its commit as the next step's
        host-gap origin.  ``accepted`` is the step's COMMITTED generated
        token count when it differs from the billed ``tokens``
        (speculation verify rows, pipelined voided work); MFU stays
        computed on billed tokens — the compute really ran.
        ``cached_tokens`` is the prompt-token count rows admitted at this
        step reused from the prefix cache — spared compute, so it never
        enters ``tokens`` and MFU stays honest."""
        total = max(0.0, host_gap_ms) + max(0.0, device_ms) + max(0.0, sample_xfer_ms)
        mfu = None
        if (
            self.flops_per_token
            and self.peak_tflops
            and total > 0
            and tokens
            and kind in ("decode", "mixed")
        ):
            achieved = tokens * self.flops_per_token / (total / 1e3) / 1e12
            mfu = achieved / self.peak_tflops
        record = self.ring.append(
            kind=kind,
            tokens=tokens,
            slots=slots,
            occupancy=min(1.0, slots / self.max_slots),
            host_gap_ms=host_gap_ms,
            device_ms=device_ms,
            sample_xfer_ms=sample_xfer_ms,
            mfu=mfu,
            accepted=accepted,
            cached_tokens=cached_tokens,
        )
        self._last_commit = commit_t if commit_t is not None else time.perf_counter()
        if self.metrics is not None:
            self.metrics.observe("step_duration_milliseconds", total)
            self.metrics.observe("step_host_gap_milliseconds", max(0.0, host_gap_ms))
        return record

    @property
    def decode_cum_ms(self) -> float:
        """Monotonic cumulative decode-bearing wall (see StepRing) — the
        eviction-proof base request decode times are derived from."""
        return self.ring.decode_cum_ms

    def summary(self, last: Optional[int] = None) -> dict:
        """Stall-attribution summary (+ measured decode MFU) over the
        ring's current window — what /healthz, /fleet and bench.py's
        ``step_attribution`` block all read."""
        return attribution(
            self.ring.records(last),
            flops_per_token=self.flops_per_token,
            peak_tflops=self.peak_tflops,
        )

    def reset(self) -> None:
        """Forget everything (device-state reset: the old timeline died
        with the old decode state; black-box dumps captured it first)."""
        self.ring.reset()
        self._last_commit = None
