"""Admission policy: how concurrent requests become compiled-program waves.

Split out of serving/engine.py (VERDICT r4 item 8): tokenised-prompt
truncation (middle-drop preserving instructions + evidence), the
shared-prefix wave decision (all-or-nothing — interior shares would
specialise unbounded programs), the dp-aware batch buckets, page granting
with partial-admission backpressure, the batched prefill dispatch itself,
and the warmup program-grid precompile whose whole point is that admission
can never select a program that was not compiled before readiness flipped.

Mixed into :class:`serving.engine.BatchedGenerator`.

With ``sched_mode=continuous`` (serving/sched/, docs/SERVING.md) wave
FORMATION moves behind the scheduler: admission becomes token-level per
step and the batched-prefill dispatch below is not used.  The POLICY
stays here — the scheduler calls :meth:`deadline_policy` and
:meth:`_truncate_prompt`, and shares the budget/page formulas
(``types.prompt_budget`` / ``types.pages_needed``) — so the two modes
cannot diverge on what gets admitted, clamped, or refused.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Sequence

import numpy as np

from ..models.llama import KVCache
from .types import (
    OversizedRequest,
    SamplingParams,
    _bucket,
    _PrefillJob,
    pages_needed,
    prompt_budget,
)

log = logging.getLogger(__name__)


class AdmissionMixin:
    """Wave formation + the warmup grid (see module doc)."""

    # ------------------------------------------------------------------
    # deadline budget (utils/deadline.py): admission is the enforcement
    # point for the decode leg — the one stage whose cost is predictable
    # up front (max_tokens x per-token step time)
    # ------------------------------------------------------------------

    def decode_token_estimate_s(self) -> float:
        """Expected seconds per decoded token: the MEASURED p50 of the
        decode_step stage once any block has run, else the constructor's
        roofline estimate (``roofline_token_s``).  0.0 = unknown — the
        policy then only rejects already-expired requests (it will not
        clamp on a guess it doesn't have)."""
        stats = self.metrics.stage("decode_step")
        if stats.count:
            return stats.p50_ms / 1e3
        return self.roofline_token_s or 0.0

    def deadline_policy(
        self,
        params: SamplingParams,
        *,
        now: "float | None" = None,
        pressure: "float | None" = None,
    ) -> "tuple[SamplingParams, str]":
        """(possibly clamped params, outcome) for one request's budget.

        Outcomes: ``"ok"`` (fits, untouched), ``"truncated"``
        (``max_tokens`` clamped to the roofline fit, ``deadline_clamped``
        set so the finish reason reads "deadline"), ``"degraded"``
        (overload ladder scaled ``max_tokens`` down — degrade-before-
        reject, router/value.py), ``"shed"`` (the ladder dropped the
        request outright: lowest value under storm, class unprotected),
        ``"rejected"`` (the residue cannot fit even one token).  Requests
        without a deadline pass the deadline leg untouched but can still
        be degraded or shed under pressure.

        ``pressure`` is the caller's load signal (queued + running rows):
        when an ``overload_policy`` is wired (serving mixins default to
        None) the ladder may truncate analysis depth BEFORE the deadline
        math, so the clamp sees the already-reduced ask."""
        policy = getattr(self, "overload_policy", None)
        degraded = False
        if (
            policy is not None
            and pressure is not None
            and not params.degraded
        ):
            residual = None
            if params.deadline is not None:
                residual = params.deadline - (
                    self._clock() if now is None else now
                )
            value = policy.model.value(
                slo_class=params.slo_class,
                residual_s=residual,
                recall_p=params.recall_p,
            )
            verdict = policy.decide(
                value, pressure, site="admission",
                request_id=params.trace_tag or "",
            )
            if verdict.action == "shed":
                return params, "shed"
            if verdict.action == "degrade":
                params = dataclasses.replace(
                    params,
                    max_tokens=max(
                        1,
                        int(params.max_tokens * verdict.degrade_tokens_frac),
                    ),
                    degraded=True,
                )
                degraded = True
        ok = "degraded" if degraded else "ok"
        if params.deadline is None:
            return params, ok
        now = self._clock() if now is None else now
        remaining = params.deadline - now
        if remaining <= 0.0:
            return params, "rejected"
        per_token = self.decode_token_estimate_s()
        if per_token <= 0.0:
            return params, ok
        fit = int(remaining / per_token)
        if fit < 1:
            return params, "rejected"
        if fit < params.max_tokens:
            return (
                dataclasses.replace(
                    params, max_tokens=fit, deadline_clamped=True
                ),
                "truncated",
            )
        return params, ok

    def _deadline_clamp_wave(
        self, params_list: "Sequence[SamplingParams]"
    ) -> list[SamplingParams]:
        """Apply the deadline policy to a whole admission wave.  Runs at
        ADMISSION time (after any queue wait eroded the budget), so the
        clamp reflects the true residue.  A request that expired between
        the serve loop's expiry sweep and this call gets the minimal
        one-token clamp instead of failing the co-batched wave — its
        result still carries finish_reason "deadline"."""
        out = []
        for sampling in params_list:
            clamped, outcome = self.deadline_policy(sampling)
            if outcome == "rejected":
                clamped = dataclasses.replace(
                    sampling, max_tokens=1, deadline_clamped=True
                )
                outcome = "truncated"
            if outcome == "truncated":
                self.metrics.incr("admission_deadline_truncated")
            out.append(clamped)
        return out

    def _program_count(self) -> int:
        """Compiled-program cache population (prefill variants + chunked +
        decode) — the precompile coverage metric."""
        decode = int(self._decode_fn is not None) + int(
            self._decode_fn_guided is not None
        )
        return (
            len(self._prefill_fns)
            + len(self._prefix_fns)
            + len(self._chunk_fns)
            + len(self._finish_fns)
            + decode
        )

    def precompile_grid(
        self,
        level: str = "serving",
        *,
        workload_prompts: "Sequence[str] | None" = None,
        workload_params: "SamplingParams | None" = None,
    ) -> dict:
        """Compile every program the admission policy can select BEFORE
        serving: a mid-run XLA compile is an SLO violation, not noise (the
        100/min CPU soak's 5.9 s p99 was exactly three first-encounter
        prefill-bucket compiles of ~2 s each in the first ten seconds).
        The reference has no analogue — its LLM leg is an external REST
        call (AIInterfaceRestClient.java:37-39); a compiled-serving design
        must instead guarantee the program grid is warm when readiness
        flips.

        ``level``:
          - ``"off"``: nothing.
          - ``"serving"``: the unguided grid — plain AND shared-prefix
            prefill for every (n_pad, t_pad) bucket admission can produce
            (driving the chunked job programs wherever ``prefill_chunk``
            makes them the selected path) plus the decode block.  Guided
            programs still compile on the first guided request: guided
            traffic is opt-in per AIProvider CR and its automaton build is
            already off-loop (ensure_guided).
          - ``"full"``: additionally the guided variants of the whole grid
            and the guided decode block.

        ``workload_prompts`` (with ``workload_params``, e.g. the bench
        harness whose prompt set is known up front) restricts the length
        buckets to exactly those the given prompts produce under the REAL
        encode/truncate/prefix pipeline — every wave SIZE stays covered
        (open-loop arrivals form all of them) but chip time is not spent
        compiling length buckets the workload cannot hit.  The bucket
        derivation lives here, next to the admission math it must mirror.

        Every wave runs through the REAL admission path (`_admit_tokens`),
        so bucket selection, page granting, shared-prefix detection, and
        the host-side glue ops all compile exactly as production traffic
        would trigger them.  Waves the KV pool cannot grant are skipped —
        production admission could not form them either — as are waves a
        concurrently-admitted live request leaves too few free slots for.
        All grid slots are cancelled and their pages released afterwards.
        """
        if level not in ("off", "serving", "full"):
            raise ValueError(
                f"warmup grid level {level!r}: expected off/serving/full"
            )
        t0 = time.perf_counter()
        before = self._program_count()
        if level == "off":
            return {"level": level, "programs": 0, "seconds": 0.0}

        vocab = self.config.vocab_size
        filler = 7 % vocab
        prefixes = (
            [list(p["tokens"]) for p in self._prefixes] if self.paged else []
        )
        while any(p[0] == filler for p in prefixes if p):
            filler = (filler + 1) % vocab
        short = 8  # filler rows: only row 0 drives the t_pad bucket
        n_pads = self._admission_n_pads()

        def t_buckets(limit: int) -> list:
            ts, t = [], 64
            while t < min(limit, self.max_seq):
                ts.append(t)
                t *= 2
            ts.append(min(limit if limit >= 64 else 64, self.max_seq))
            return sorted(set(ts))

        plain_ts = t_buckets(self.max_seq - 1)
        # per registered prefix: its suffix t buckets (distinct prefix
        # LENGTHS specialise distinct programs; same-length prefixes share)
        prefix_ts = {
            i: t_buckets(self.max_seq - 1 - len(ptoks))
            for i, ptoks in enumerate(prefixes)
        }
        if workload_prompts is not None:
            # restrict to the buckets THIS workload's prompts produce,
            # derived through the real encode/truncate/prefix pipeline so
            # it can never desync from admission
            if workload_params is None:
                raise ValueError(
                    "workload_prompts requires workload_params: the "
                    "truncation budget (max_tokens) decides the buckets"
                )
            probe = workload_params
            budget = self.max_seq - max(
                1, min(probe.max_tokens, self.max_seq // 2)
            )
            plain_set: set = set()
            prefix_sets: dict = {i: set() for i in range(len(prefixes))}
            for prompt in workload_prompts:
                toks = self._truncate_prompt(
                    self.tokenizer.encode(prompt), budget
                )
                for i, ptoks in enumerate(prefixes):
                    if (
                        len(toks) - 1 >= len(ptoks)
                        and toks[: len(ptoks)] == ptoks
                    ):
                        prefix_sets[i].add(
                            _bucket(len(toks) - len(ptoks), 64, self.max_seq)
                        )
                # EVERY prompt's full-length plain bucket is admissible,
                # prefix-sharer or not: sharing is per-wave all-or-nothing,
                # so a mixed wave (sharer + non-sharer) takes the PLAIN
                # program at the longest row's full length
                plain_set.add(_bucket(len(toks), 64, self.max_seq))
            plain_ts = sorted(plain_set)
            prefix_ts = {i: sorted(v) for i, v in prefix_sets.items()}

        guided_variants = [False] + ([True] if level == "full" else [])
        base = dict(max_tokens=1, stop_on_eos=False)
        waves: list[tuple[list, SamplingParams]] = []
        for guided in guided_variants:
            params = SamplingParams(
                **base,
                guided_choice=("warm", "cold") if guided else None,
            )
            # plain grid: first token diverges from every registered
            # prefix so _wave_prefix_match refuses and the plain program
            # is selected
            for t in plain_ts:
                long_row = [filler] * min(t, self.max_seq - 1)
                for n in n_pads:
                    rows = [list(long_row)] + [
                        [filler] * short for _ in range(n - 1)
                    ]
                    waves.append((rows, params))
            # shared-prefix grid, per registered prefix: every row starts
            # with THAT prefix
            for i, ptoks in enumerate(prefixes):
                for t in prefix_ts.get(i, []):
                    long_sfx = min(t, self.max_seq - 1 - len(ptoks))
                    if long_sfx < 1:
                        continue
                    for n in n_pads:
                        rows = [ptoks + [filler] * long_sfx] + [
                            ptoks + [filler] * short for _ in range(n - 1)
                        ]
                        waves.append((rows, params))

        decode_warm = {False: False, True: False}
        skipped = 0

        def drive(rows: list, params: SamplingParams) -> None:
            nonlocal skipped
            guided = params.guided_choice is not None
            if len(self.free_slots()) < len(rows):
                # a live request admitted between waves holds slots — the
                # grid must degrade, not assert: an early client during
                # startup is harmless, its programs compile in-band and
                # the remaining waves still warm everything slots permit
                skipped += 1
                return
            try:
                taken = self._admit_tokens(
                    [list(r) for r in rows], [params] * len(rows),
                    time.perf_counter(),
                )
            except OversizedRequest:
                skipped += 1
                return
            while self._prefill_job is not None:
                self.step()
            if len(taken) < len(rows):
                skipped += 1  # page pool can't grant the full wave
            if taken and not decode_warm[guided]:
                self.step()  # compiles the (guided) decode block
                decode_warm[guided] = True
            for slot_id in taken:
                self.cancel(slot_id)
            while self._inflight_blocks:
                self.step()

        for rows, params in waves:
            guided = params.guided_choice is not None
            n_pad = self._admission_n_pad(len(rows))
            t_all = max(len(r) for r in rows)
            shared = self._wave_shared_prefix(rows, [params] * len(rows))
            t_pad = _bucket(t_all - shared, 64, self.max_seq)
            if shared:
                key_hit = (n_pad, t_pad, shared, guided) in self._prefix_fns
            elif (
                self.prefill_chunk is not None and t_pad > self.prefill_chunk
            ):
                key_hit = (n_pad, t_pad, guided) in self._finish_fns
            else:
                key_hit = (n_pad, t_pad, guided) in self._prefill_fns
            if key_hit and decode_warm[guided]:
                continue
            drive(rows, params)

        # n-specific host glue (page-table staging, slot-activation
        # vectors) compiles eagerly per ACTUAL wave size, not per bucket:
        # one cheap wave at every n (programs already cached above) keeps
        # those 10-50 ms first-occurrence compiles out of request latency
        params = SamplingParams(**base)
        for n in range(1, self.max_slots + 1):
            drive([[filler] * short] * n, params)
            if prefixes:
                drive([prefixes[0] + [filler] * short] * n, params)
        result = {
            "level": level,
            "programs": self._program_count() - before,
            "skipped_waves": skipped,
            "seconds": round(time.perf_counter() - t0, 2),
        }
        if self._aot is not None:
            # warm boots restore executables instead of compiling:
            # hits > 0 and live_compiles == 0 is the warm-start signature
            result["aot"] = self._aot.stats()
        log.info("precompile grid: %s", result)
        return result

    def admit(
        self, prompts: Sequence[str], params_list: Sequence[SamplingParams]
    ) -> list[int]:
        """Tokenise + batch-prefill prompts into free slots; returns slot ids.

        One forward pass for the whole group — the "32 concurrent failure
        events -> one prefill" shape (BASELINE config 4).

        In paged mode admission may be PARTIAL: when the KV free list can't
        cover every prompt's worst case (prompt + max_tokens), only the
        longest prefix that fits is admitted and the returned list is
        shorter than ``prompts`` — the caller requeues the rest.  A single
        request larger than the whole cache raises :class:`OversizedRequest`.
        """
        free = self.free_slots()
        assert len(prompts) <= len(free), "admit() called with too few free slots"
        if not prompts:
            return []
        started = time.perf_counter()

        if any(p.deadline is not None for p in params_list):
            # clamp BEFORE token budgeting: max_tokens decides both the
            # truncation budget and the page grant below
            params_list = self._deadline_clamp_wave(params_list)

        token_lists = []
        for prompt, sampling in zip(prompts, params_list):
            ids = self.tokenizer.encode(prompt)
            # shared budget formula (types.prompt_budget): the continuous
            # scheduler's enqueue truncates with the same one
            budget = prompt_budget(self.max_seq, sampling.max_tokens)
            token_lists.append(self._truncate_prompt(ids, budget))
        return self._admit_tokens(token_lists, params_list, started)

    def _admit_tokens(
        self,
        token_lists: list,
        params_list: Sequence[SamplingParams],
        started: float,
    ) -> list[int]:
        """Admission after tokenisation/truncation: page grants + the
        shared-prefix decision + the batched prefill.  Split from admit()
        so precompile_grid() can drive exact token-length waves through
        the REAL admission path (bucket selection included)."""
        page_grants: list[list[int]] = []
        if self.paged:
            # shared-prefix reuse: when EVERY prompt starts with one
            # registered prefix, rows reference its generator-owned pages
            # and allocate (and later prefill) only their suffix
            shared, prefix_pages = self._wave_prefix_match(
                token_lists, params_list
            )
            pool = self.allocator.num_pages - 1 - self.prefix_held_pages
            for toks, sampling in zip(token_lists, params_list):
                need = pages_needed(
                    len(toks), sampling.max_tokens, self.max_seq,
                    self.page_size,
                ) - shared // self.page_size
                if need > pool:
                    if not page_grants:
                        raise OversizedRequest(
                            f"request needs {need} KV pages, cache holds {pool}"
                        )
                    break
                try:
                    page_grants.append(self.allocator.allocate(need))
                except MemoryError:
                    break  # backpressure: admit the prefix that fits
            if not page_grants:
                return []
            token_lists = token_lists[: len(page_grants)]
            params_list = params_list[: len(page_grants)]
            try:
                return self._admit_batch(
                    token_lists, params_list, page_grants, started,
                    prefix_shared=shared, prefix_pages=prefix_pages,
                )
            except BaseException:
                for grant in page_grants:  # don't leak pages on prefill failure
                    self.allocator.release(grant)
                raise
        return self._admit_batch(token_lists, params_list, [], started)

    def _admission_n_pads(self) -> list[int]:
        """The CLOSED set of batch buckets admission can assign: power-of-
        two buckets, dp-rounded (multiples of dp*fsdd so prefill rows shard
        instead of hitting the replicated fallback, _prefill_shardings),
        capped at max_slots.  Selecting the smallest member >= n keeps
        _admission_n_pad idempotent even when dp*fsdp is not a power of two
        (naive re-rounding would map 6 -> 9 for dp_total=3 and leave the
        6-row bucket uncompilable by any warmup)."""
        pads = set()
        d = self._dp_total if self.mesh is not None else 1
        for k in range(self.max_slots.bit_length() + 1):
            pads.add(min(self.max_slots, -(-(1 << k) // d) * d))
        return sorted(pads)

    def _admission_n_pad(self, n: int) -> int:
        """Smallest admissible batch bucket that fits ``n`` rows (padding
        rows are row-0 duplicates, so the only cost is their flops on one
        device's shard)."""
        for pad in self._admission_n_pads():
            if pad >= n:
                return pad
        return self.max_slots

    def _admit_batch(
        self,
        token_lists: list[list[int]],
        params_list: Sequence[SamplingParams],
        page_grants: list[list[int]],
        started: float,
        prefix_shared: int = 0,
        prefix_pages: "list[int] | None" = None,
    ) -> list[int]:
        jnp = self._jnp
        free = self.free_slots()
        n = len(token_lists)
        if prefix_shared:
            # shared-prefix wave: the program sees only suffixes; lengths
            # stay FULL (decode appends at the true sequence length)
            token_lists = [toks[prefix_shared:] for toks in token_lists]
        max_len = max(len(t) for t in token_lists)
        n_pad = self._admission_n_pad(n)
        t_pad = _bucket(max_len, 64, self.max_seq)

        ids = np.zeros((n_pad, t_pad), np.int32)
        lengths = np.ones((n_pad,), np.int32)
        temp = np.zeros((n_pad,), np.float32)
        top_p = np.ones((n_pad,), np.float32)
        slot_ids = np.zeros((n_pad,), np.int32)
        adapter_idx = np.zeros((n_pad,), np.int32)
        taken = free[:n]
        for row, (toks, sampling) in enumerate(zip(token_lists, params_list)):
            ids[row, : len(toks)] = toks
            lengths[row] = len(toks) + prefix_shared  # FULL sequence length
            temp[row] = sampling.temperature
            top_p[row] = sampling.top_p
            slot_ids[row] = taken[row]
            if sampling.adapter is not None and sampling.adapter not in self._adapter_ids:
                raise ValueError(
                    f"unknown LoRA adapter {sampling.adapter!r}; registered: "
                    f"{sorted(n for n in self._adapter_ids if n)}"
                )
            adapter_idx[row] = self._adapter_ids[sampling.adapter]
        # padding rows duplicate row 0 verbatim (tokens, length, AND slot):
        # the scatter then writes identical values to one slot from several
        # rows, which is order-independent — no scratch slot needed, no
        # free-slot budget consumed, no risk of corrupting a live slot
        for row in range(n, n_pad):
            ids[row] = ids[0]
            lengths[row] = lengths[0]
            slot_ids[row] = slot_ids[0]
            adapter_idx[row] = adapter_idx[0]

        # fast-path observability: operators verify the prefix cache is
        # actually taken in production from these two counters (a custom
        # template that silently stopped matching shows up as plain waves)
        self.metrics.incr(
            "prefill_waves_prefix" if prefix_shared else "prefill_waves_plain"
        )

        # guided decoding: stack the automata this wave + active slots need
        wave_specs = [self._guided_spec(p) for p in params_list]
        if any(wave_specs) or self._guided_tables is not None:
            self._refresh_guided_tables(wave_specs)
        guided = self._guided_tables is not None
        row_aut = (
            self._guided_row_aut(wave_specs, n_pad) if guided
            else np.zeros((n_pad,), np.int32)
        )

        key = (n_pad, t_pad)
        if (
            self.prefill_chunk is not None
            and t_pad > self.prefill_chunk
            and self._prefill_job is None
            and not prefix_shared  # suffix-only prefill is already short
        ):
            return self._start_prefill_job(
                key, ids, lengths, temp, top_p, slot_ids, adapter_idx,
                token_lists, params_list, page_grants, taken,
            )
        if prefix_shared:
            pkey = (n_pad, t_pad, prefix_shared, guided)
            if pkey not in self._prefix_fns:
                log.info(
                    "compiling prefixed prefill bucket n=%d t_sfx=%d shared=%d "
                    "(guided=%s)", n_pad, t_pad, prefix_shared, guided,
                )
                self._prefix_fns[pkey] = self._aot_wrap(
                    f"prefix_n{n_pad}_t{t_pad}_s{prefix_shared}_g{int(guided)}",
                    self._make_prefill_paged_prefixed(
                        n_pad, t_pad, prefix_shared, guided
                    ),
                )
            staged, row_tables = self._stage_page_tables(
                n, n_pad, slot_ids, page_grants, lengths,
                prefix_shared=prefix_shared, prefix_pages=prefix_pages,
            )
            prefix_table = jnp.asarray(
                (prefix_pages or [])[: prefix_shared // self.page_size],
                jnp.int32,
            )
            with self._annotation("podmortem.prefill", params_list):
                outs = self._prefix_fns[pkey](
                    self.params, staged, prefix_table, jnp.asarray(ids),
                    jnp.asarray(lengths), jnp.asarray(row_tables), self._rng,
                    jnp.asarray(temp), jnp.asarray(top_p), self.lora,
                    jnp.asarray(adapter_idx) if self.lora is not None else None,
                    *((self._guided_tables, jnp.asarray(row_aut)) if guided else ()),
                )
            if guided:
                self.paged_cache, first_tokens, self._rng, first_state = outs
            else:
                self.paged_cache, first_tokens, self._rng = outs
            result = self._activate_slots(
                np.asarray(first_tokens), lengths, taken, params_list,
                page_grants, (time.perf_counter() - started) * 1e3,
            )
            if guided:
                self._apply_guided_activation(row_aut, taken, first_state)
            return result
        key = (n_pad, t_pad, guided)
        if key not in self._prefill_fns:
            log.info("compiling prefill bucket n=%d t=%d (paged=%s guided=%s)",
                     n_pad, t_pad, self.paged, guided)
            self._prefill_fns[key] = self._aot_wrap(
                f"prefill_n{n_pad}_t{t_pad}_g{int(guided)}",
                self._make_prefill_paged(n_pad, t_pad, guided)
                if self.paged
                else self._make_prefill(n_pad, t_pad, guided),
            )

        if self.paged:
            staged, row_tables = self._stage_page_tables(
                n, n_pad, slot_ids, page_grants, lengths
            )
            with self._annotation("podmortem.prefill", params_list):
                outs = self._prefill_fns[key](
                    self.params, staged, jnp.asarray(ids), jnp.asarray(lengths),
                    jnp.asarray(row_tables), self._rng, jnp.asarray(temp),
                    jnp.asarray(top_p), self.lora,
                    jnp.asarray(adapter_idx) if self.lora is not None else None,
                    *((self._guided_tables, jnp.asarray(row_aut)) if guided else ()),
                )
            if guided:
                self.paged_cache, first_tokens, self._rng, first_state = outs
            else:
                self.paged_cache, first_tokens, self._rng = outs
        else:
            with self._annotation("podmortem.prefill", params_list):
                outs = self._prefill_fns[key](
                    self.params, self.cache, jnp.asarray(ids), jnp.asarray(lengths),
                    jnp.asarray(slot_ids), self._rng, jnp.asarray(temp), jnp.asarray(top_p),
                    self.lora,
                    jnp.asarray(adapter_idx) if self.lora is not None else None,
                    *((self._guided_tables, jnp.asarray(row_aut)) if guided else ()),
                )
            if guided:
                self.cache, first_tokens, self._rng, first_state = outs
            else:
                self.cache, first_tokens, self._rng = outs
        result = self._activate_slots(
            np.asarray(first_tokens), lengths, taken, params_list,
            page_grants, (time.perf_counter() - started) * 1e3,
        )
        if guided:
            self._apply_guided_activation(row_aut, taken, first_state)
        return result

    def _truncate_prompt(self, ids: list, budget: int) -> list:
        """Fit ``ids`` into ``budget`` tokens.

        Failure evidence concentrates at the TAIL; instructions sit at
        the HEAD — when the prompt starts with the cached prefix, drop
        the MIDDLE so both survive.  The head keeps at most half the
        budget so evidence always gets the larger share; without a
        matching cached prefix this is plain tail truncation.  A
        truncated prompt usually keeps only PART of the cached prefix,
        so its wave takes the plain prefill program (_wave_shared_prefix
        is all-or-nothing) — the head is kept for the instructions, not
        for KV reuse.
        """
        if len(ids) <= budget:
            return ids
        head = 0
        if self.paged and self._prefixes:
            # keep the longest registered-prefix run as the head (the
            # instructions), whichever template produced this prompt
            for entry in self._prefixes:
                common = 0
                for a, b in zip(ids, entry["tokens"]):
                    if a != b:
                        break
                    common += 1
                head = max(head, common)
            head = min(head, budget // 2)
            head = (head // self.page_size) * self.page_size
        return ids[:head] + ids[-(budget - head):]

    def _wave_prefix_match(
        self, token_lists: list, params_list: "Sequence[SamplingParams]"
    ) -> "tuple[int, list[int]]":
        """(shared token count, that prefix's pages) for the LONGEST
        registered prefix EVERY prompt in the wave fully matches —
        (0, []) when no prefix covers the whole wave.

        LoRA waves never share: adapters modify the K/V projections, so
        the base-model prefix KV would not equal what a full prefill with
        the adapter computes — reuse must stay EXACT."""
        if not (self.paged and self._prefixes and token_lists):
            return 0, []
        if any(p.adapter for p in params_list):
            return 0, []
        if any(not toks for toks in token_lists):
            # encode() normally guarantees >=1 token (BOS), but the page
            # arithmetic below must not hinge on tokenizer behavior: an
            # empty row would make len(toks)-1 negative and the floored
            # page multiple would slice token_lists from the tail
            return 0, []
        best, best_pages = 0, []
        for entry in self._prefixes:
            ptoks = entry["tokens"]
            # all-or-nothing makes partial-run counting useless: a C-speed
            # slice equality per row decides coverage (every row must also
            # keep >=1 suffix token: its first sampled token needs a logit
            # row in the suffix program)
            shared = len(ptoks)
            for toks in token_lists:
                if len(toks) - 1 < len(ptoks) or toks[: len(ptoks)] != ptoks:
                    shared = 0
                    break  # this prefix can't cover the whole wave
            # all-or-nothing PER PREFIX: the suffix program is specialised
            # on the static shared length, so interior values (e.g. the
            # page-floored half budget a truncated long prompt keeps,
            # _truncate_prompt) would each compile their OWN
            # (n_pad, t_sfx, shared) program — an unbounded compile
            # surface that defeats the warmup grid (precompile_grid) and
            # turns rare long prompts into mid-run multi-second p99
            # outliers.  A wave that cannot reuse a WHOLE cached prefix
            # takes the precompiled plain program instead.
            if shared and shared > best:
                best, best_pages = shared, entry["pages"]
        return best, best_pages

    def _wave_shared_prefix(
        self, token_lists: list, params_list: "Sequence[SamplingParams]"
    ) -> int:
        """Shared token count alone (see :meth:`_wave_prefix_match`)."""
        return self._wave_prefix_match(token_lists, params_list)[0]

    def _stage_page_tables(
        self, n: int, n_pad: int, slot_ids, page_grants, lengths,
        prefix_shared: int = 0,
        prefix_pages: "list[int] | None" = None,
    ):
        """Build the wave's page-table rows and a STAGED cache carrying
        them (shared by one-shot and chunked prefill); padding rows
        duplicate row 0 (identical duplicate writes are order-independent).

        The staged cache is NOT committed to ``self.paged_cache`` — the
        caller assigns only from its prefill/finish program's return value,
        so a failed prefill leaves the device state untouched (inactive
        slots keep their zeroed table rows pointing at the trash page while
        the failed wave's grants go back to the allocator).

        Returns ``(staged_cache, row_tables)``."""
        from ..ops.paged_attention import PagedKVCache

        jnp = self._jnp
        row_tables = np.zeros((n_pad, self.pages_per_seq), np.int32)
        n_prefix = prefix_shared // self.page_size if prefix_shared else 0
        for row, grant in enumerate(page_grants):
            if n_prefix:
                # shared-prefix wave: every row's table starts with the
                # MATCHED prefix's generator-owned pages (read-only; never
                # in the grant, so slot teardown cannot free them)
                row_tables[row, :n_prefix] = (prefix_pages or [])[:n_prefix]
            row_tables[row, n_prefix: n_prefix + len(grant)] = grant
        for row in range(n, n_pad):
            row_tables[row] = row_tables[0]
        paged = self.paged_cache
        table = paged.page_table.at[jnp.asarray(slot_ids[:n])].set(
            jnp.asarray(row_tables[:n])
        )
        lens = paged.lengths.at[jnp.asarray(slot_ids[:n])].set(
            jnp.asarray(lengths[:n])
        )
        staged = PagedKVCache(
            k_pages=paged.k_pages, v_pages=paged.v_pages,
            page_table=table, lengths=lens,
        )
        return staged, row_tables

    def _start_prefill_job(
        self, key, ids, lengths, temp, top_p, slot_ids, adapter_idx,
        token_lists, params_list, page_grants, taken,
    ) -> list[int]:
        """Reserve the wave's slots and stage device state; chunks run one
        per step() call so in-flight decodes interleave."""
        jnp = self._jnp
        n_pad, t_pad = key
        # NOTE: the device page table is NOT touched here — chunks run in
        # the job's mini cache only; tables commit atomically with the
        # finish program's successful return (_advance_prefill), so a
        # failure at any chunk leaves the device state untouched
        cache_ref = self.paged_cache.k_pages if self.paged else self.cache.k
        mini = KVCache.create(self.config, n_pad, t_pad, dtype=cache_ref.dtype)
        last_logits = jnp.zeros((n_pad, self.config.vocab_size), jnp.float32)
        if self.mesh is not None:
            # commit the carried device state to its program shardings once
            # at job start; every later chunk keeps it in place (the chunk
            # programs' in/out shardings match), so no per-chunk resharding
            rows, _ = self._prefill_shardings(n_pad)
            mini = self._jax.device_put(mini, self._shardings["cache"])
            last_logits = self._jax.device_put(last_logits, rows)
        self._prefill_job = _PrefillJob(
            key=key,
            ids=jnp.asarray(ids),
            lengths_np=lengths,
            lengths=jnp.asarray(lengths),
            temp=jnp.asarray(temp),
            top_p=jnp.asarray(top_p),
            slot_ids_np=slot_ids,
            taken=list(taken),
            params_list=list(params_list),
            page_grants=list(page_grants),
            adapter_idx=(
                jnp.asarray(adapter_idx) if self.lora is not None else None
            ),
            mini=mini,
            last_logits=last_logits,
            written=0,
        )
        self._reserved.update(taken)
        return list(taken)
