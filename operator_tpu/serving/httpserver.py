"""OpenAI-compatible HTTP front for the batching engine.

The reference's ai-interface is an internal REST service the operator
calls (`AIInterfaceRestClient.java:26,37-39`); this module is its
externally-callable form: any OpenAI SDK / curl user can drive the same
continuous-batching TPU engine the operator uses in-process.

Endpoints (stdlib asyncio, close-delimited HTTP/1.1 — same discipline as
operator/httpserver.py):

- ``GET  /v1/models``            — the loaded model (+ embedder if wired)
- ``POST /v1/completions``       — prompt (str or list), n, max_tokens,
  temperature, top_p, stop; every prompt/replica joins the shared
  continuous batch and decodes concurrently
- ``POST /v1/chat/completions``  — messages rendered with the loaded
  model family's published conversation format (serving/templates.py:
  llama3 headers, ChatML, Mistral [INST], Zephyr; neutral fallback)
- ``POST /v1/embeddings``        — the pattern-matching embedder (MiniLM
  when an encoder checkpoint is mounted, lexical hashing otherwise)
  exposed OpenAI-style for log-similarity tooling
- ``GET  /healthz``              — liveness for probes, plus this
  replica's identity and load report (queue depth, roofline decode
  estimate, supervisor gave-up flag, step-clock perf summary) for the
  failover router (operator_tpu/router/)
- ``POST /profile?seconds=N``    — on-demand TPU profiler capture
  (``jax.profiler.start_trace``/``stop_trace``): N seconds of device
  trace written under the profile dir, 404 unless enabled
  (``PROFILE_ENABLED``), 409 while a capture is already running;
  token-gated with everything else when ``api_token`` is set

``stream: true`` serves Server-Sent Events: one OpenAI-format chunk per
decode BLOCK (the engine's host-sync granularity — per-token events
would fabricate a cadence the device doesn't have), then ``[DONE]``.
Streaming is per-request (n=1, single prompt), like the SDKs use it.

Deliberate non-features: logprobs are null, and ``stop`` sequences are
applied by post-truncation (the jitted decode block has fixed shape; a
stop hit sets finish_reason but the step still ran its block — honest
accounting, not early exit).

Auth: set ``api_token`` (env OPERATOR_TPU_API_TOKEN via the CLI) to
require ``Authorization: Bearer <token>``.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
import uuid
from typing import Any, Optional

from ..obs import current_trace_id, parse_traceparent
from .engine import (
    GenerationResult,
    OversizedRequest,
    SamplingParams,
    ServingEngine,
)
from .templates import template_for

log = logging.getLogger(__name__)

_MAX_HEADER_BYTES = 16384
_MAX_BODY_BYTES = 10 << 20
_READ_TIMEOUT_S = 30.0

#: sentinel: the handler already wrote the (SSE) response to the socket
_STREAMED = object()

#: sentinel: the bounded pre-header peek in _stream expired before the
#: first engine update — commit the SSE headers and report in-stream
_PEEK_TIMED_OUT = object()


class _Binary(bytes):
    """Route payload that must go out as application/octet-stream (the
    fabric's /kv/blocks wire bytes), distinct from the plain ``bytes``
    the /metrics exposition path emits as text."""


def _content_text(content: Any) -> str:
    """Flatten OpenAI message content: plain string or content-parts list
    (``[{"type": "text", "text": ...}, ...]``; non-text parts rejected)."""
    if isinstance(content, str):
        return content
    if isinstance(content, list):
        texts = []
        for part in content:
            if not isinstance(part, dict) or part.get("type") != "text" \
                    or not isinstance(part.get("text"), str):
                raise ValueError("only string or text content parts are supported")
            texts.append(part["text"])
        return "".join(texts)
    raise ValueError("message content must be a string or list of text parts")


def _flatten_messages(messages: list) -> list[dict]:
    """Validate + flatten content-parts; raises ValueError on bad shape."""
    flat = []
    for msg in messages:
        if not isinstance(msg, dict) or "content" not in msg:
            raise ValueError("each message needs 'role' and 'content'")
        flat.append({
            "role": msg.get("role", "user"),
            "content": _content_text(msg["content"]),
        })
    return flat


def _earliest_stop(text: str, stop: list[str]) -> Optional[int]:
    """Index of the earliest stop-sequence occurrence, or None."""
    cut = None
    for seq in stop:
        idx = text.find(seq)
        if idx >= 0 and (cut is None or idx < cut):
            cut = idx
    return cut


def _truncate_at_stop(
    result: GenerationResult, stop: list[str]
) -> tuple[str, str]:
    """Earliest stop-sequence occurrence wins; returns (text, finish_reason)."""
    cut = _earliest_stop(result.text, stop)
    if cut is not None:
        return result.text[:cut], "stop"
    return result.text, result.finish_reason


class ApiError(Exception):
    def __init__(self, status: int, message: str, err_type: str = "invalid_request_error"):
        super().__init__(message)
        self.status = status
        self.err_type = err_type


def _map_engine_error(exc: BaseException) -> Optional[ApiError]:
    """The admission-error contract, shared by the streaming and
    non-streaming paths so the same engine failure can never produce
    diverging responses: OversizedRequest (prompt needs more KV pages than
    the whole cache) is a CLIENT error -> 400; RuntimeError (engine
    closed/dead) -> 503.  Other engine-internal errors (including
    ValueError) deliberately stay 5xx via the generic handler."""
    if isinstance(exc, OversizedRequest):
        return ApiError(400, str(exc))
    if isinstance(exc, RuntimeError):
        return ApiError(503, f"engine unavailable: {exc}", "server_error")
    return None


class CompletionServer:
    """Serve the shared ``ServingEngine`` over the OpenAI wire format."""

    #: how long _stream holds back the status line waiting for the first
    #: engine update (which surfaces admission failures as clean 400/503s);
    #: generous enough for an idle engine's prefill compile-hit, short
    #: enough to stay under client/ingress response-header timeouts
    stream_peek_timeout_s = 1.0

    def __init__(
        self,
        engine: ServingEngine,
        *,
        model_id: str,
        host: str = "0.0.0.0",
        port: int = 8000,
        api_token: Optional[str] = None,
        max_tokens_cap: int = 2048,
        embedder: Optional[Any] = None,  # .embed(texts)->ndarray, .dim
        embedding_model_id: str = "log-embedder",
        analysis_backend: Optional[Any] = None,  # .generate(AnalysisRequest)
        tracer: Optional[Any] = None,  # obs.Tracer for inbound traceparent
        drain_grace_s: float = 30.0,  # OperatorConfig.serving_drain_grace_s
        replica_id: Optional[str] = None,
        profile_enabled: bool = False,
        profile_dir: Optional[str] = None,
    ) -> None:
        self.engine = engine
        self.model_id = model_id
        #: this replica's stable identity in the multi-engine data plane
        #: (operator_tpu/router/): surfaced on GET /healthz next to the
        #: engine's load report so the failover router can poll one
        #: endpoint for liveness, identity, and shed feedback.  The
        #: deployment injects POD_NAME; "" falls back to hostname.
        if not replica_id:
            import socket

            replica_id = socket.gethostname()
        self.replica_id = replica_id
        #: wire parity with the reference's ai-interface contract
        #: (AIInterfaceRestClient.java:37-39): when a backend is wired,
        #: POST /api/v1/analysis/analyze serves AnalysisRequest->AIResponse
        #: verbatim, so tools written against the reference's service point
        #: here unchanged
        self.analysis_backend = analysis_backend
        self.host = host
        self.port = port
        self.api_token = api_token
        self.max_tokens_cap = max_tokens_cap
        self.embedder = embedder
        self.embedding_model_id = embedding_model_id
        #: inbound W3C traceparent support (docs/OBSERVABILITY.md): a
        #: request carrying the header runs under a trace joining the
        #: caller's trace id, and its engine spans (queue wait vs
        #: prefill/decode) land in the flight recorder.  None = header
        #: accepted but ignored.
        self.tracer = tracer
        #: POST /profile gate (OperatorConfig.profile_enabled /
        #: PROFILE_ENABLED): off by default — a capture costs device
        #: attention and disk, and must be an explicit operator decision
        self.profile_enabled = profile_enabled
        self.profile_dir = profile_dir or "/tmp/operator-tpu-profile"
        self._profiling = False
        self._server: Optional[asyncio.AbstractServer] = None
        self._started = time.time()
        # graceful drain (docs/ROBUSTNESS.md): stop() closes the listener
        # (no new connections), then waits for in-flight handlers — their
        # active engine waves complete — up to this grace before returning
        self.drain_grace_s = drain_grace_s
        self._active_handlers = 0
        self._drained = asyncio.Event()
        self._drained.set()

    @property
    def bound_port(self) -> Optional[int]:
        if self._server is None or not self._server.sockets:
            return None
        return self._server.sockets[0].getsockname()[1]

    async def start(self) -> None:
        await self.engine.start()
        # limit= makes readuntil overrun (-> 431) at exactly the header
        # budget instead of the 64 KiB StreamReader default; readexactly
        # for bodies is unaffected by the buffer limit
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port, limit=_MAX_HEADER_BYTES
        )
        log.info("completion api listening on %s:%s", self.host, self.bound_port)

    async def stop(self) -> None:
        """Graceful: stop ACCEPTING first, then let in-flight requests —
        and the engine waves they are riding — complete within the drain
        grace.  Requests still running at the boundary are abandoned to
        the engine close that follows (operator/app.py stop ordering)."""
        # swap-then-act: detach the listener before awaiting so a concurrent
        # stop() can't close the same server twice across the suspension
        server, self._server = self._server, None
        if server is not None:
            server.close()
            try:
                # 3.12.1+ wait_closed() ALSO waits for every connection
                # handler — unbounded, a wedged streaming handler would
                # hold shutdown here forever.  close() has already stopped
                # the listener; the _drained wait below is the real
                # (grace-bounded) drain, so bound this to a beat.
                await asyncio.wait_for(server.wait_closed(), timeout=1.0)
            except asyncio.TimeoutError:
                pass
        if self._active_handlers:
            try:
                await asyncio.wait_for(
                    self._drained.wait(), timeout=self.drain_grace_s
                )
            except asyncio.TimeoutError:
                log.warning(
                    "%d request(s) still in flight after the %.0fs drain "
                    "grace; closing under them",
                    self._active_handlers, self.drain_grace_s,
                )

    # -- http plumbing ------------------------------------------------------

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        self._active_handlers += 1
        self._drained.clear()
        try:
            await self._handle_inner(reader, writer)
        finally:
            self._active_handlers -= 1
            if self._active_handlers == 0:
                self._drained.set()

    async def _handle_inner(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        status, payload = 500, {"error": {"message": "internal error"}}
        accept = ""
        try:
            method, path, headers, body = await self._read_request(reader)
            accept = headers.get("accept", "")
            auth_exempt = path.split("?", 1)[0] == "/healthz"
            if not auth_exempt:  # probes can't carry tokens
                self._check_auth(headers)
            remote = parse_traceparent(headers.get("traceparent"))
            if remote is not None and auth_exempt and self.api_token:
                # recording a trace consumes bounded flight-recorder ring
                # slots; on a token-secured server the auth-exempt probe
                # path must not let unauthenticated clients mint them
                remote = None
            # join the caller's distributed trace when one was offered:
            # the serving-side spans (engine queue wait vs prefill/decode)
            # record under THEIR trace id, inspectable via /traces
            if remote is not None and self.tracer is not None:
                trace_ctx = self.tracer.trace(
                    f"http {path.split('?', 1)[0]}",
                    trace_id=remote[0], parent_id=remote[1],
                    attributes={"path": path.split("?", 1)[0]},
                )
            else:
                import contextlib

                trace_ctx = contextlib.nullcontext()
            with trace_ctx:
                status, payload = await self._route(
                    method, path, body, writer, accept=accept
                )
        except ApiError as exc:
            status = exc.status
            payload = {"error": {"message": str(exc), "type": exc.err_type, "code": None}}
        except asyncio.TimeoutError:
            status = 408
            payload = {"error": {"message": "request read timed out",
                                 "type": "invalid_request_error", "code": None}}
        except (asyncio.IncompleteReadError, ConnectionResetError):
            # TCP health probes / port scans connect and hang up without a
            # full request — a normal disconnect, not an error to log
            writer.close()
            return
        except asyncio.CancelledError:
            # engine shutdown resolves in-flight futures with CancelledError
            # (BaseException: would otherwise skip the response entirely and
            # strand the client); the handler task itself is not cancelled
            # by server.close(), so answering 503 here is always safe
            status = 503
            payload = {"error": {"message": "server shutting down",
                                 "type": "server_error", "code": None}}
        except Exception:  # noqa: BLE001 - never leak a traceback to the wire
            log.exception("completion api request failed")
        if payload is _STREAMED:  # response already written chunk by chunk
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError):
                pass
            return
        try:
            if isinstance(payload, _Binary):  # /kv/blocks wire payload
                data = bytes(payload)
                ctype = "application/octet-stream"
            elif isinstance(payload, bytes):  # /metrics Prometheus exposition
                data = payload
                ctype = (
                    "application/openmetrics-text; version=1.0.0; charset=utf-8"
                    if "application/openmetrics-text" in accept
                    else "text/plain; version=0.0.4"
                )
            else:
                data, ctype = json.dumps(payload).encode(), "application/json"
            writer.write(
                f"HTTP/1.1 {status} {'OK' if status < 400 else 'Error'}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(data)}\r\n"
                f"Connection: close\r\n\r\n".encode() + data
            )
            await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            pass
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError):
                pass

    async def _read_request(self, reader: asyncio.StreamReader):
        try:
            head = await asyncio.wait_for(
                reader.readuntil(b"\r\n\r\n"), timeout=_READ_TIMEOUT_S
            )
        except asyncio.LimitOverrunError:
            # separator not found within the StreamReader buffer limit —
            # oversized headers are a 431, not an internal error
            raise ApiError(431, "headers too large") from None
        if len(head) > _MAX_HEADER_BYTES:
            raise ApiError(431, "headers too large")
        request_line, *header_lines = head.decode("latin-1").split("\r\n")
        parts = request_line.split()
        if len(parts) != 3:
            raise ApiError(400, "malformed request line")
        method, path = parts[0].upper(), parts[1]
        headers = {}
        for line in header_lines:
            if ":" in line:
                key, value = line.split(":", 1)
                headers[key.strip().lower()] = value.strip()
        body = b""
        length = int(headers.get("content-length", "0") or "0")
        if length > _MAX_BODY_BYTES:
            raise ApiError(413, "request body too large")
        if length:
            body = await asyncio.wait_for(
                reader.readexactly(length), timeout=_READ_TIMEOUT_S
            )
        return method, path, headers, body

    def _check_auth(self, headers: dict) -> None:
        if not self.api_token:
            return
        import hmac

        supplied = headers.get("authorization", "")
        if not hmac.compare_digest(supplied, f"Bearer {self.api_token}"):
            raise ApiError(401, "missing or invalid bearer token", "authentication_error")

    # -- routing ------------------------------------------------------------

    async def _route(self, method: str, path: str, body: bytes, writer, *,
                     accept: str = ""):
        import urllib.parse

        path, _, raw_query = path.partition("?")
        query = urllib.parse.parse_qs(raw_query)
        if method == "GET" and path == "/healthz":
            # identity + load report for the data-plane router
            # (operator_tpu/router/): one poll answers liveness, WHO this
            # replica is, and how loaded it is — queue depth and the
            # admission roofline's per-token estimate feed the router's
            # shed decision, gaveUp excludes a supervisor-bricked engine
            load = self.engine.load_report()
            return 200, {
                "status": "degraded" if load.gave_up else "ok",
                "uptime_s": round(time.time() - self._started, 1),
                "replica": self.replica_id,
                "load": load.to_dict(),
            }
        if method == "GET" and path == "/metrics.json":
            # per-stage latency percentiles (prefill, decode_step, ...) from
            # the engine's registry — the operator endpoint's twin for the
            # standalone server
            return 200, self.engine.generator.metrics.snapshot()
        if method == "GET" and path == "/metrics":
            # exemplars only under OpenMetrics negotiation (a mid-line '#'
            # breaks the classic text 0.0.4 parser outright)
            return 200, self.engine.generator.metrics.prometheus(
                openmetrics="application/openmetrics-text" in accept
            ).encode()
        if method == "GET" and path == "/v1/models":
            models = [{
                "id": self.model_id,
                "object": "model",
                "created": int(self._started),
                "owned_by": "operator-tpu",
            }]
            # LoRA adapters are addressable models (the vLLM convention):
            # model=<adapter> routes the request through that adapter on
            # the shared base — one batch, per-slot adapters
            for adapter in self._adapter_names():
                models.append({
                    "id": adapter,
                    "object": "model",
                    "created": int(self._started),
                    "owned_by": "operator-tpu",
                    "parent": self.model_id,
                })
            if self.embedder is not None:
                models.append({
                    "id": self.embedding_model_id,
                    "object": "model",
                    "created": int(self._started),
                    "owned_by": "operator-tpu",
                })
            return 200, {"object": "list", "data": models}
        if method == "POST" and path == "/profile":
            return await self._profile(query)
        if method == "POST" and path == "/api/v1/analysis/analyze":
            return await self._analyze(self._parse_json(body))
        if method == "POST" and path == "/v1/embeddings":
            return await self._embeddings(self._parse_json(body))
        if method == "POST" and path == "/v1/completions":
            return await self._completions(self._parse_json(body), chat=False, writer=writer)
        if method == "POST" and path == "/v1/chat/completions":
            return await self._completions(self._parse_json(body), chat=True, writer=writer)
        if method == "GET" and path.startswith("/kv/blocks/"):
            return self._kv_block(path)
        raise ApiError(404, f"no route for {method} {path}")

    def _kv_block(self, path: str):
        """Fleet KV fabric peer endpoint (docs/FABRIC.md): serve one KV
        block straight out of the host pool.  Token-gated like every
        non-probe route (the generic auth check already ran); pure host
        numpy + checksum, so serving a page never touches the device or
        the scheduler."""
        hash_hex = path.rsplit("/", 1)[-1].lower()
        if len(hash_hex) != 32 or any(
            c not in "0123456789abcdef" for c in hash_hex
        ):
            raise ApiError(400, f"malformed block hash {hash_hex!r}")
        data = self.engine.kv_block_bytes(hash_hex)
        if data is None:
            raise ApiError(404, f"block {hash_hex} is not pooled here")
        return 200, _Binary(data)

    @staticmethod
    def _parse_json(body: bytes) -> dict:
        try:
            parsed = json.loads(body or b"null")
        except json.JSONDecodeError as exc:
            raise ApiError(400, f"body is not valid JSON: {exc}") from None
        if not isinstance(parsed, dict):
            raise ApiError(400, "body must be a JSON object")
        return parsed

    # -- completion handling -------------------------------------------------

    def _adapter_names(self) -> list[str]:
        generator = getattr(self.engine, "generator", None)
        return list(getattr(generator, "adapter_names", []) or [])

    def _resolve_adapter(self, req: dict) -> Optional[str]:
        """``model`` naming a registered adapter selects it; the base model
        id (or absent model) selects none; anything else is a 404."""
        model = req.get("model")
        if model is None or model == self.model_id:
            return None
        if model in self._adapter_names():
            return model
        raise ApiError(
            404,
            f"model {model!r} not found; available: "
            f"{[self.model_id, *self._adapter_names()]}",
            "invalid_request_error",
        )

    async def _ensure_guided(self, spec: tuple) -> None:
        """engine.ensure_guided with the validate-time ValueError→400
        mapping.  Engine-internal ValueErrors raised later deliberately
        stay 5xx, so the 400 mapping lives only here."""
        try:
            await self.engine.ensure_guided(spec)
        except ValueError as exc:
            raise ApiError(400, str(exc)) from None

    async def _sampling(self, req: dict) -> tuple[SamplingParams, list[str]]:
        max_tokens = req.get("max_tokens", 256)
        if not isinstance(max_tokens, int) or max_tokens < 1:
            raise ApiError(400, "max_tokens must be a positive integer")
        max_tokens = min(max_tokens, self.max_tokens_cap)
        temperature = req.get("temperature", 0.3)
        top_p = req.get("top_p", 0.95)
        for name, value in (("temperature", temperature), ("top_p", top_p)):
            if not isinstance(value, (int, float)) or value < 0:
                raise ApiError(400, f"{name} must be a non-negative number")
        stop = req.get("stop") or []
        if isinstance(stop, str):
            stop = [stop]
        if not isinstance(stop, list) or not all(isinstance(s, str) for s in stop):
            raise ApiError(400, "stop must be a string or list of strings")
        guided = req.get("guided_choice")
        if guided is not None:
            if (
                not isinstance(guided, list)
                or not guided
                or not all(
                    isinstance(c, str) and 0 < len(c) <= 512 for c in guided
                )
                or len(guided) > 256
            ):
                raise ApiError(
                    400,
                    "guided_choice must be a non-empty list of <=256 strings "
                    "of <=512 chars each",
                )
            await self._ensure_guided(("choice", tuple(guided)))
        regex = req.get("guided_regex")
        if regex is not None:
            if guided is not None:
                raise ApiError(400, "guided_choice and guided_regex are mutually exclusive")
            if not isinstance(regex, str) or not regex or len(regex) > 1024:
                raise ApiError(400, "guided_regex must be a non-empty string (<=1024 chars)")
            await self._ensure_guided(("regex", regex))
        schema = req.get("guided_json")
        response_format = req.get("response_format")
        if schema is None and isinstance(response_format, dict):
            kind = response_format.get("type")
            if kind == "json_schema":
                # OpenAI wire shape: response_format.json_schema.schema
                wrapper = response_format.get("json_schema")
                if wrapper is not None and not isinstance(wrapper, dict):
                    raise ApiError(400, "response_format.json_schema must be an object")
                schema = (wrapper or {}).get("schema") or response_format.get("schema")
                if schema is None:
                    raise ApiError(
                        400, "response_format json_schema needs a schema"
                    )
            elif kind == "json_object":
                raise ApiError(
                    400,
                    "response_format json_object (free-form JSON) is not "
                    "supported: arbitrary nesting is not a regular language; "
                    "provide a schema via json_schema or guided_json",
                )
            elif kind not in (None, "text"):
                raise ApiError(400, f"unknown response_format type {kind!r}")
        if schema is not None:
            if guided is not None or regex is not None:
                raise ApiError(
                    400,
                    "guided_json is mutually exclusive with guided_choice "
                    "and guided_regex",
                )
            from .json_schema import lower_guided_json

            try:
                # lower the schema onto the regex path: one automaton
                # machinery end to end, validated here so a bad schema can
                # never fail a co-batched wave
                regex = lower_guided_json(schema)
            except ValueError as exc:
                raise ApiError(400, str(exc)) from None
            await self._ensure_guided(("regex", regex))
        params = SamplingParams(
            max_tokens=max_tokens, temperature=float(temperature),
            top_p=float(top_p), adapter=self._resolve_adapter(req),
            guided_choice=tuple(guided) if guided is not None else None,
            guided_regex=regex,  # guided_json arrives lowered to a regex
            # a traceparent-carrying request's trace id rides into the
            # engine's profiler annotations (None outside a trace)
            trace_tag=current_trace_id(),
        )
        return params, stop

    async def _completions(self, req: dict, *, chat: bool, writer=None):
        params, stop = await self._sampling(req)
        n = req.get("n", 1)
        if not isinstance(n, int) or not 1 <= n <= 16:
            raise ApiError(400, "n must be an integer in [1, 16]")

        if chat:
            messages = req.get("messages")
            if not isinstance(messages, list) or not messages:
                raise ApiError(400, "messages must be a non-empty list")
            try:
                # the loaded model family's published conversation format —
                # instruct checkpoints degrade badly on anything else
                prompts = [template_for(self.model_id)(_flatten_messages(messages))]
            except ValueError as exc:
                raise ApiError(400, str(exc)) from None
        else:
            prompt = req.get("prompt")
            if isinstance(prompt, str):
                prompts = [prompt]
            elif isinstance(prompt, list) and prompt and all(
                isinstance(p, str) for p in prompt
            ):
                prompts = prompt
            else:
                raise ApiError(400, "prompt must be a string or non-empty list of strings")

        if req.get("stream"):
            if n != 1 or len(prompts) != 1:
                raise ApiError(400, "stream=true requires n=1 and a single prompt")
            await self._stream(writer, prompts[0], params, stop, req, chat=chat)
            return 200, _STREAMED

        # every replica of every prompt joins the shared continuous batch
        jobs = [p for p in prompts for _ in range(n)]
        tasks = [
            asyncio.ensure_future(self.engine.generate(p, params)) for p in jobs
        ]
        try:
            results = await asyncio.gather(*tasks)
        except BaseException as exc:
            # one failed job must not leave its siblings decoding on the
            # shared engine after the response went out — cancellation
            # triggers the engine's slot/page reclamation.  EVERY sibling
            # is then AWAITED (the loop never exits early): a task that
            # already failed holds an unretrieved exception ("Task
            # exception was never retrieved" log noise at GC), and a
            # cancelled one finishes its engine-side cleanup only when
            # awaited — both must resolve before the error response is
            # written
            for task in tasks:
                if not task.done():
                    task.cancel()
            handler_cancelled = False
            for task in tasks:
                try:
                    await task
                except asyncio.CancelledError:
                    # the cancellation is OURS when it was delivered while
                    # the sibling was still running, or injected into this
                    # handler (teardown) while awaiting an already-
                    # cancelled sibling — task.cancelled() alone cannot
                    # tell the latter apart, .cancelling() (3.11+; absent
                    # on 3.10, where that rarer case is missed) can.
                    # Remember it and KEEP draining: later siblings still
                    # need their exceptions retrieved and cleanup awaited
                    current = asyncio.current_task()
                    cancelling = getattr(current, "cancelling", None)
                    if not task.cancelled() or (
                        cancelling is not None and cancelling()
                    ):
                        handler_cancelled = True
                except Exception as sibling:
                    # retrieved (silencing the GC "never retrieved" noise),
                    # but a DISTINCT internal failure co-occurring with the
                    # mapped one must still leave a trace in the logs
                    if sibling is not exc:
                        log.warning("sibling generation also failed: %r", sibling)
            if handler_cancelled:
                raise asyncio.CancelledError from None
            mapped = _map_engine_error(exc)
            if mapped is not None:
                raise mapped from None
            raise

        choices = []
        usage_prompt = usage_completion = 0
        for index, result in enumerate(results):
            text, finish = _truncate_at_stop(result, stop)
            usage_prompt += result.prompt_tokens
            usage_completion += result.completion_tokens
            if chat:
                choices.append({
                    "index": index,
                    "message": {"role": "assistant", "content": text},
                    "logprobs": None,
                    "finish_reason": finish,
                })
            else:
                choices.append({
                    "index": index,
                    "text": text,
                    "logprobs": None,
                    "finish_reason": finish,
                })
        kind = "chat.completion" if chat else "text_completion"
        prefix = "chatcmpl" if chat else "cmpl"
        return 200, {
            "id": f"{prefix}-{uuid.uuid4().hex[:24]}",
            "object": kind,
            "created": int(time.time()),
            "model": req.get("model") or self.model_id,
            "choices": choices,
            "usage": {
                "prompt_tokens": usage_prompt,
                "completion_tokens": usage_completion,
                "total_tokens": usage_prompt + usage_completion,
            },
        }


    # -- on-demand profiler capture ------------------------------------------

    async def _profile(self, query: dict):
        """Capture ``seconds`` of ``jax.profiler`` device trace into a
        fresh directory under ``profile_dir`` and return its path.  The
        serving loops keep running — the whole point is to catch the
        LIVE workload's step timeline, not a synthetic one; the step
        clock says WHERE a step's time goes, the xplane capture says
        why.  One capture at a time (409): nested start_trace raises
        deep inside jax, and two captures would interleave anyway."""
        if not self.profile_enabled:
            raise ApiError(
                404, "profiling disabled (enable with PROFILE_ENABLED=1)"
            )
        try:
            seconds = float(query.get("seconds", ["2"])[0])
        except ValueError:
            raise ApiError(400, "seconds must be a number") from None
        # clamp: long captures produce multi-GB xplane dirs and hold the
        # profiler hostage; 0 would stop before the first step lands
        seconds = min(max(seconds, 0.1), 60.0)
        if self._profiling:
            raise ApiError(409, "a profile capture is already running")
        profiler = getattr(
            self.engine.generator._jax, "profiler", None
        )
        if profiler is None or not hasattr(profiler, "start_trace"):
            raise ApiError(
                501, "jax.profiler is unavailable in this runtime",
                "server_error",
            )
        import os

        out_dir = os.path.join(
            self.profile_dir, f"profile-{int(time.time() * 1e3)}"
        )
        self._profiling = True
        try:
            # start/stop are host-side control calls but can block on
            # device bookkeeping — keep them off the event loop
            await asyncio.to_thread(profiler.start_trace, out_dir)
            try:
                await asyncio.sleep(seconds)
            finally:
                await asyncio.to_thread(profiler.stop_trace)
        finally:
            self._profiling = False
        return 200, {
            "object": "profile",
            "artifact": out_dir,
            "seconds": seconds,
            "replica": self.replica_id,
        }

    # -- reference ai-interface contract -------------------------------------

    async def _analyze(self, req: dict) -> dict:
        """The reference's ai-interface route, byte-compatible: POST an
        AnalysisRequest (AnalysisResult + AIProviderConfig [+ failure
        data]), get an AIResponse back (reference
        AIInterfaceRestClient.java:37-39, AIInterfaceClient.java:45-59).
        Tools written against the reference's service point here
        unchanged; the compute is the in-process engine instead of an
        external LLM API."""
        if self.analysis_backend is None:
            raise ApiError(
                404,
                "analysis backend not wired (operator mode serves it; "
                "see CompletionServer(analysis_backend=...))",
            )
        from ..schema.analysis import AnalysisRequest

        try:
            request = AnalysisRequest.parse(req)
        except Exception as exc:  # noqa: BLE001 - schema violation -> client error
            raise ApiError(400, f"not an AnalysisRequest: {exc}") from None
        response = await self.analysis_backend.generate(request)
        return 200, response.to_dict()

    # -- embeddings ----------------------------------------------------------

    async def _embeddings(self, req: dict):
        if self.embedder is None:
            raise ApiError(404, "no embedding model is configured")
        texts = req.get("input")
        if isinstance(texts, str):
            texts = [texts]
        if (
            not isinstance(texts, list)
            or not texts
            or not all(isinstance(t, str) for t in texts)
            or len(texts) > 256
        ):
            raise ApiError(
                400, "input must be a string or list of <=256 strings"
            )
        loop = asyncio.get_running_loop()
        # neural embedders run a jax forward; keep the event loop responsive
        vectors = await loop.run_in_executor(None, self.embedder.embed, texts)
        return 200, {
            "object": "list",
            "model": req.get("model") or self.embedding_model_id,
            "data": [
                {
                    "object": "embedding",
                    "index": i,
                    "embedding": [float(x) for x in row],
                }
                for i, row in enumerate(vectors)
            ],
            "usage": {
                "prompt_tokens": sum(len(t.split()) for t in texts),
                "total_tokens": sum(len(t.split()) for t in texts),
            },
        }

    # -- streaming -----------------------------------------------------------

    async def _stream(
        self,
        writer: asyncio.StreamWriter,
        prompt: str,
        params: SamplingParams,
        stop: list[str],
        req: dict,
        *,
        chat: bool,
    ) -> None:
        """Write one SSE chunk per decode block, then [DONE] and close.

        Emission holds back an unstable tail so what is sent is never
        retracted: trailing U+FFFD (an incomplete UTF-8 sequence mid-block
        decodes to a replacement char that a later block may *replace* with
        the real character) and ``max(len(stop))-1`` chars (a stop sequence
        may span a block boundary; the non-streaming truncation must never
        cut below already-sent text).  Engine failures after the SSE
        headers surface as an OpenAI-style ``{"error": ...}`` event — a
        second HTTP response can never be written into an open stream.
        """
        tokenizer = self.engine.generator.tokenizer
        updates: asyncio.Queue = asyncio.Queue()
        job = asyncio.ensure_future(
            self.engine.generate(prompt, params, on_partial=updates.put_nowait)
        )

        def _on_done(t: asyncio.Task) -> None:
            if not t.cancelled():
                t.exception()  # mark retrieved: the early-exit paths
                # (peek cancellation, client OSError, finally-cancel) never
                # await the job, and an unretrieved failure would log GC
                # "Task exception was never retrieved" noise
            updates.put_nowait(None)  # wake the loop

        job.add_done_callback(_on_done)

        ident = f"{'chatcmpl' if chat else 'cmpl'}-{uuid.uuid4().hex[:24]}"
        created = int(time.time())
        model = req.get("model") or self.model_id
        kind = "chat.completion.chunk" if chat else "text_completion"
        stop_holdback = max((len(s) for s in stop), default=0)
        stop_holdback = stop_holdback - 1 if stop_holdback else 0

        def chunk(delta_text: Optional[str], finish: Optional[str]) -> bytes:
            if chat:
                delta: dict = {}
                if delta_text is not None:
                    delta = {"role": "assistant", "content": delta_text}
                choice = {"index": 0, "delta": delta, "finish_reason": finish}
            else:
                choice = {"index": 0, "text": delta_text or "",
                          "logprobs": None, "finish_reason": finish}
            event = {"id": ident, "object": kind, "created": created,
                     "model": model, "choices": [choice]}
            return f"data: {json.dumps(event)}\n\n".encode()

        def stable_prefix(text: str) -> str:
            """Strip the tail that a later block might rewrite."""
            end = len(text)
            while end > 0 and text[end - 1] == "�":
                end -= 1  # incomplete multi-byte sequence still in flight
            return text[: max(0, end - stop_holdback)]

        # peek at the FIRST engine update before committing to the 200/SSE
        # headers: admission-time failures (OversizedRequest, engine down)
        # resolve the job before any partial arrives, and they must surface
        # as the same 400/503 the non-streaming path returns — not as a 200
        # with an in-stream error event.  The peek is BOUNDED: a healthy
        # request queued behind a long prefill may take many seconds to its
        # first block, and holding back the status line that long would trip
        # client/ingress response-header timeouts — on timeout, commit the
        # headers and fall back to in-stream error reporting (the pre-fix
        # behavior), keeping the 400 mapping for the fast failure case
        try:
            first = await asyncio.wait_for(
                updates.get(), self.stream_peek_timeout_s
            )
        except asyncio.TimeoutError:
            first = _PEEK_TIMED_OUT
        except BaseException:
            job.cancel()
            raise
        if first is None and job.done():
            try:
                job.result()
            except asyncio.CancelledError:
                raise ApiError(503, "server shutting down", "server_error") from None
            except BaseException as exc:
                mapped = _map_engine_error(exc)
                if mapped is not None:
                    raise mapped from None
                raise
            # success with no partials (or an unexpected failure -> the
            # outer 500 mapping, matching non-streaming): fall through and
            # emit the final text below

        writer.write(
            b"HTTP/1.1 200 OK\r\n"
            b"Content-Type: text/event-stream\r\n"
            b"Cache-Control: no-cache\r\n"
            b"Connection: close\r\n\r\n"
        )
        sent_text = ""
        stopped = False
        try:
            await writer.drain()
            token_ids = (
                await updates.get() if first is _PEEK_TIMED_OUT else first
            )
            while token_ids is not None:
                if stopped:
                    token_ids = await updates.get()
                    continue  # drain remaining deltas past a stop match
                text = tokenizer.decode(token_ids)
                cut = _earliest_stop(text, stop)
                if cut is not None:
                    text, stopped = text[:cut], True
                else:
                    text = stable_prefix(text)
                if len(text) > len(sent_text) and text.startswith(sent_text):
                    writer.write(chunk(text[len(sent_text):], None))
                    await writer.drain()
                    sent_text = text
                token_ids = await updates.get()
            try:
                result = await job
            except asyncio.CancelledError:
                if not job.done():
                    raise  # this handler task was cancelled, not the engine
                # engine shutdown resolved the future with CancelledError
                writer.write(
                    b'data: {"error": {"message": "server shutting down", '
                    b'"type": "server_error", "code": null}}\n\n'
                    b"data: [DONE]\n\n"
                )
                await writer.drain()
                return
            except Exception as exc:  # engine failure mid-stream
                log.exception("stream generation failed")
                event = {"error": {"message": str(exc) or type(exc).__name__,
                                   "type": "server_error", "code": None}}
                writer.write(
                    f"data: {json.dumps(event)}\n\ndata: [DONE]\n\n".encode()
                )
                await writer.drain()
                return
            text, finish = _truncate_at_stop(result, stop)
            if len(text) > len(sent_text) and text.startswith(sent_text):
                writer.write(chunk(text[len(sent_text):], None))
            writer.write(chunk(None, "stop" if stopped else finish))
            writer.write(b"data: [DONE]\n\n")
            await writer.drain()
        except OSError:  # client went away mid-stream (reset/abort/pipe)
            job.cancel()
        finally:
            if not job.done():
                job.cancel()


async def serve_forever(
    engine: ServingEngine,
    *,
    model_id: str,
    host: str = "0.0.0.0",
    port: int = 8000,
    api_token: Optional[str] = None,
    embedder: Optional[Any] = None,
    analysis_backend: Optional[Any] = None,
    replica_id: Optional[str] = None,
    profile_enabled: bool = False,
    profile_dir: Optional[str] = None,
) -> None:
    """Run the completion API until cancelled (SIGINT/SIGTERM via CLI)."""
    server = CompletionServer(
        engine, model_id=model_id, host=host, port=port, api_token=api_token,
        embedder=embedder, analysis_backend=analysis_backend,
        replica_id=replica_id, profile_enabled=profile_enabled,
        profile_dir=profile_dir,
    )
    await server.start()
    try:
        await asyncio.Event().wait()
    finally:
        await server.stop()
        await engine.close()
