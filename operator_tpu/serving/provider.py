"""The ``tpu-native`` AI provider: in-tree TPU inference, zero external calls.

This is the leg of the reference the rebuild replaces outright — the
operator no longer POSTs to an ai-interface pod that fronts a GPU/OpenAI
backend (reference AIInterfaceRestClient.java:37-39); ``providerId:
tpu-native`` routes straight into the local serving engine (BASELINE north
star: "0 external AI calls").

Configuration comes from the same AIProvider CR fields the reference
honours (promptTemplate / maxTokens / temperature,
aiprovider-crd.yaml:36-62): the prompt builder applies the template, and
each request carries its own SamplingParams into the shared batch
(per-slot sampling, serving/engine.py).

Model selection: ``modelId`` in the CR (must name a registered config);
weights from ``OperatorConfig.checkpoint_dir`` (HF safetensors). Without a
checkpoint the factory REFUSES to build (:class:`MissingCheckpoint`) so the
pipeline degrades to the pattern-only/template path — the reference emits a
degradation event rather than storing garbage (PodFailureWatcher.java:385-420),
and random-weight text is garbage.  Benches/tests that genuinely want a
random-init engine set ``allow_random_weights`` (they construct prompts
whose THROUGHPUT is weight-independent, so the measurement is honest).
"""

from __future__ import annotations

import asyncio
import logging
import os
from typing import Optional

from ..obs import annotate_root, current_trace_id
from ..schema.analysis import AIResponse, AnalysisRequest
from ..utils.config import OperatorConfig
from .engine import (
    BatchedGenerator,
    DeadlineExceeded,
    SamplingParams,
    ServingEngine,
    SupervisorPolicy,
)
from .prompts import build_prompt

log = logging.getLogger(__name__)


class MissingCheckpoint(RuntimeError):
    """tpu-native is configured but no model weights are mounted."""


def _parse_mesh_plan(spec: str, devices: list, model_config):
    """'auto' or 'dp=2,tp=4[,fsdp=1]' -> MeshPlan."""
    from ..parallel.mesh import MeshPlan, plan_for

    if spec == "auto":
        # pass devices so tp sizing uses measured HBM, not the v5e constant
        return plan_for(len(devices), config=model_config, devices=devices)
    sizes = {"dp": 1, "fsdp": 1, "tp": 1}
    for part in spec.split(","):
        axis, _, value = part.strip().partition("=")
        if axis not in sizes or not value.isdigit():
            raise ValueError(
                f"bad serving_mesh {spec!r}: expected 'auto' or 'dp=N,tp=N[,fsdp=N]'"
            )
        sizes[axis] = int(value)
    plan = MeshPlan(**sizes)
    if plan.total > len(devices):
        raise ValueError(
            f"serving_mesh {spec!r} needs {plan.total} devices, found {len(devices)}"
        )
    return plan


class TPUNativeProvider:
    """AIProviderBackend serving explanations from the in-process engine."""

    def __init__(
        self,
        engine: ServingEngine,
        *,
        model_id: str,
        register_template_prefixes: bool = True,
    ) -> None:
        self.engine = engine
        self.model_id = model_id
        #: gate for lazy promptTemplate prefix registration — follows the
        #: operator's PREFIX_CACHE config (a disabled cache must not grow
        #: a registry through the side door)
        self.register_template_prefixes = register_template_prefixes
        # custom promptTemplate preambles already registered (or refused)
        # as shared prefixes — one attempt per distinct template
        self._registered_templates: set[str] = set()

    async def _ensure_template_prefix(self, template: Optional[str]) -> None:
        """Register a custom template's static preamble as a shared KV
        prefix, once: later waves of this CR's requests then prefill only
        their variable remainder (the default template was registered at
        engine build, serving/provider.py build_serving_engine)."""
        if not self.register_template_prefixes:
            return
        if not template or template in self._registered_templates:
            return
        self._registered_templates.add(template)
        from .prompts import template_preamble

        preamble = template_preamble(template)
        if not preamble:
            # build_prompt will fall back to DEFAULT_TEMPLATE for this
            # broken template; registering its preamble would hold pages
            # and a registry slot for a prefix no prompt ever starts with
            log.warning("promptTemplate does not render; prefix not cached")
            return
        try:
            cached = await self.engine.add_prefix(preamble)
            if cached:
                log.info("custom template preamble cached: %d tokens", cached)
        except Exception:  # noqa: BLE001 - an optimisation must never fail a request
            log.warning("custom template prefix registration failed",
                        exc_info=True)

    async def generate(self, request: AnalysisRequest) -> AIResponse:
        config = request.provider_config
        await self._ensure_template_prefix(
            config.prompt_template if config else None
        )
        prompt = build_prompt(request)
        # per-CR LoRA adapter (multi-LoRA serving): AIProvider
        # spec.additionalConfig.lora_adapter names a registered adapter;
        # different CRs then share one batch with different adapters
        extra = (config.additional_config or {}) if config else {}
        adapter = extra.get("lora_adapter") or None
        # per-CR constrained decoding: additionalConfig may carry a
        # guided_regex pattern or a guided_json schema (JSON text, lowered
        # onto the same regex automaton) — reference parity: the CR's
        # additionalConfig flows verbatim to the AI backend
        # (AIInterfaceClient.java:71-105); here it reaches the sampler.
        # A bad pattern/schema is a CONFIG error: fail this provider's
        # generation (pipeline stores the pattern-only result) rather than
        # silently dropping the constraint the CR asked for.
        guided_regex = extra.get("guided_regex") or None
        guided_schema = extra.get("guided_json") or None
        if guided_regex is not None and (
            not isinstance(guided_regex, str) or len(guided_regex) > 1024
        ):
            # same bound the HTTP entry point enforces: DFA compilation
            # runs synchronously at submit time, so an unbounded pattern
            # from one misconfigured CR could stall the serving thread
            return AIResponse(
                error="additionalConfig.guided_regex must be a string of "
                      "<=1024 chars",
                provider_id="tpu-native", model_id=self.model_id,
            )
        if guided_schema is not None:
            if guided_regex is not None:
                return AIResponse(
                    error="additionalConfig guided_json and guided_regex are "
                          "mutually exclusive",
                    provider_id="tpu-native", model_id=self.model_id,
                )
            from .json_schema import lower_guided_json

            try:
                guided_regex = lower_guided_json(guided_schema)
            except ValueError as exc:
                return AIResponse(
                    error=f"additionalConfig.guided_json: {exc}",
                    provider_id="tpu-native", model_id=self.model_id,
                )
        # deadline budget: the pipeline's residual envelope becomes an
        # absolute admission deadline — the engine clamps max_tokens to the
        # roofline fit or rejects outright (serving/admission.py)
        abs_deadline = None
        if request.deadline_s is not None:
            abs_deadline = (
                self.engine.generator._clock() + max(0.0, request.deadline_s)
            )
        params = SamplingParams(
            max_tokens=(config.max_tokens if config and config.max_tokens else 500),
            temperature=(
                config.temperature if config and config.temperature is not None else 0.3
            ),
            adapter=adapter,
            guided_regex=guided_regex,
            deadline=abs_deadline,
            # the analysis trace rides into the engine's profiler
            # annotations (podmortem.prefill/decode TraceMe tags), so an
            # xplane capture joins the flight-recorder timeline
            trace_tag=current_trace_id(),
        )
        try:
            # priority 10: pod-failure explanations admit ahead of external
            # completion-API callers sharing the engine (engine.generate)
            result = await self.engine.generate(prompt, params, priority=10)
        except asyncio.CancelledError:
            raise
        except DeadlineExceeded as exc:
            # no chip time was spent: admission refused the residue
            return AIResponse(
                error=f"deadline exceeded before generation: {exc}",
                provider_id="tpu-native", model_id=self.model_id,
                deadline_outcome="deadline-exceeded",
            )
        except Exception as exc:  # noqa: BLE001 - pipeline degrades to pattern-only
            log.exception("tpu-native generation failed")
            # a dead serve loop / device error is exactly the moment the
            # per-request timeline matters: flag the ambient trace for a
            # black-box dump (operator/pipeline.py reads the root attr)
            annotate_root("blackbox", "engine-error", overwrite=False)
            return AIResponse(error=str(exc), provider_id="tpu-native", model_id=self.model_id)
        outcome = None
        if abs_deadline is not None:
            outcome = (
                "truncated" if result.finish_reason == "deadline" else "completed"
            )
        return AIResponse(
            explanation=result.text,
            provider_id="tpu-native",
            model_id=self.model_id,
            prompt_tokens=result.prompt_tokens,
            completion_tokens=result.completion_tokens,
            deadline_outcome=outcome,
        )


def build_serving_engine(
    config: Optional[OperatorConfig] = None,
) -> "tuple[ServingEngine, str]":
    """Build the shared batching engine from operator config.

    Loads weights (checkpoint if configured, random init otherwise when
    ``allow_random_weights``), applies the serving mesh, and wraps the
    generator in a ``ServingEngine``.  Shared by the in-process
    ``tpu-native`` provider and the OpenAI-compatible HTTP server
    (serving/httpserver.py).  Returns ``(engine, model_id)``.
    """
    import jax
    import jax.numpy as jnp

    from ..models import get_config, init_params
    from ..models.loader import load_params_async
    from ..utils.platform import enable_persistent_compilation_cache

    cache_dir = enable_persistent_compilation_cache()
    if cache_dir:
        log.info("persistent XLA compilation cache: %s", cache_dir)
    from ..models.tokenizer import load_tokenizer

    config = config or OperatorConfig.from_env()
    model_id = os.environ.get("OPERATOR_TPU_MODEL", config.model_id)
    model_config = get_config(model_id)

    checkpoint_dir = config.checkpoint_dir
    tokenizer = load_tokenizer(checkpoint_dir)
    # legacy WEIGHT_DTYPE (when set) wins over the serving_dtype default —
    # int8 since PR 10, behind the tests/test_quant_parity.py gate
    serving_dtype = (config.weight_dtype or config.serving_dtype or "bf16").lower()
    quantize = serving_dtype == "int8"
    if quantize:
        log.info("int8 weight-only serving (per-output-channel)")
    elif serving_dtype not in ("bf16", "bfloat16"):
        raise ValueError(f"unknown serving dtype {serving_dtype!r}")

    # AOT executable cache: fingerprint from the SAME knobs the generator
    # construction below uses, built BEFORE the weight load finishes —
    # executable deserialization needs disk + host only, so it overlaps
    # the HBM weight transfer (the whole point of the warm-start path)
    mesh = None
    if config.serving_mesh:
        from ..parallel.mesh import make_mesh, mesh_summary

        devices = jax.devices()
        plan = _parse_mesh_plan(config.serving_mesh, devices, model_config)
        mesh = make_mesh(plan, devices)
        log.info("sharded serving: %s", mesh_summary(mesh))

    # multi-LoRA registry: every `<name>.safetensors` under lora_dir becomes
    # a selectable adapter; a bad file disables ONLY that adapter.  Loaded
    # before the AOT cache so the adapter names fold into its fingerprint
    # (the stacked-adapter axis changes every serving program's shape)
    lora_adapters = None
    if config.lora_dir and os.path.isdir(config.lora_dir):
        from ..parallel.lora import load_lora

        lora_adapters = {}
        for fname in sorted(os.listdir(config.lora_dir)):
            if not fname.endswith(".safetensors"):
                continue
            name = fname[: -len(".safetensors")]
            try:
                lora_adapters[name] = load_lora(os.path.join(config.lora_dir, fname))
            except Exception:  # noqa: BLE001 - optional per-adapter surface
                log.warning("LoRA adapter %s unusable; skipping", fname, exc_info=True)
        # one compiled program serves the whole set, so every adapter must
        # share targets and FULL factor shapes (stack_adapters); drop
        # empty/mismatched/name-colliding ones instead of letting the stack
        # (or API routing) break
        signature = None
        for name in sorted(lora_adapters):
            adapter = lora_adapters[name]
            sig = tuple(
                (target, adapter[target]["a"].shape, adapter[target]["b"].shape)
                for target in sorted(adapter)
            )
            if not sig:
                log.warning("LoRA adapter %r is empty; skipping", name)
                del lora_adapters[name]
            elif name == model_id:
                log.warning(
                    "LoRA adapter %r collides with the base model id and "
                    "would be unroutable over the API; skipping", name,
                )
                del lora_adapters[name]
            elif signature is None:
                signature = sig
            elif sig != signature:
                log.warning(
                    "LoRA adapter %r has targets/shapes %s != %s of the first "
                    "adapter; skipping (adapters must match to share one "
                    "compiled program)", name, sig, signature,
                )
                del lora_adapters[name]
        log.info("multi-LoRA serving: %s", sorted(lora_adapters) or "none loaded")
        lora_adapters = lora_adapters or None
    elif config.lora_dir:
        log.warning(
            "lora_dir %r does not exist or is not a directory; "
            "multi-LoRA serving disabled", config.lora_dir,
        )

    prefill_chunk = config.prefill_chunk or None
    max_slots = config.max_batch_size
    max_seq = min(model_config.max_seq_len, 2048)
    aot = None
    if config.aot_cache_path:
        from .aotcache import AotCache, generator_fingerprint

        try:
            aot = AotCache(config.aot_cache_path, generator_fingerprint(
                config=model_config,
                weight_dtype="int8" if quantize else "bfloat16",
                max_slots=max_slots,
                max_seq=max_seq,
                paged=config.kv_cache_mode == "paged",
                page_size=config.kv_page_size,
                kv_pages=config.kv_pages or None,
                mesh=mesh,
                decode_block=config.decode_block,
                sample_top_k=config.sample_top_k,
                pipeline_depth=config.pipeline_depth,
                prefill_chunk=prefill_chunk,
                sched_pipeline_depth=config.sched_pipeline_depth,
                spec_width=1 + (
                    config.spec_lookup_k if config.spec_decode else 0
                ),
                kv_prefix_cache=config.kv_prefix_cache,
                lora_names=sorted(lora_adapters) if lora_adapters else (),
            ))
        except Exception:  # noqa: BLE001 - cache is an optimisation only
            log.warning("AOT executable cache disabled", exc_info=True)

    if checkpoint_dir and os.path.isdir(checkpoint_dir):
        log.info("loading %s weights from %s", model_id, checkpoint_dir)
        # quantize-at-load: each layer group quantizes as it is placed, so
        # an 8B int8 load peaks at int8 tree + one bf16 group, never the
        # full float tree (models/loader.py).  The load STREAMS on a
        # background thread while the AOT cache deserializes executables —
        # compile/restore needs shapes, not values, so the two bring-up
        # legs run concurrently instead of serially
        handle = load_params_async(
            checkpoint_dir, model_config, dtype=jnp.bfloat16, quantize=quantize
        )
        if aot is not None:
            preloaded = aot.preload()
            if preloaded:
                log.info(
                    "AOT cache: %d executables restored while weights "
                    "streamed", preloaded,
                )
        params = handle.result()
        log.info("weight stream finished in %.1fs", handle.seconds or 0.0)
    elif config.allow_random_weights:
        log.warning(
            "no checkpoint for %s (checkpoint_dir=%r); using random init — "
            "explanations will be non-linguistic (allow_random_weights set)",
            model_id, checkpoint_dir,
        )
        if quantize:
            from ..models.quant import init_params_quantized

            params = init_params_quantized(
                model_config, jax.random.PRNGKey(0), dtype=jnp.bfloat16
            )
        else:
            params = init_params(model_config, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    else:
        # refusing keeps random-weight noise out of pod annotations: the
        # pipeline catches the ProviderError and stores the pattern-only
        # result + degradation event instead (reference behaviour for a
        # missing AI backend, PodFailureWatcher.java:385-420)
        raise MissingCheckpoint(
            f"providerId tpu-native needs weights for {model_id!r} but "
            f"checkpoint_dir={checkpoint_dir!r} does not exist; mount a "
            f"checkpoint or set ALLOW_RANDOM_WEIGHTS=true (testing only)"
        )

    if aot is not None:
        # idempotent: the checkpoint branch already preloaded during the
        # weight stream; the random-init branches reach it only here
        aot.preload()
    generator = BatchedGenerator(
        params,
        model_config,
        tokenizer,
        max_slots=max_slots,
        max_seq=max_seq,
        paged=config.kv_cache_mode == "paged",
        page_size=config.kv_page_size,
        kv_pages=config.kv_pages or None,
        mesh=mesh,
        decode_block=config.decode_block,
        pipeline_depth=config.pipeline_depth,
        sample_top_k=config.sample_top_k,
        lora_adapters=lora_adapters,
        lora_alpha=config.lora_alpha,
        prefill_chunk=prefill_chunk,
        aot_cache=aot,
        step_ring_capacity=config.step_ring_capacity,
    )
    # continuous-batching scheduler (serving/sched/, docs/SERVING.md):
    # the DEFAULT since the decode-ahead/speculation PR (wave stays as
    # the explicit SCHED_MODE=wave opt-out); falls back to the wave
    # engine with a loud warning when the engine shape can't serve it
    # (the mixed program has no mesh/LoRA path yet).  Decided BEFORE
    # prefix priming: the scheduler prefills every prompt in full, so
    # priming would only hold KV pages hostage for the process lifetime.
    scheduler = None
    if config.sched_mode == "continuous":
        if not generator.paged or mesh is not None or lora_adapters:
            log.warning(
                "sched_mode=continuous requires paged KV, no mesh and no "
                "LoRA adapters (paged=%s mesh=%s lora=%s); falling back "
                "to the wave engine",
                generator.paged, mesh is not None, bool(lora_adapters),
            )
        else:
            from .sched import Scheduler

            # automatic block-hash prefix caching (serving/kvstore.py):
            # the continuous scheduler's generalisation of the wave
            # engine's registered-shared-prefix — any cached prompt
            # prefix is reused, with an optional host-RAM offload tier
            # for evicted blocks (ops/kv_transfer.py)
            kvstore = None
            if config.kv_prefix_cache:
                from .kvstore import PrefixKVStore

                host_pool = None
                if config.kv_host_pool_mb > 0:
                    from ..ops.kv_transfer import HostKVPool

                    host_pool = HostKVPool(config.kv_host_pool_mb)
                kvstore = PrefixKVStore(
                    config.kv_page_size,
                    host_pool=host_pool,
                    metrics=generator.metrics,
                )
            scheduler = Scheduler(
                generator,
                chunk=config.sched_chunk,
                token_budget=config.sched_token_budget,
                pipeline_depth=config.sched_pipeline_depth,
                spec_decode=config.spec_decode,
                spec_lookup_k=config.spec_lookup_k,
                kvstore=kvstore,
                # fleet KV fabric (operator_tpu/fabric/): mirror newly
                # registered prompt blocks into the host pool so peers
                # can fetch them over GET /kv/blocks/{hash}
                fabric_mirror=(
                    config.kv_fabric
                    and config.kv_fabric_mirror
                    and kvstore is not None
                    and kvstore.host_pool is not None
                ),
            )
    elif config.sched_mode != "wave":
        raise ValueError(
            f"unknown sched_mode {config.sched_mode!r}: expected "
            "'wave' or 'continuous'"
        )
    # loud, unambiguous mode line: fleet operators grep for it when a
    # rollout flips scheduling behaviour
    if scheduler is not None:
        log.info(
            "serving mode: CONTINUOUS scheduler (pipeline_depth=%d "
            "spec_decode=%s spec_lookup_k=%d kv_prefix_cache=%s "
            "kv_host_pool_mb=%d); SCHED_MODE=wave opts out",
            scheduler.depth, scheduler.spec_k > 0, scheduler.spec_k,
            scheduler._kvstore is not None, config.kv_host_pool_mb,
        )
    else:
        log.info(
            "serving mode: WAVE engine (sched_mode=%s)", config.sched_mode
        )
    if config.prefix_cache and generator.paged and scheduler is None:
        # the default template's static preamble is shared by every
        # explanation request: cache its KV once so each admission
        # prefills only its variable remainder.  CRs with a custom
        # promptTemplate simply fall back to full prefill (the engine
        # compares TOKENS per wave; a non-matching wave costs nothing).
        # Skipped in continuous mode: the mixed program has no prefix
        # path, and the primed pages would shrink the pool for nothing.
        from .prompts import DEFAULT_TEMPLATE, template_preamble

        static_preamble = template_preamble(DEFAULT_TEMPLATE)
        try:
            generator.set_shared_prefix(static_preamble)
        except Exception:  # noqa: BLE001 - an optimisation must never block startup
            log.warning("shared-prefix priming failed; serving without it",
                        exc_info=True)
    # supervised by default in production wiring (docs/ROBUSTNESS.md): a
    # stalled or errored decode loop resets the engine and requeues
    # in-flight requests once with their residual deadlines.  Direct
    # ServingEngine(...) constructions (tests) keep the unsupervised
    # pre-supervisor semantics unless they opt in.
    supervisor = None
    if config.engine_supervisor:
        supervisor = SupervisorPolicy(
            stall_timeout_s=config.supervisor_stall_s,
            join_grace_s=config.supervisor_join_grace_s,
        )
    engine = ServingEngine(
        generator, supervisor=supervisor, scheduler=scheduler
    )
    # fleet KV fabric + disaggregation role (operator_tpu/fabric/,
    # docs/FABRIC.md).  The fetcher starts with a private empty index;
    # two feeders exist: in-process fleets (loadgen storm, bench, tests)
    # point it at the router's health.kv_index, which the existing
    # /healthz poll keeps fresh, while a standalone replica (the k8s
    # Deployment) runs the KV_FABRIC_PEERS poller — without one of the
    # two the empty-index gate makes the fabric a true no-op (no probe,
    # no tokenize) rather than a silent per-request tax.
    from ..fabric.disagg import normalize_role

    engine.replica_role = normalize_role(config.replica_role)
    if config.kv_fabric and scheduler is not None:
        from ..fabric.fetch import FabricFetcher
        from ..fabric.index import FabricIndex

        self_id = (
            os.environ.get("SERVING_REPLICA_ID")
            or os.environ.get("POD_NAME")
            or ""
        )
        engine.fabric = FabricFetcher(
            FabricIndex(),
            api_token=os.environ.get("OPERATOR_TPU_API_TOKEN") or None,
            timeout_s=config.kv_fabric_fetch_timeout_s,
            concurrency=config.kv_fabric_concurrency,
            self_id=self_id,
            metrics=generator.metrics,
        )
        peers = [
            u.strip() for u in config.kv_fabric_peers.split(",") if u.strip()
        ]
        if peers:
            from ..fabric.peers import PeerPoller

            engine.fabric_poller = PeerPoller(
                engine.fabric.index,
                peers=peers,
                self_id=self_id,
                poll_s=config.kv_fabric_poll_s,
                timeout_s=config.kv_fabric_fetch_timeout_s,
                metrics=generator.metrics,
            )
        log.info(
            "fleet KV fabric: fetch timeout %.2fs concurrency %d role %s "
            "mirror %s peers %s",
            config.kv_fabric_fetch_timeout_s, config.kv_fabric_concurrency,
            engine.replica_role, config.kv_fabric_mirror,
            ",".join(peers) or "<in-process index>",
        )
    return engine, model_id


def build_tpu_native_provider(
    config: Optional[OperatorConfig] = None,
) -> TPUNativeProvider:
    """Factory for ProviderRegistry.register_factory('tpu-native', ...).

    Builds the shared engine once; every AIProvider CR with
    ``providerId: tpu-native`` then multiplexes onto the same batch.
    """
    engine, model_id = build_serving_engine(config)
    return TPUNativeProvider(
        engine, model_id=model_id,
        register_template_prefixes=(config or OperatorConfig()).prefix_cache,
    )
