"""Byte-level regex -> DFA -> token transition table (guided_regex).

A small, self-contained regex compiler for constrained decoding:
Thompson NFA construction over BYTES, subset construction to a DFA, then
a vectorized closure over the tokenizer vocabulary so each DFA state
carries a token-level transition row (serving/guided.py table format —
the same stacked tables the decode scan consumes for guided_choice).

Supported syntax (full-match semantics, byte alphabet):

- literals (non-ASCII via their UTF-8 bytes), ``\\`` escapes
- ``.`` (any byte except ``\\n``), classes ``[a-z]``/``[^...]`` with
  ranges, and the usual shorthands ``\\d \\D \\w \\W \\s \\S``
- grouping ``(...)``, alternation ``|``
- quantifiers ``* + ?`` and bounded ``{m}``/``{m,}``/``{m,n}`` (n <= 64)

Deliberately NOT supported (rejected with ValueError): backreferences,
lookaround, lazy/stacked quantifiers (constrained decoding is a language
filter; greedy/lazy is meaningless), alphanumeric escapes outside the
supported shorthands (word-boundary/hex/unicode escapes would silently
change meaning),
and interior anchors — a single leading ``^`` / trailing ``$`` is
accepted and ignored (patterns are implicitly anchored).

Dead-end elimination: DFA states from which no TOKEN sequence can reach
acceptance are pruned, so the sampler can never be steered into a state
whose row is all -inf (a pattern the tokenizer cannot realise raises).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

MAX_REPEAT = 64


# --------------------------------------------------------------------------
# parsing -> NFA (Thompson construction)
# --------------------------------------------------------------------------


@dataclass
class _NfaState:
    #: byte-class edges: (256-bool mask, target state id)
    edges: list = field(default_factory=list)
    eps: list = field(default_factory=list)


class _Nfa:
    def __init__(self) -> None:
        self.states: list[_NfaState] = []

    def new_state(self) -> int:
        self.states.append(_NfaState())
        return len(self.states) - 1


def _class_mask(chars: str) -> np.ndarray:
    mask = np.zeros(256, bool)
    for ch in chars:
        mask[ord(ch)] = True
    return mask


_DIGIT = _class_mask("0123456789")
_WORD = _class_mask(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_"
)
_SPACE = _class_mask(" \t\n\r\f\v")
_ANY = np.ones(256, bool)
_ANY[ord("\n")] = False

_ESCAPES = {
    "d": _DIGIT, "D": ~_DIGIT,
    "w": _WORD, "W": ~_WORD,
    "s": _SPACE, "S": ~_SPACE,
    "n": _class_mask("\n"), "t": _class_mask("\t"), "r": _class_mask("\r"),
}


class _Parser:
    """Recursive-descent: alt -> concat -> repeat -> atom."""

    def __init__(self, pattern: str) -> None:
        # full-match semantics: tolerate the habitual outer anchors
        if pattern.startswith("^"):
            pattern = pattern[1:]
        if pattern.endswith("$") and not pattern.endswith("\\$"):
            pattern = pattern[:-1]
        self.src = pattern
        self.pos = 0
        self.nfa = _Nfa()

    def fail(self, message: str) -> Exception:
        return ValueError(
            f"guided_regex: {message} at position {self.pos} in {self.src!r}"
        )

    def peek(self) -> Optional[str]:
        return self.src[self.pos] if self.pos < len(self.src) else None

    def take(self) -> str:
        ch = self.src[self.pos]
        self.pos += 1
        return ch

    # fragments are (start, accept) state-id pairs
    def parse(self) -> tuple:
        fragment = self.alt()
        if self.pos != len(self.src):
            raise self.fail(f"unexpected {self.peek()!r}")
        return fragment

    def alt(self) -> tuple:
        branches = [self.concat()]
        while self.peek() == "|":
            self.take()
            branches.append(self.concat())
        if len(branches) == 1:
            return branches[0]
        start, accept = self.nfa.new_state(), self.nfa.new_state()
        for b_start, b_accept in branches:
            self.nfa.states[start].eps.append(b_start)
            self.nfa.states[b_accept].eps.append(accept)
        return start, accept

    def concat(self) -> tuple:
        parts = []
        while self.peek() is not None and self.peek() not in "|)":
            parts.append(self.repeat())
        if not parts:  # empty branch: epsilon
            state = self.nfa.new_state()
            return state, state
        start, accept = parts[0]
        for nxt_start, nxt_accept in parts[1:]:
            self.nfa.states[accept].eps.append(nxt_start)
            accept = nxt_accept
        return start, accept

    def repeat(self) -> tuple:
        fragment = self.atom()
        ch = self.peek()
        if ch == "*":
            self.take()
            fragment = self._star(fragment)
        elif ch == "+":
            self.take()
            fragment = self._concat_pair(fragment, self._star(self._copy(fragment)))
        elif ch == "?":
            self.take()
            fragment = self._optional(fragment)
        elif ch == "{":
            fragment = self._bounded(fragment)
        else:
            return fragment
        if self.peek() in ("*", "+", "?", "{"):
            raise self.fail(
                "lazy/stacked quantifiers are not supported (group the "
                "inner quantifier explicitly if you mean it)"
            )
        return fragment

    def _bounded(self, fragment: tuple) -> tuple:
        self.take()  # '{'
        digits = ""
        while self.peek() and self.peek().isdigit():
            digits += self.take()
        if not digits:
            raise self.fail("malformed {m,n}")
        low = int(digits)
        high = low
        if self.peek() == ",":
            self.take()
            digits = ""
            while self.peek() and self.peek().isdigit():
                digits += self.take()
            high = int(digits) if digits else None
        if self.peek() != "}":
            raise self.fail("unterminated {m,n}")
        self.take()
        if high is not None and (high < low or high > MAX_REPEAT):
            raise self.fail(f"repeat bound must be <= {MAX_REPEAT} and >= the minimum")
        if low > MAX_REPEAT:
            raise self.fail(f"repeat bound must be <= {MAX_REPEAT}")
        parts = [self._copy(fragment) for _ in range(low)]
        if high is None:
            parts.append(self._star(self._copy(fragment)))
        else:
            parts.extend(
                self._optional(self._copy(fragment)) for _ in range(high - low)
            )
        if not parts:  # {0} / {0,0}
            state = self.nfa.new_state()
            return state, state
        out = parts[0]
        for part in parts[1:]:
            out = self._concat_pair(out, part)
        return out

    def _concat_pair(self, a: tuple, b: tuple) -> tuple:
        self.nfa.states[a[1]].eps.append(b[0])
        return a[0], b[1]

    def _star(self, fragment: tuple) -> tuple:
        start, accept = self.nfa.new_state(), self.nfa.new_state()
        f_start, f_accept = fragment
        self.nfa.states[start].eps += [f_start, accept]
        self.nfa.states[f_accept].eps += [f_start, accept]
        return start, accept

    def _optional(self, fragment: tuple) -> tuple:
        start, accept = self.nfa.new_state(), self.nfa.new_state()
        f_start, f_accept = fragment
        self.nfa.states[start].eps += [f_start, accept]
        self.nfa.states[f_accept].eps.append(accept)
        return start, accept

    def _copy(self, fragment: tuple) -> tuple:
        """Deep-copy a fragment's subgraph (for counted repeats / ``+``)."""
        start, accept = fragment
        reachable = set()
        stack = [start]
        while stack:
            state = stack.pop()
            if state in reachable:
                continue
            reachable.add(state)
            node = self.nfa.states[state]
            stack += [t for _, t in node.edges] + list(node.eps)
        mapping = {old: self.nfa.new_state() for old in reachable}
        for old in reachable:
            node = self.nfa.states[old]
            clone = self.nfa.states[mapping[old]]
            clone.edges = [(mask, mapping[t]) for mask, t in node.edges if t in mapping]
            clone.eps = [mapping[t] for t in node.eps if t in mapping]
        return mapping[start], mapping[accept]

    def atom(self) -> tuple:
        ch = self.peek()
        if ch is None:
            raise self.fail("unexpected end of pattern")
        if ch == "(":
            self.take()
            if self.peek() == "?":
                raise self.fail("(?...) groups are not supported")
            fragment = self.alt()
            if self.peek() != ")":
                raise self.fail("unbalanced parenthesis")
            self.take()
            return fragment
        if ch == "[":
            return self._fragment_for(self._char_class())
        if ch == ".":
            self.take()
            return self._fragment_for(_ANY.copy())
        if ch == "\\":
            self.take()
            return self._fragment_for(self._escape())
        if ch in "*+?{":
            raise self.fail(f"quantifier {ch!r} with nothing to repeat")
        if ch in ")|":
            raise self.fail(f"unexpected {ch!r}")
        if ch in "^$":
            raise self.fail(
                "interior anchors are not supported (patterns are "
                "implicitly anchored; escape a literal with \\)"
            )
        self.take()
        return self._bytes_fragment(ch.encode("utf-8"))

    def _escape(self) -> np.ndarray:
        if self.peek() is None:
            raise self.fail("dangling escape")
        ch = self.take()
        if ch in _ESCAPES:
            return _ESCAPES[ch].copy()
        if ch.isalnum():
            raise self.fail(
                f"unsupported escape \\{ch} (supported: "
                f"{' '.join(sorted(_ESCAPES))}; punctuation escapes literal)"
            )
        return _class_mask(ch)  # \. \[ \\ etc: the literal byte

    def _char_class(self) -> np.ndarray:
        self.take()  # '['
        negate = self.peek() == "^"
        if negate:
            self.take()
        mask = np.zeros(256, bool)
        first = True
        while True:
            ch = self.peek()
            if ch is None:
                raise self.fail("unterminated character class")
            if ch == "]" and not first:
                self.take()
                break
            first = False
            if ch == "\\":
                self.take()
                mask |= self._escape()
                continue
            self.take()
            lo = ch.encode("utf-8")
            if len(lo) != 1:
                raise self.fail("non-ASCII in character class")
            if self.peek() == "-" and self.pos + 1 < len(self.src) \
                    and self.src[self.pos + 1] != "]":
                self.take()
                hi = self.take().encode("utf-8")
                if len(hi) != 1 or hi[0] < lo[0]:
                    raise self.fail("bad character range")
                mask[lo[0]: hi[0] + 1] = True
            else:
                mask[lo[0]] = True
        return ~mask if negate else mask

    def _fragment_for(self, mask: np.ndarray) -> tuple:
        start, accept = self.nfa.new_state(), self.nfa.new_state()
        self.nfa.states[start].edges.append((mask, accept))
        return start, accept

    def _bytes_fragment(self, data: bytes) -> tuple:
        start = self.nfa.new_state()
        current = start
        for byte in data:
            nxt = self.nfa.new_state()
            mask = np.zeros(256, bool)
            mask[byte] = True
            self.nfa.states[current].edges.append((mask, nxt))
            current = nxt
        return start, current


# --------------------------------------------------------------------------
# NFA -> DFA (subset construction) over bytes
# --------------------------------------------------------------------------


def _compile_byte_dfa(pattern: str, max_states: int) -> tuple:
    """Returns (byte_transition [S, 256] int32 with -1, accepting [S] bool)."""
    parser = _Parser(pattern)
    start, accept = parser.parse()
    nfa = parser.nfa

    def closure(states: frozenset) -> frozenset:
        seen = set(states)
        stack = list(states)
        while stack:
            for target in nfa.states[stack.pop()].eps:
                if target not in seen:
                    seen.add(target)
                    stack.append(target)
        return frozenset(seen)

    start_set = closure(frozenset({start}))
    index = {start_set: 0}
    order = [start_set]
    rows: list[np.ndarray] = []
    position = 0
    while position < len(order):
        current = order[position]
        position += 1
        # move table for all 256 bytes at once
        targets: list[set] = [set() for _ in range(256)]
        for state in current:
            for mask, target in nfa.states[state].edges:
                for byte in np.nonzero(mask)[0]:
                    targets[int(byte)].add(target)
        row = np.full(256, -1, np.int32)
        for byte, target_set in enumerate(targets):
            if not target_set:
                continue
            closed = closure(frozenset(target_set))
            if closed not in index:
                if len(order) >= max_states:
                    raise ValueError(
                        f"guided_regex pattern needs more than {max_states} "
                        f"DFA states; simplify the pattern"
                    )
                index[closed] = len(order)
                order.append(closed)
            row[byte] = index[closed]
        rows.append(row)
    byte_transition = np.stack(rows)
    accepting = np.array([accept in s for s in order], bool)
    return byte_transition, accepting


# --------------------------------------------------------------------------
# token closure
# --------------------------------------------------------------------------


def token_byte_table(tokenizer, vocab_size: int) -> "list[Optional[bytes]]":
    """bytes of each token id, or None for ids that must never be emitted
    (specials, out-of-tokenizer ids).  Supported for the in-tree
    tokenizers; HF-backed tokenizers raise (their byte mapping is
    model-specific)."""
    table: list[Optional[bytes]] = [None] * vocab_size
    inner = getattr(tokenizer, "_bytes", None)
    if inner is not None:  # models/bpe.py BPETokenizer
        from ..models.bpe import NUM_SPECIALS

        for token in range(min(vocab_size, len(inner))):
            if token >= NUM_SPECIALS and inner[token]:
                table[token] = inner[token]
        return table
    specials = getattr(tokenizer, "SPECIALS", None)
    if specials is not None:  # models/tokenizer.py ByteTokenizer
        for token in range(specials, min(vocab_size, 256 + specials)):
            table[token] = bytes([token - specials])
        return table
    raise ValueError(
        "guided_regex needs a tokenizer with a known byte mapping "
        "(byte or builtin-bpe); guided_choice works with any tokenizer"
    )


def compile_regex_automaton(
    pattern: str, tokenizer, vocab_size: int, *, max_states: int
):
    """Token-level ``ChoiceAutomaton``-compatible table for ``pattern``.

    Vectorized closure: all (state, token) pairs advance byte-position by
    byte-position; tokens whose bytes dead-end map to -1.  Accepting
    states allow EOS (self-loop); states from which acceptance is
    UNREACHABLE via tokens are pruned so the sampler never faces an
    all-forbidden row.
    """
    from .guided import ChoiceAutomaton

    eos = tokenizer.eos_id
    if eos is None or not 0 <= int(eos) < vocab_size:
        raise ValueError("guided decoding needs a tokenizer with an eos id")
    byte_transition, accepting = _compile_byte_dfa(pattern, max_states)
    table = token_byte_table(tokenizer, vocab_size)
    num_states = byte_transition.shape[0]
    # the closure materialises [num_states, vocab] int32 grids; bound the
    # allocation so one pathological pattern can't eat gigabytes inside the
    # API's validation call
    if num_states * vocab_size > 16_000_000:
        raise ValueError(
            f"guided_regex pattern needs {num_states} DFA states x "
            f"{vocab_size} vocab — too large; simplify the pattern"
        )

    max_len = max((len(b) for b in table if b), default=0)
    if max_len == 0:
        raise ValueError("tokenizer exposes no usable tokens")
    token_bytes = np.zeros((vocab_size, max_len), np.int32)
    token_lengths = np.zeros(vocab_size, np.int32)
    for token, data in enumerate(table):
        if data:
            token_bytes[token, : len(data)] = np.frombuffer(data, np.uint8)
            token_lengths[token] = len(data)

    # advance every (state, token) pair through the byte DFA, vectorized
    # over the full [S, V] grid one byte position at a time
    current = np.broadcast_to(
        np.arange(num_states, dtype=np.int32)[:, None], (num_states, vocab_size)
    ).copy()
    for position in range(max_len):
        live = (token_lengths > position)[None, :] & (current >= 0)
        stepped = byte_transition[
            np.clip(current, 0, None), token_bytes[:, position][None, :]
        ]
        current = np.where(live, stepped, current)
    transition = np.where(token_lengths[None, :] > 0, current, -1).astype(np.int32)

    # EOS in accepting states (self-loop), forbidden elsewhere
    transition[:, eos] = np.where(accepting, np.arange(num_states, dtype=np.int32), -1)

    # prune states that cannot reach acceptance through TOKEN edges: a
    # token-level dead end would leave the sampler an all--inf row
    alive = accepting.copy()
    changed = True
    while changed:
        reaches = (transition >= 0) & alive[np.clip(transition, 0, None)]
        new_alive = alive | reaches.any(axis=1)
        changed = bool((new_alive != alive).any())
        alive = new_alive
    if not alive[0]:
        raise ValueError(
            f"guided_regex pattern {pattern!r} cannot be realised by this "
            f"tokenizer's vocabulary"
        )
    dead_target = (transition >= 0) & ~alive[np.clip(transition, 0, None)]
    transition[dead_target] = -1

    return ChoiceAutomaton(
        transition=transition, num_states=num_states, choices=("regex", pattern)
    )


__all__ = ["compile_regex_automaton", "token_byte_table", "MAX_REPEAT"]
