"""Prompt construction for explanation generation.

Honours the AIProvider CR's ``promptTemplate`` (reference
aiprovider-crd.yaml:46-48); the default template instructs the model to
answer in the Root Cause / Fix sections that downstream event truncation
preserves (reference EventService.java:282-301).

Context management for long logs (SURVEY.md §5 long-context entry): rather
than shipping the whole log, the prompt carries the top-scoring match
windows — the selection the pattern engine already did — plus the log tail,
within a fixed character budget so batched prefill lengths stay bounded.
"""

from __future__ import annotations

from typing import Optional

from ..schema.analysis import AnalysisRequest, AnalysisResult

#: the preamble before the first placeholder is STATIC across every
#: request, so the engine caches its KV once (set_shared_prefix) and each
#: admission prefills only the variable remainder — keep new static
#: instructions above the first ``{`` and variable content below it
DEFAULT_TEMPLATE = """You are a Kubernetes failure analyst. A pod failed; your job is to name the root cause and the most direct fix.

Ground rules:
- Trust the pattern analysis and the quoted log evidence over speculation; if they conflict, say which you believe and why.
- Distinguish the root cause from its symptoms (a CrashLoopBackOff is a symptom; the exception or exit code behind it is the cause).
- Common causes worth checking against the evidence: out-of-memory kills (exit 137, OOMKilled), failed liveness/readiness probes, image pull errors, missing config/secrets, permission errors, disk pressure or eviction, dependency outages (databases, DNS, upstream services), and application exceptions at startup.
- Name concrete Kubernetes objects and fields in the fix when the evidence identifies them (limits, probes, image tags, env vars).
- If the evidence is insufficient for a confident diagnosis, say so and name the single most useful signal to collect next.

Pod: {pod_name} (namespace {namespace})
Pattern analysis (severity {severity}): {patterns}

Strongest log evidence:
{evidence}

Recent log tail:
{log_tail}

Answer concisely with exactly two sections:
Root Cause: <one or two sentences naming the root cause>
Fix: <the most direct remediation>"""

#: budgets keep batched prefill bounded (32 concurrent events -> one prefill,
#: BASELINE config 4)
MAX_EVIDENCE_CHARS = 1600
MAX_TAIL_CHARS = 1200
#: retrieval-augmented context from incident memory (near-miss recall,
#: operator_tpu/memory/recall.py) rides the SAME budget discipline —
#: injecting prior incidents must never blow up the prefill bucket
MAX_PRIOR_INCIDENT_CHARS = 1200


def pack_blocks(blocks: "list[str]", budget: int, *, sep: str = "\n---\n") -> str:
    """The one budget-aware block packer every prompt section uses: take
    blocks in order, truncating the block that crosses the char budget and
    dropping the rest.  Evidence selection and prior-incident injection
    share this so neither can silently exceed its slice of the prompt."""
    kept: list[str] = []
    used = 0
    for block in blocks:
        block = block.strip()
        if not block:
            continue
        remaining = budget - used
        if remaining <= 0:
            break
        if len(block) > remaining:
            block = block[:remaining]
        kept.append(block)
        used += len(block)
    return sep.join(kept)


def _pattern_summary(result: Optional[AnalysisResult]) -> str:
    if result is None or not result.events:
        return "no known failure patterns matched"
    parts = []
    for event in result.top_events(3):
        if event.matched_pattern is None:
            continue
        parts.append(f"{event.matched_pattern.name} (score {event.score:.2f})")
    return "; ".join(parts) or "no named patterns"


def _evidence(result: Optional[AnalysisResult]) -> str:
    if result is None:
        return "(none)"
    blocks = [
        event.context.render()
        for event in result.top_events(3)
        if event.context is not None
    ]
    return pack_blocks(blocks, MAX_EVIDENCE_CHARS) or "(none)"


def prior_incident_section(request: AnalysisRequest) -> str:
    """Render near-miss recalls as an appended prompt section ("" when
    there are none).  Appended AFTER the template so the static preamble —
    and its shared-prefix KV registration — is untouched."""
    priors = request.prior_incidents
    if not priors:
        return ""
    blocks = []
    for i, prior in enumerate(priors):
        if not prior.explanation:
            continue
        head = (
            f"[{i + 1}] similarity {prior.score:.2f}, "
            f"seen {prior.seen_count}x"
            + (f", severity {prior.severity}" if prior.severity else "")
            + (f", last {prior.last_seen}" if prior.last_seen else "")
        )
        blocks.append(f"{head}\n{prior.explanation}")
    body = pack_blocks(blocks, MAX_PRIOR_INCIDENT_CHARS)
    if not body:
        return ""
    return (
        "\n\nSimilar previously-analyzed incidents (for context; this "
        "failure is NOT identical to them — diagnose the evidence above "
        "on its own merits):\n" + body
    )


def build_warmup_prompt() -> str:
    """A production-shaped prompt for engine warmup (operator/app.py).

    Starts with the template's static preamble (so the PREFIXED prefill
    bucket compiles, not just the plain one) and pads evidence/log_tail to
    their production CHAR budgets with log-shaped filler: prefill programs
    are keyed by the power-of-two bucket of the suffix TOKEN length, so
    the filler must tokenize at real log density — tiny dummy fields (or
    repeated single chars, which BPE packs very differently) would warm a
    different bucket than real explanation prompts use.  Lives next to
    DEFAULT_TEMPLATE so a placeholder change updates both or neither."""
    line = ("2026-01-01T00:00:00Z ERROR connection refused "
            "connecting to upstream service on port 8080\n")
    evidence = (line * (MAX_EVIDENCE_CHARS // len(line) + 1))[:MAX_EVIDENCE_CHARS]
    log_tail = (line * (MAX_TAIL_CHARS // len(line) + 1))[:MAX_TAIL_CHARS]
    return DEFAULT_TEMPLATE.format(
        pod_name="warmup", namespace="warmup", severity="NONE",
        patterns="warmup", evidence=evidence, log_tail=log_tail,
    )


def template_preamble(template: str) -> "str | None":
    """The static preamble of a prompt template — everything above its
    first ``{`` placeholder — IF the template actually renders.

    The one extraction rule for every shared-prefix registration site
    (engine build, the operator's startup CR scan, the provider's lazy
    path): a template whose ``format`` raises falls back to
    DEFAULT_TEMPLATE in :func:`build_prompt`, so registering ITS preamble
    would hold KV pages and a registry slot for a prefix no rendered
    prompt ever starts with — such templates return None."""
    if not template or not template.strip():
        return None
    probe = {
        "pod_name": "p", "namespace": "n", "severity": "NONE",
        "patterns": "x", "evidence": "x", "log_tail": "x",
    }
    try:
        template.format(**probe)
    except Exception:  # noqa: BLE001 - ANY render failure (KeyError,
        # AttributeError from '{x.y}', TypeError from '{x[0]}' on str, ...)
        # means build_prompt will fall back to DEFAULT_TEMPLATE, and the
        # caller sites must never be taken down by a malformed CR template
        return None
    return template.split("{", 1)[0]


def build_prompt(request: AnalysisRequest) -> str:
    from ..patterns.windows import tail_chars  # local import keeps serving lean

    result = request.analysis_result
    config = request.provider_config
    template = (config.prompt_template if config and config.prompt_template else DEFAULT_TEMPLATE)
    failure = request.failure_data
    pod = failure.pod if failure else None
    log_tail = tail_chars(failure.logs if failure else "", MAX_TAIL_CHARS)
    fields = {
        "pod_name": (pod.metadata.name if pod else None) or (result.pod_name if result else None) or "unknown",
        "namespace": (pod.metadata.namespace if pod else None)
        or (result.pod_namespace if result else None)
        or "unknown",
        "severity": (result.summary.highest_severity if result else None) or "NONE",
        "patterns": _pattern_summary(result),
        "evidence": _evidence(result),
        "log_tail": log_tail or "(no logs)",
    }
    try:
        rendered = template.format(**fields)
    except (KeyError, IndexError, ValueError):
        # user template with unknown placeholders: fall back to default
        rendered = DEFAULT_TEMPLATE.format(**fields)
    # retrieval-augmented context (near-miss recall) appends AFTER the
    # render: the template's static preamble stays byte-identical, so the
    # shared-prefix KV cache keeps matching these prompts
    return rendered + prior_incident_section(request)
