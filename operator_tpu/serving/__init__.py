"""TPU serving plane — in-tree replacement for the reference's external
ai-interface service (SURVEY.md §2.2, §7 stage 4).

``prompts`` is model-free; the batching engine, KV cache, and the
``tpu-native`` provider backend live in the sibling modules and import jax
lazily so the control plane runs on accelerator-less machines.
"""

from .prompts import DEFAULT_TEMPLATE, build_prompt

__all__ = [
    "DEFAULT_TEMPLATE",
    "build_prompt",
    # lazy (import jax): serving.engine — BatchedGenerator, ServingEngine,
    # SamplingParams, GenerationResult; serving.provider —
    # TPUNativeProvider, build_serving_engine, build_tpu_native_provider;
    # serving.httpserver — CompletionServer (OpenAI-compatible API;
    # `python -m operator_tpu.serving` serves it standalone)
]
