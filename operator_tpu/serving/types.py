"""Serving data types shared by the engine, admission, and program layers.

Split out of serving/engine.py (round 5) so the admission-policy and
program-builder modules can import them without a cycle; the public import
surface is unchanged (serving.engine re-exports everything here).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass(frozen=True)
class SamplingParams:
    max_tokens: int = 256
    temperature: float = 0.3  # reference default, aiprovider-crd.yaml:56-58
    top_p: float = 0.95
    stop_on_eos: bool = True
    #: LoRA adapter name for this request (multi-LoRA serving: every slot
    #: picks its own adapter from the generator's stacked registry; None =
    #: base model).  Unknown names are rejected at admission.
    adapter: Optional[str] = None
    #: constrain the output to one of these strings (serving/guided.py):
    #: a token-trie automaton rides the decode scan as device state and
    #: masks the sampler every step.  None = unconstrained.
    guided_choice: Optional[tuple] = None
    #: constrain the output to match this regex (serving/regex_dfa.py:
    #: byte-level DFA, token closure, same device-state machinery).
    #: Mutually exclusive with guided_choice.
    guided_regex: Optional[str] = None
    #: absolute time.monotonic() deadline for this request (deadline
    #: budget, utils/deadline.py).  Admission rejects a request whose
    #: roofline decode estimate cannot fit the residue, or clamps
    #: max_tokens to what does fit (admission.deadline_policy); an entry
    #: that expires while queued fails with DeadlineExceeded.  None = no
    #: budget.
    deadline: Optional[float] = None
    #: set by admission when max_tokens was clamped to fit the deadline —
    #: the finish reason then reads "deadline" instead of "length"
    deadline_clamped: bool = False
    #: set when the overload ladder truncated analysis depth (max_tokens
    #: scaled down under pressure, admission.deadline_policy): the finish
    #: reason then reads "degraded" — degrade-before-reject, distinct
    #: from deadline clamping
    degraded: bool = False
    #: recall-hit probability from memory/recall.py's predictor: a
    #: recalled incident costs ~4% of a cold analysis, so this rides into
    #: the request's overload value (router/value.py) — recalled work is
    #: shed only after all cold work of equal-or-lower class
    recall_p: float = 0.0
    #: obs trace id of the request's analysis (operator_tpu/obs/): the
    #: engine stamps it into its jax.profiler prefill/decode annotations
    #: so an xplane capture joins the flight recorder's timeline.  None =
    #: untraced (external API caller without a traceparent).
    trace_tag: Optional[str] = None
    #: SLO class this request is accounted under (obs/sloledger.py): the
    #: engine's per-class SLOBoard buckets attainment + goodput by it and
    #: /healthz carries the rollup.  None = the board's "default" bucket.
    slo_class: Optional[str] = None


@dataclass
class GenerationResult:
    text: str
    token_ids: list[int]
    prompt_tokens: int
    completion_tokens: int
    finish_reason: str  # "stop" | "length" | "deadline" (budget-clamped) | "degraded" (overload-truncated)
    prefill_ms: float = 0.0
    #: decode wall DERIVED FROM THE STEP CLOCK (obs/steptrace.py): the
    #: cumulative attributed wall of decode-bearing steps this request
    #: lived through — the same records /metrics histograms and black-box
    #: dumps carry, so span timings and step records cannot disagree
    decode_ms: float = 0.0
    #: submit -> admission wall (measured, not inferred as wall minus
    #: compute — the coarse delta the engine.generate span used to carry)
    queue_wait_ms: float = 0.0

    @property
    def total_ms(self) -> float:
        return self.prefill_ms + self.decode_ms


@dataclass
class _Slot:
    active: bool = False
    prompt_len: int = 0
    generated: list[int] = field(default_factory=list)
    params: SamplingParams = field(default_factory=SamplingParams)
    started: float = 0.0
    prefill_ms: float = 0.0
    pages: list[int] = field(default_factory=list)  # paged mode only
    #: step-clock decode cumulative (StepRing.decode_cum_ms) when the slot
    #: went live — _finish derives decode_ms as the delta, eviction-proof
    decode_cum0: float = 0.0
    queue_wait_ms: float = 0.0


@dataclass
class _PrefillJob:
    """An in-progress chunked prefill (engine.prefill_chunk).

    Device state (the bucket mini cache and the running last-token logits)
    carries across chunk calls; host arrays describe the admitted wave the
    same way _admit_batch's one-shot path does."""

    key: tuple  # (n_pad, t_pad)
    ids: Any  # [n_pad, t_pad] device tokens
    lengths_np: Any
    lengths: Any  # device
    temp: Any
    top_p: Any
    slot_ids_np: Any  # padded rows duplicate row 0
    taken: list
    params_list: list
    page_grants: list
    adapter_idx: Any  # device or None
    mini: Any  # KVCache carry
    last_logits: Any  # [n_pad, vocab] carry
    written: int
    chunk_ms: float = 0.0  # accumulated chunk compute (not interleaved wall)


class OversizedRequest(ValueError):
    """A single request needs more KV pages than the whole cache holds."""


class DeadlineExceeded(RuntimeError):
    """The request's deadline budget cannot fit even one decoded token
    (rejected at submit), or expired while the request was queued."""


class ShedLowValue(RuntimeError):
    """The overload ladder shed this request: under storm its value score
    (router/value.py) fell below the rising cutoff and its SLO class was
    not protected — shed-lowest-value-first, after degradation already
    fired."""


def _bucket(n: int, floor: int, cap: int) -> int:
    """Smallest power-of-two >= n, clamped to [floor, cap]."""
    size = floor
    while size < n and size < cap:
        size *= 2
    return min(size, cap)


def prompt_budget(max_seq: int, max_tokens: int) -> int:
    """Prompt-token budget for truncation: leave room for at least one
    generated token, and never let the generation reservation eat more
    than half the sequence.  The ONE formula both admission paths use
    (AdmissionMixin.admit and the continuous Scheduler.enqueue) — a
    drift here would make the two modes truncate the same prompt
    differently."""
    return max_seq - max(1, min(max_tokens, max_seq // 2))


def pages_needed(
    prompt_tokens: int, max_tokens: int, max_seq: int, page_size: int
) -> int:
    """Worst-case KV pages a request needs (prompt + full generation,
    clamped to the sequence cap) — the grant both admission paths make
    up front so the page table stays static for the row's lifetime."""
    total = min(prompt_tokens + max_tokens, max_seq)
    return -(-total // page_size)


class PageAllocator:
    """Host-side free list for the paged KV cache (ops/paged_attention.py).

    Page 0 is reserved as the trash page: padded prefill rows and released
    slots write there, so a page handed to a live sequence is never touched
    by anyone else.  Allocation is worst-case up front (prompt + max new
    tokens), which keeps the device page table static for a sequence's
    whole lifetime — no mid-decode growth, no host sync in the decode loop.
    """

    def __init__(self, num_pages: int) -> None:
        assert num_pages >= 2, "need at least one real page beyond the trash page"
        self.num_pages = num_pages
        self._free = list(range(num_pages - 1, 0, -1))  # pop() yields low ids first

    @property
    def available(self) -> int:
        return len(self._free)

    def allocate(self, count: int) -> list[int]:
        if count > len(self._free):
            raise MemoryError(f"KV pages exhausted: want {count}, have {len(self._free)}")
        return [self._free.pop() for _ in range(count)]

    def release(self, pages: list[int]) -> None:
        self._free.extend(pages)
