"""Minimal typed models for the core-Kubernetes objects the system touches:
Pods (failure detection), Events (result channel), Secrets (credentials),
ReplicaSets/Deployments (owner-chase for event targeting).

Field coverage mirrors what the reference actually reads:
- container terminated state w/ exit code   (reference PodFailureWatcher.java:147-159)
- restart counts / lastState                (reference PodmortemReconciler.java:121-128)
- events.k8s.io/v1 Event shape              (reference EventService.java:158-203)
- owner references Pod->ReplicaSet->Deployment (reference EventService.java:224-256)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .meta import K8sObject, ObjectMeta
from .serde import wire


@dataclass
class ContainerStateTerminated:
    exit_code: Optional[int] = None
    signal: Optional[int] = None
    reason: Optional[str] = None
    message: Optional[str] = None
    started_at: Optional[str] = None
    finished_at: Optional[str] = None


@dataclass
class ContainerStateWaiting:
    reason: Optional[str] = None  # e.g. CrashLoopBackOff, ImagePullBackOff
    message: Optional[str] = None


@dataclass
class ContainerState:
    terminated: Optional[ContainerStateTerminated] = None
    waiting: Optional[ContainerStateWaiting] = None
    running: Optional[dict] = None


@dataclass
class ContainerStatus:
    name: Optional[str] = None
    ready: Optional[bool] = None
    restart_count: int = 0
    state: Optional[ContainerState] = None
    last_state: Optional[ContainerState] = None
    image: Optional[str] = None


@dataclass
class PodStatus:
    phase: Optional[str] = None  # Pending|Running|Succeeded|Failed|Unknown
    reason: Optional[str] = None
    message: Optional[str] = None
    container_statuses: list[ContainerStatus] = field(default_factory=list)
    init_container_statuses: list[ContainerStatus] = field(default_factory=list)
    start_time: Optional[str] = None


@dataclass
class Container:
    name: Optional[str] = None
    image: Optional[str] = None


@dataclass
class PodSpec:
    containers: list[Container] = field(default_factory=list)
    node_name: Optional[str] = None


@dataclass
class Pod(K8sObject):
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    def __post_init__(self) -> None:
        super().__post_init__()
        self.api_version = self.api_version or "v1"
        self.kind = self.kind or "Pod"


@dataclass
class ObjectReference:
    api_version: Optional[str] = None
    kind: Optional[str] = None
    name: Optional[str] = None
    namespace: Optional[str] = None
    uid: Optional[str] = None


@dataclass
class Event(K8sObject):
    """events.k8s.io/v1 Event (reference EventService.java:158-203)."""

    reason: Optional[str] = None
    note: Optional[str] = None  # the message body (1024-byte budget)
    type_: Optional[str] = wire("type", default=None)  # Normal | Warning
    regarding: Optional[ObjectReference] = None
    reporting_controller: Optional[str] = None
    reporting_instance: Optional[str] = None
    action: Optional[str] = None
    event_time: Optional[str] = None

    def __post_init__(self) -> None:
        super().__post_init__()
        self.api_version = self.api_version or "events.k8s.io/v1"
        self.kind = self.kind or "Event"


@dataclass
class Secret(K8sObject):
    """Opaque secret; ``data`` values are base64-encoded on the wire, exactly
    as the reference consumes them (reference AIInterfaceClient.java:138-139)."""

    data: dict[str, str] = field(default_factory=dict)
    string_data: dict[str, str] = field(default_factory=dict)
    type_: Optional[str] = wire("type", default=None)

    def __post_init__(self) -> None:
        super().__post_init__()
        self.api_version = self.api_version or "v1"
        self.kind = self.kind or "Secret"

    def decoded(self, key: str) -> Optional[str]:
        import base64

        if key in self.string_data:
            return self.string_data[key]
        raw = self.data.get(key)
        if raw is None:
            return None
        try:
            return base64.b64decode(raw).decode("utf-8").strip()
        except Exception:
            return raw


@dataclass
class LeaseSpec:
    """coordination.k8s.io/v1 LeaseSpec — the fields leader election
    reads/writes (operator/lease.py)."""

    holder_identity: Optional[str] = None
    lease_duration_seconds: Optional[int] = None
    acquire_time: Optional[str] = None  # RFC3339 MicroTime
    renew_time: Optional[str] = None  # RFC3339 MicroTime
    lease_transitions: Optional[int] = None


@dataclass
class Lease(K8sObject):
    """The leader-election lock object: whoever is in
    ``spec.holderIdentity`` with a fresh ``renewTime`` runs the control
    plane; everyone else is a hot standby."""

    spec: LeaseSpec = field(default_factory=LeaseSpec)

    def __post_init__(self) -> None:
        super().__post_init__()
        self.api_version = self.api_version or "coordination.k8s.io/v1"
        self.kind = self.kind or "Lease"


@dataclass
class ReplicaSet(K8sObject):
    def __post_init__(self) -> None:
        super().__post_init__()
        self.api_version = self.api_version or "apps/v1"
        self.kind = self.kind or "ReplicaSet"


@dataclass
class DeploymentSpec:
    replicas: Optional[int] = None


@dataclass
class Deployment(K8sObject):
    spec: DeploymentSpec = field(default_factory=DeploymentSpec)

    def __post_init__(self) -> None:
        super().__post_init__()
        self.api_version = self.api_version or "apps/v1"
        self.kind = self.kind or "Deployment"


@dataclass
class EndpointAddress:
    """One ready (or not-ready) pod IP behind a Service subset."""

    ip: Optional[str] = None
    hostname: Optional[str] = None
    node_name: Optional[str] = None


@dataclass
class EndpointPort:
    name: Optional[str] = None
    port: Optional[int] = None
    protocol: Optional[str] = None


@dataclass
class EndpointSubset:
    """core/v1 EndpointSubset: the (addresses x ports) cross product the
    headless serving Service publishes — what ``router/discovery.py``
    turns into consistent-hash ring members."""

    addresses: list[EndpointAddress] = field(default_factory=list)
    not_ready_addresses: list[EndpointAddress] = field(default_factory=list)
    ports: list[EndpointPort] = field(default_factory=list)


@dataclass
class Endpoints(K8sObject):
    """core/v1 Endpoints for the headless serving Service: the
    membership source of truth the endpoint-watch discovery loop
    (docs/SCALING.md) lists + watches."""

    subsets: list[EndpointSubset] = field(default_factory=list)

    def __post_init__(self) -> None:
        super().__post_init__()
        self.api_version = self.api_version or "v1"
        self.kind = self.kind or "Endpoints"


@dataclass
class ScaleSpec:
    replicas: int = 0


@dataclass
class ScaleStatus:
    replicas: int = 0


@dataclass
class Scale(K8sObject):
    """autoscaling/v1 Scale — the Deployment ``scale`` subresource shape
    the autoscale controller (operator/autoscale.py) reads and patches."""

    spec: ScaleSpec = field(default_factory=ScaleSpec)
    status: ScaleStatus = field(default_factory=ScaleStatus)

    def __post_init__(self) -> None:
        super().__post_init__()
        self.api_version = self.api_version or "autoscaling/v1"
        self.kind = self.kind or "Scale"
