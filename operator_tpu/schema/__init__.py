"""Typed schema layer — replaces the reference's external ``common-lib``
artifact and hand-written CRD YAMLs (SURVEY.md §2.2, §7 stage 1)."""

from .analysis import (
    AIProviderConfig,
    AIResponse,
    AnalysisEvent,
    AnalysisRequest,
    AnalysisResult,
    AnalysisSummary,
    MatchContext,
    MatchedPattern,
    PodFailureData,
    Severity,
    StageTimings,
)
from .crds import (
    API_VERSION,
    GROUP,
    VERSION,
    AIProvider,
    AIProviderRef,
    AIProviderSpec,
    AIProviderStatus,
    AuthenticationRef,
    PatternLibrary,
    PatternLibrarySpec,
    PatternLibraryStatus,
    PatternRepository,
    PodFailureStatus,
    Podmortem,
    PodmortemSpec,
    PodmortemStatus,
    RepositoryCredentials,
    SecretRef,
    SyncedRepository,
    parse_refresh_interval,
)
from .kube import (
    Container,
    ContainerState,
    ContainerStateTerminated,
    ContainerStateWaiting,
    ContainerStatus,
    Deployment,
    DeploymentSpec,
    EndpointAddress,
    EndpointPort,
    Endpoints,
    EndpointSubset,
    Event,
    Lease,
    LeaseSpec,
    ObjectReference,
    Pod,
    PodSpec,
    PodStatus,
    ReplicaSet,
    Scale,
    ScaleSpec,
    ScaleStatus,
    Secret,
)
from .meta import (
    K8sObject,
    LabelSelector,
    LabelSelectorRequirement,
    ObjectMeta,
    OwnerReference,
    now_iso,
)
from .patterns import (
    ContextExtraction,
    LibraryMetadata,
    Pattern,
    PatternLibraryFile,
    PrimaryPattern,
    Remediation,
    SecondaryPattern,
)
from .serde import from_dict, to_dict

__all__ = [name for name in dir() if not name.startswith("_")]
