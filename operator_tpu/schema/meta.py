"""Kubernetes object metadata + label-selector semantics.

Replaces the fabric8 model classes the reference leans on.  Notably we
implement *full* ``LabelSelector`` matching — ``matchLabels`` **and**
``matchExpressions`` — where the reference only honours ``matchLabels``
(reference PodFailureWatcher.java:247-265 ignores the ``matchExpressions``
field its own CRD declares at podmortem-crd.yaml:26-39).
"""

from __future__ import annotations

import datetime
from dataclasses import dataclass, field
from typing import Any, Optional

from .serde import from_dict, to_dict


def now_iso() -> str:
    """RFC3339 UTC timestamp, the Kubernetes wire format for times."""
    return (
        datetime.datetime.now(datetime.timezone.utc)
        .isoformat(timespec="seconds")
        .replace("+00:00", "Z")
    )


@dataclass
class OwnerReference:
    api_version: Optional[str] = None
    kind: Optional[str] = None
    name: Optional[str] = None
    uid: Optional[str] = None
    controller: Optional[bool] = None


@dataclass
class ObjectMeta:
    name: Optional[str] = None
    namespace: Optional[str] = None
    uid: Optional[str] = None
    resource_version: Optional[str] = None
    generation: Optional[int] = None
    creation_timestamp: Optional[str] = None
    deletion_timestamp: Optional[str] = None
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    owner_references: list[OwnerReference] = field(default_factory=list)


@dataclass
class LabelSelectorRequirement:
    """One matchExpressions entry (podmortem-crd.yaml:29-39)."""

    key: Optional[str] = None
    operator: Optional[str] = None  # In | NotIn | Exists | DoesNotExist
    values: list[str] = field(default_factory=list)


@dataclass
class LabelSelector:
    match_labels: dict[str, str] = field(default_factory=dict)
    match_expressions: list[LabelSelectorRequirement] = field(default_factory=list)

    def is_empty(self) -> bool:
        return not self.match_labels and not self.match_expressions

    def matches(self, labels: Optional[dict[str, str]]) -> bool:
        """Kubernetes label-selector semantics.

        An empty selector matches everything (the reference treats this the
        same way: PodFailureWatcher.java:251-254).
        """
        labels = labels or {}
        for key, want in self.match_labels.items():
            if labels.get(key) != want:
                return False
        for req in self.match_expressions:
            have = req.key in labels
            value = labels.get(req.key)
            op = (req.operator or "").lower()
            if op == "in":
                if value not in (req.values or []):
                    return False
            elif op == "notin":
                if have and value in (req.values or []):
                    return False
            elif op == "exists":
                if not have:
                    return False
            elif op == "doesnotexist":
                if have:
                    return False
            else:  # unknown operator: fail closed
                return False
        return True


@dataclass
class K8sObject:
    """Base for anything with apiVersion/kind/metadata."""

    api_version: Optional[str] = None
    kind: Optional[str] = None
    metadata: ObjectMeta = field(default_factory=ObjectMeta)

    def __post_init__(self) -> None:
        if self.metadata is None:
            self.metadata = ObjectMeta()

    # --- identity helpers -------------------------------------------------
    @property
    def name(self) -> Optional[str]:
        return self.metadata.name

    @property
    def namespace(self) -> Optional[str]:
        return self.metadata.namespace

    def qualified_name(self) -> str:
        return f"{self.metadata.namespace or '_'}/{self.metadata.name}"

    # --- serde ------------------------------------------------------------
    def to_dict(self) -> dict[str, Any]:
        return to_dict(self)

    @classmethod
    def parse(cls, data: dict[str, Any]):
        return from_dict(cls, data)
