"""The three custom resources of the system — Podmortem, AIProvider,
PatternLibrary — as typed dataclasses.

Field-for-field parity with the reference CRDs:
- Podmortem       reference podmortem-crd.yaml:19-82
- AIProvider      reference aiprovider-crd.yaml:19-69
- PatternLibrary  reference patternlibrary-crd.yaml:19-87

plus the pieces the reference declared but never implemented, which we do
implement: per-repo sync status (reference PatternLibraryReconciler.java:171-176
is a stub) and AIProvider status reconciliation (no AIProvider reconciler
exists in the reference at all — SURVEY.md §2.1).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Optional

from .meta import K8sObject, LabelSelector

GROUP = "podmortem.tpu.dev"
VERSION = "v1alpha1"
API_VERSION = f"{GROUP}/{VERSION}"


# --------------------------------------------------------------------------
# Podmortem
# --------------------------------------------------------------------------


@dataclass
class AIProviderRef:
    """spec.aiProviderRef (reference podmortem-crd.yaml:40-49)."""

    name: Optional[str] = None
    namespace: Optional[str] = None


@dataclass
class PodmortemSpec:
    pod_selector: LabelSelector = field(default_factory=LabelSelector)
    ai_provider_ref: Optional[AIProviderRef] = None
    ai_analysis_enabled: bool = True  # default true (podmortem-crd.yaml:50-53)
    #: end-to-end budget for one failure's analysis ("90s"/"2m"/"1h30m",
    #: parse_refresh_interval grammar); None = the operator default, which
    #: mirrors the reference's 180 s external-LLM envelope
    #: (application.properties:8-9).  Enforced at every hop: collection
    #: slice, parse cap, AI remainder, engine admission clamp.
    analysis_deadline: Optional[str] = None


@dataclass
class FailureRecurrence:
    """status.recentFailures[].recurrence — how incident memory classified
    this failure (operator_tpu/memory/): the stable fingerprint, how often
    the class has been seen fleet-wide, and whether the stored analysis
    was reused instead of re-generated."""

    fingerprint: Optional[str] = None
    seen_count: int = 0
    first_seen: Optional[str] = None
    reused_analysis: bool = False


@dataclass
class PodFailureStatus:
    """One entry of status.recentFailures (reference podmortem-crd.yaml:68-82,
    written by AnalysisStorageService.java:286-333)."""

    pod_name: Optional[str] = None
    pod_namespace: Optional[str] = None
    failure_time: Optional[str] = None
    analysis_status: Optional[str] = None  # Analyzed|PatternOnly|Failed|degraded|deadline-exceeded
    explanation: Optional[str] = None
    severity: Optional[str] = None
    #: deadline-budget outcome for the AI leg (utils/deadline.py):
    #: completed | truncated (max_tokens clamped to fit the residual
    #: budget) | degraded (overload ladder reduced analysis depth,
    #: router/value.py) | shed (ladder dropped the request) |
    #: deadline-exceeded (degraded to pattern-only)
    deadline_outcome: Optional[str] = None
    #: incident-memory classification (None when memory is disabled)
    recurrence: Optional[FailureRecurrence] = None
    #: flight-recorder trace id for this analysis (operator_tpu/obs/):
    #: ``GET /traces/{id}`` on the health port replays the span tree —
    #: where the deadline budget went, stage by stage
    trace_id: Optional[str] = None


@dataclass
class PodmortemStatus:
    phase: Optional[str] = None  # Pending|Ready|Processing|Error (crd:57-59)
    message: Optional[str] = None
    last_update_time: Optional[str] = None
    recent_failures: list[PodFailureStatus] = field(default_factory=list)
    observed_generation: Optional[int] = None


@dataclass
class Podmortem(K8sObject):
    spec: PodmortemSpec = field(default_factory=PodmortemSpec)
    status: Optional[PodmortemStatus] = None

    def __post_init__(self) -> None:
        super().__post_init__()
        self.api_version = self.api_version or API_VERSION
        self.kind = self.kind or "Podmortem"
        if self.spec is None:
            self.spec = PodmortemSpec()


# --------------------------------------------------------------------------
# AIProvider
# --------------------------------------------------------------------------


@dataclass
class AuthenticationRef:
    """spec.authenticationRef (reference aiprovider-crd.yaml:28-35)."""

    secret_name: Optional[str] = None
    secret_key: Optional[str] = None


@dataclass
class AIProviderSpec:
    """Provider config.  ``provider_id`` values: ``tpu-native`` (in-tree TPU
    serving — the whole point of this rebuild), plus ``openai``-compatible
    HTTP fallback preserved for parity (reference aiprovider-crd.yaml:19-21).

    Defaults mirror reference AIInterfaceClient.java:78-84.
    """

    provider_id: Optional[str] = None
    api_url: Optional[str] = None
    model_id: Optional[str] = None
    authentication_ref: Optional[AuthenticationRef] = None
    timeout_seconds: int = 30
    max_retries: int = 3
    caching_enabled: bool = True
    prompt_template: Optional[str] = None
    max_tokens: int = 500
    temperature: float = 0.3
    additional_config: dict[str, str] = field(default_factory=dict)


@dataclass
class AIProviderStatus:
    phase: Optional[str] = None  # Pending|Ready|Failed (aiprovider-crd.yaml:67-69)
    message: Optional[str] = None
    last_validated: Optional[str] = None
    observed_generation: Optional[int] = None  # aiprovider-crd.yaml:73-75


@dataclass
class AIProvider(K8sObject):
    spec: AIProviderSpec = field(default_factory=AIProviderSpec)
    status: Optional[AIProviderStatus] = None

    def __post_init__(self) -> None:
        super().__post_init__()
        self.api_version = self.api_version or API_VERSION
        self.kind = self.kind or "AIProvider"
        if self.spec is None:
            self.spec = AIProviderSpec()


# --------------------------------------------------------------------------
# PatternLibrary
# --------------------------------------------------------------------------


@dataclass
class SecretRef:
    name: Optional[str] = None
    namespace: Optional[str] = None
    key: Optional[str] = None


@dataclass
class RepositoryCredentials:
    secret_ref: Optional[SecretRef] = None


@dataclass
class PatternRepository:
    """spec.repositories[] (reference patternlibrary-crd.yaml:19-41)."""

    name: Optional[str] = None
    url: Optional[str] = None
    branch: str = "main"  # default matches reference PatternSyncService.java:132
    credentials: Optional[RepositoryCredentials] = None


@dataclass
class PatternLibrarySpec:
    repositories: list[PatternRepository] = field(default_factory=list)
    refresh_interval: str = "1h"  # default (patternlibrary-crd.yaml:42-45)
    enabled_libraries: list[str] = field(default_factory=list)


@dataclass
class SyncedRepository:
    """status.syncedRepositories[] (patternlibrary-crd.yaml:65-82) — declared
    by the reference CRD but never populated (PatternLibraryReconciler.java:171-176
    stub); we populate it."""

    name: Optional[str] = None
    last_sync_time: Optional[str] = None
    last_sync_commit: Optional[str] = None
    status: Optional[str] = None  # Synced|Failed
    message: Optional[str] = None
    pattern_count: Optional[int] = None


@dataclass
class PatternLibraryStatus:
    phase: Optional[str] = None  # Pending|Syncing|Ready|Failed (crd:54-58)
    message: Optional[str] = None
    last_sync_time: Optional[str] = None
    synced_repositories: list[SyncedRepository] = field(default_factory=list)
    available_libraries: list[str] = field(default_factory=list)


@dataclass
class PatternLibrary(K8sObject):
    spec: PatternLibrarySpec = field(default_factory=PatternLibrarySpec)
    status: Optional[PatternLibraryStatus] = None

    def __post_init__(self) -> None:
        super().__post_init__()
        self.api_version = self.api_version or API_VERSION
        self.kind = self.kind or "PatternLibrary"
        if self.spec is None:
            self.spec = PatternLibrarySpec()


# --------------------------------------------------------------------------
# refresh-interval parsing
# --------------------------------------------------------------------------

_INTERVAL_RE = re.compile(r"(\d+)\s*([smhd])", re.IGNORECASE)
_UNIT_SECONDS = {"s": 1, "m": 60, "h": 3600, "d": 86400}


def parse_refresh_interval(text: Optional[str], default_seconds: int = 3600) -> int:
    """Parse ``30s`` / ``5m`` / ``1h`` / ``2d`` / compound ``1h30m`` into
    seconds (reference PatternLibraryReconciler.java:282-305 format set).

    Unparseable or empty input falls back to the 1h default, matching the
    CRD default (patternlibrary-crd.yaml:42-45).
    """
    if not text:
        return default_seconds
    text = text.strip()
    if text.isdigit():  # bare number == seconds
        return int(text)
    matches = _INTERVAL_RE.findall(text)
    consumed = "".join(f"{n}{u}" for n, u in matches).lower()
    if not matches or consumed != re.sub(r"\s+", "", text).lower():
        return default_seconds
    return sum(int(n) * _UNIT_SECONDS[u.lower()] for n, u in matches)
