"""Dataclass <-> JSON-ish dict conversion with Kubernetes camelCase keys.

The reference system's typed model layer lives in an external Maven artifact
(``com.redhat.podmortem:common``, reference pom.xml:95-99) whose Jackson
serialisation uses camelCase field names.  This module gives our dataclasses
the same wire shape: ``snake_case`` attribute names map to ``camelCase`` keys,
``None`` fields are omitted, nested dataclasses / lists / dicts / enums are
handled recursively, and unknown keys are ignored on input (Kubernetes objects
always carry fields we don't model).
"""

from __future__ import annotations

import dataclasses
import enum
import types
import typing
from typing import Any, Optional, TypeVar, Union, get_args, get_origin, get_type_hints

T = TypeVar("T")

_HINTS_CACHE: dict[type, dict[str, Any]] = {}


def snake_to_camel(name: str) -> str:
    head, *rest = name.split("_")
    return head + "".join(part[:1].upper() + part[1:] for part in rest)


def camel_to_snake(name: str) -> str:
    out = []
    for ch in name:
        if ch.isupper():
            out.append("_")
            out.append(ch.lower())
        else:
            out.append(ch)
    return "".join(out)


def _type_hints(cls: type) -> dict[str, Any]:
    hints = _HINTS_CACHE.get(cls)
    if hints is None:
        hints = get_type_hints(cls)
        _HINTS_CACHE[cls] = hints
    return hints


def to_dict(obj: Any, *, drop_none: bool = True) -> Any:
    """Recursively convert a dataclass tree to plain dicts with camelCase keys."""
    if isinstance(obj, enum.Enum):  # before the scalar check: str-enums are strs
        return obj.value
    if obj is None or isinstance(obj, (str, int, float, bool)):
        return obj
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        for f in dataclasses.fields(obj):
            if not f.metadata.get("serialize", True):
                continue
            value = getattr(obj, f.name)
            if value is None and drop_none:
                continue
            key = f.metadata.get("wire_name") or snake_to_camel(f.name)
            out[key] = to_dict(value, drop_none=drop_none)
        return out
    if isinstance(obj, dict):
        return {k: to_dict(v, drop_none=drop_none) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_dict(v, drop_none=drop_none) for v in obj]
    return obj


def _unwrap_optional(tp: Any) -> Any:
    origin = get_origin(tp)
    if origin is Union or origin is types.UnionType:
        args = [a for a in get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return tp


def _coerce(value: Any, tp: Any) -> Any:
    if value is None:
        return None
    tp = _unwrap_optional(tp)
    if tp is Any or tp is None:
        return value
    origin = get_origin(tp)
    if origin in (list, tuple):
        (elem_tp,) = get_args(tp) or (Any,)
        seq = [_coerce(v, elem_tp) for v in value]
        return tuple(seq) if origin is tuple else seq
    if origin is dict:
        args = get_args(tp)
        val_tp = args[1] if len(args) == 2 else Any
        return {k: _coerce(v, val_tp) for k, v in value.items()}
    if isinstance(tp, type):
        if dataclasses.is_dataclass(tp):
            return from_dict(tp, value)
        if issubclass(tp, enum.Enum):
            return tp(value)
        if tp is float and isinstance(value, int):
            return float(value)
    return value


def from_dict(cls: type[T], data: Optional[dict[str, Any]]) -> T:
    """Build dataclass ``cls`` from a camelCase dict, ignoring unknown keys.

    Missing keys — and keys explicitly set to JSON ``null``, which Kubernetes
    treats as unset — fall back to the field default; a field with no default
    becomes ``None`` (Kubernetes objects are pervasively partial, so we prefer
    permissiveness over hard failures at the deserialisation boundary).
    """
    if data is None:
        data = {}
    if not isinstance(data, dict):
        raise TypeError(f"expected dict for {cls.__name__}, got {type(data).__name__}")
    hints = _type_hints(cls)
    kwargs: dict[str, Any] = {}
    for f in dataclasses.fields(cls):
        key = f.metadata.get("wire_name") or snake_to_camel(f.name)
        if key not in data:
            key = f.name  # tolerate snake_case input too
        has_default = (
            f.default is not dataclasses.MISSING or f.default_factory is not dataclasses.MISSING
        )
        if data.get(key) is not None:
            kwargs[f.name] = _coerce(data[key], hints.get(f.name, Any))
        elif not has_default:
            kwargs[f.name] = None
    return cls(**kwargs)  # type: ignore[return-value]


def wire(name: str, **kw: Any) -> Any:
    """Field helper for attributes whose wire name isn't the camelCase of the
    python name (e.g. ``type_`` -> ``type``)."""
    metadata = dict(kw.pop("metadata", {}) or {})
    metadata["wire_name"] = name
    return dataclasses.field(metadata=metadata, **kw)
