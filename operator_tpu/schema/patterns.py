"""Pattern-library YAML schema.

The reference never shows the pattern file format — it lives in the unseen
``log-parser`` sibling repo; all that is structurally visible is: YAML files,
one library per file, and that matched patterns carry name/severity/score
(reference PatternSyncService.java:94-107, AnalysisStorageService.java:314-323).
We therefore define a compatible schema (SURVEY.md §2.2) with enough structure
for both the CPU regex scorer and the TPU semantic matcher:

```yaml
metadata:
  library_id: quarkus-patterns
  version: "1.0"
patterns:
  - id: port-conflict
    name: "Port already in use"
    severity: HIGH
    category: startup
    primary_pattern:
      regex: 'Port \\d+ already in use'
      confidence: 0.9
    secondary_patterns:
      - regex: 'java\\.net\\.BindException'
        weight: 0.5
        proximity_window: 20
    semantic_text: "server failed to start because the TCP port was taken"
    context_extraction: {lines_before: 5, lines_after: 3}
    remediation:
      description: "Another process owns the port..."
      common_causes: [...]
      suggested_commands: [...]
```
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Optional

import yaml

from .analysis import Severity
from .serde import from_dict, to_dict


@dataclass
class PrimaryPattern:
    regex: Optional[str] = None
    keywords: list[str] = field(default_factory=list)  # all must appear in a line
    confidence: float = 1.0

    def compiled(self) -> Optional[re.Pattern]:
        if not self.regex:
            return None
        return _compile_cached(self.regex)


@dataclass
class SecondaryPattern:
    """Corroborating evidence near the primary match; adds ``weight`` to the
    score when found within ``proximity_window`` lines."""

    regex: Optional[str] = None
    weight: float = 0.5
    proximity_window: int = 20

    def compiled(self) -> Optional[re.Pattern]:
        if not self.regex:
            return None
        return _compile_cached(self.regex)


@dataclass
class ContextExtraction:
    lines_before: int = 5
    lines_after: int = 3


@dataclass
class Remediation:
    description: Optional[str] = None
    common_causes: list[str] = field(default_factory=list)
    suggested_commands: list[str] = field(default_factory=list)
    documentation_links: list[str] = field(default_factory=list)


@dataclass
class Pattern:
    id: Optional[str] = None
    name: Optional[str] = None
    severity: str = "MEDIUM"
    category: Optional[str] = None
    primary_pattern: Optional[PrimaryPattern] = None
    secondary_patterns: list[SecondaryPattern] = field(default_factory=list)
    semantic_text: Optional[str] = None  # embedding anchor for the TPU matcher
    context_extraction: ContextExtraction = field(default_factory=ContextExtraction)
    remediation: Optional[Remediation] = None

    @property
    def severity_enum(self) -> Severity:
        return Severity.parse(self.severity)

    def anchor_text(self) -> str:
        """Text embedded for semantic matching: explicit anchor, else
        name + remediation description."""
        if self.semantic_text:
            return self.semantic_text
        parts = [self.name or self.id or ""]
        if self.remediation and self.remediation.description:
            parts.append(self.remediation.description)
        return ". ".join(p for p in parts if p)


@dataclass
class LibraryMetadata:
    library_id: Optional[str] = None
    version: Optional[str] = None
    description: Optional[str] = None


@dataclass
class PatternLibraryFile:
    """One YAML file == one library (reference PatternSyncService.java:94-107
    strips the extension to get the library name)."""

    metadata: LibraryMetadata = field(default_factory=LibraryMetadata)
    patterns: list[Pattern] = field(default_factory=list)

    def to_dict(self) -> dict[str, Any]:
        return to_dict(self)

    @classmethod
    def parse(cls, data: dict[str, Any]) -> "PatternLibraryFile":
        return from_dict(cls, data)

    @classmethod
    def load(cls, path) -> "PatternLibraryFile":
        with open(path, "r", encoding="utf-8") as f:
            data = yaml.safe_load(f) or {}
        lib = cls.parse(data)
        if not lib.metadata.library_id:
            import os

            lib.metadata.library_id = os.path.splitext(os.path.basename(str(path)))[0]
        return lib

    def dump(self, path) -> None:
        with open(path, "w", encoding="utf-8") as f:
            yaml.safe_dump(self.to_dict(), f, sort_keys=False)


_REGEX_CACHE: dict[str, re.Pattern] = {}


def _compile_cached(pattern: str) -> re.Pattern:
    compiled = _REGEX_CACHE.get(pattern)
    if compiled is None:
        compiled = re.compile(pattern)
        _REGEX_CACHE[pattern] = compiled
    return compiled
