"""Analysis-pipeline data models.

These replace the external ``common-lib`` classes whose shape is only visible
through usage in the reference (SURVEY.md §2.2):

- ``PodFailureData``  — what the operator collects and POSTs to the parser
  (reference LogParserClient.java:36, PodFailureWatcher.java:319-332).
- ``AnalysisResult``  — what the parser returns; the operator reads
  ``summary.highestSeverity``, ``summary.significantEvents``,
  ``events[].score`` and ``events[].matchedPattern.{name,severity}``
  (reference EventService.java:75-78, AnalysisStorageService.java:147-156,308-325).
- ``AnalysisRequest`` / ``AIResponse`` — the ai-interface contract
  (reference AIInterfaceClient.java:45-59).
- ``AIProviderConfig`` — resolved provider config incl. auth token
  (reference AIInterfaceClient.java:71-105).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Optional

from .kube import Event, Pod
from .serde import from_dict, to_dict


class Severity(str, enum.Enum):
    """Pattern severity ladder; ordering is by ``rank``."""

    CRITICAL = "CRITICAL"
    HIGH = "HIGH"
    MEDIUM = "MEDIUM"
    LOW = "LOW"
    INFO = "INFO"

    @property
    def rank(self) -> int:
        return _SEVERITY_RANK[self]

    @classmethod
    def parse(cls, value) -> "Severity":
        if isinstance(value, cls):
            return value
        if value is None:
            return cls.INFO
        try:
            return cls(str(value).upper())
        except ValueError:
            return cls.INFO

    @classmethod
    def highest(cls, values: list["Severity"]) -> "Severity":
        return max(values, key=lambda s: s.rank) if values else cls.INFO


_SEVERITY_RANK = {
    Severity.INFO: 0,
    Severity.LOW: 1,
    Severity.MEDIUM: 2,
    Severity.HIGH: 3,
    Severity.CRITICAL: 4,
}


@dataclass
class PodFailureData:
    """The failure evidence bundle (reference collectPodFailureData,
    PodFailureWatcher.java:310-345): the pod object, its raw log tail, and
    recent namespace events."""

    pod: Optional[Pod] = None
    logs: str = ""
    events: list[Event] = field(default_factory=list)
    collection_time: Optional[str] = None

    def to_dict(self) -> dict[str, Any]:
        return to_dict(self)

    @classmethod
    def parse(cls, data: dict[str, Any]) -> "PodFailureData":
        return from_dict(cls, data)


@dataclass
class MatchedPattern:
    """events[].matchedPattern (reference AnalysisStorageService.java:314-323)."""

    id: Optional[str] = None
    name: Optional[str] = None
    severity: Optional[str] = None
    category: Optional[str] = None
    remediation: Optional[str] = None


@dataclass
class MatchContext:
    """The log window that produced a match; feeds prompt construction."""

    line_number: Optional[int] = None
    matched_line: Optional[str] = None
    lines_before: list[str] = field(default_factory=list)
    lines_after: list[str] = field(default_factory=list)

    def render(self) -> str:
        return "\n".join([*self.lines_before, self.matched_line or "", *self.lines_after])


@dataclass
class AnalysisEvent:
    """One scored match (reference reads .score and .matchedPattern:
    AnalysisStorageService.java:308-325)."""

    score: float = 0.0
    matched_pattern: Optional[MatchedPattern] = None
    context: Optional[MatchContext] = None
    source: str = "regex"  # regex | keyword | semantic

    @property
    def severity(self) -> Severity:
        return Severity.parse(self.matched_pattern.severity if self.matched_pattern else None)


@dataclass
class AnalysisSummary:
    """summary block (reference EventService.java:75-78 reads
    highestSeverity + significantEvents)."""

    highest_severity: Optional[str] = None
    significant_events: int = 0
    total_events: int = 0
    score: float = 0.0


@dataclass
class StageTimings:
    """Per-stage latency accounting (milliseconds) — the observability the
    reference lacks entirely (SURVEY.md §5 tracing: none)."""

    collect_ms: Optional[float] = None
    parse_ms: Optional[float] = None
    embed_ms: Optional[float] = None
    prefill_ms: Optional[float] = None
    decode_ms: Optional[float] = None
    store_ms: Optional[float] = None
    total_ms: Optional[float] = None


@dataclass
class AnalysisResult:
    analysis_id: Optional[str] = None
    pod_name: Optional[str] = None
    pod_namespace: Optional[str] = None
    summary: AnalysisSummary = field(default_factory=AnalysisSummary)
    events: list[AnalysisEvent] = field(default_factory=list)
    timings: Optional[StageTimings] = None

    def top_events(self, k: int = 5) -> list[AnalysisEvent]:
        return sorted(self.events, key=lambda e: e.score, reverse=True)[:k]

    def pattern_summary_line(self) -> str:
        """The compact one-line summary stored when AI analysis is off
        (behavioural spec: reference AnalysisStorageService.java:142-156)."""
        if not self.events:
            return "No known failure patterns matched."
        top = self.top_events(1)[0]
        name = top.matched_pattern.name if top.matched_pattern else "unknown"
        sev = self.summary.highest_severity or "INFO"
        return (
            f"Pattern analysis: {name} (severity: {sev}, score: {top.score:.2f}); "
            f"{self.summary.significant_events} significant event(s)."
        )

    def to_dict(self) -> dict[str, Any]:
        return to_dict(self)

    @classmethod
    def parse(cls, data: dict[str, Any]) -> "AnalysisResult":
        return from_dict(cls, data)


@dataclass
class AIProviderConfig:
    """Resolved provider configuration handed to the inference backend
    (reference AIInterfaceClient.convertToProviderConfig :71-105, defaults
    :78-84, auth token resolved from a Secret :118-149)."""

    provider_id: Optional[str] = None
    api_url: Optional[str] = None
    model_id: Optional[str] = None
    auth_token: Optional[str] = None
    timeout_seconds: int = 30
    max_retries: int = 3
    caching_enabled: bool = True
    prompt_template: Optional[str] = None
    max_tokens: int = 500
    temperature: float = 0.3
    additional_config: dict[str, str] = field(default_factory=dict)


@dataclass
class PriorIncident:
    """One remembered incident injected into the prompt as
    retrieval-augmented context on a near-miss recall
    (memory/recall.py; rendered by serving/prompts.py)."""

    fingerprint: Optional[str] = None
    score: float = 0.0
    seen_count: int = 0
    severity: Optional[str] = None
    last_seen: Optional[str] = None
    explanation: Optional[str] = None


@dataclass
class AnalysisRequest:
    """POST body for explanation generation (reference
    AIInterfaceClient.java:45-59: wraps AnalysisResult + provider config)."""

    analysis_result: Optional[AnalysisResult] = None
    provider_config: Optional[AIProviderConfig] = None
    failure_data: Optional[PodFailureData] = None
    #: residual deadline budget (seconds) at dispatch time
    #: (utils/deadline.py): backends must finish inside it — the tpu-native
    #: engine clamps max_tokens to the roofline fit, the HTTP provider
    #: clamps its read timeout.  None = no budget (legacy callers).
    deadline_s: Optional[float] = None
    #: near-miss recalls from incident memory, best first — prompt
    #: construction appends them under a bounded char budget
    prior_incidents: list[PriorIncident] = field(default_factory=list)
    #: the failure-class fingerprint digest (memory/fingerprint.py) when
    #: incident memory computed one — the router's first-choice affinity
    #: key, so recurrences land on the replica whose recall cache is hot
    fingerprint: Optional[str] = None
    #: SLO class this analysis is accounted under (obs/sloledger.py) —
    #: the overload value model (router/value.py) weights shed decisions
    #: by it.  None = the ledger's default class.
    slo_class: Optional[str] = None
    #: recall-hit probability (memory/recall.py hit_probability): how
    #: likely this request resolves from incident memory instead of a
    #: cold analysis — a recalled request costs ~4% of a cold one, so
    #: this rides into its overload value score
    recall_p: float = 0.0

    def to_dict(self) -> dict[str, Any]:
        return to_dict(self)

    @classmethod
    def parse(cls, data: dict[str, Any]) -> "AnalysisRequest":
        return from_dict(cls, data)


@dataclass
class AIResponse:
    """Explanation response (reference AIInterfaceClient.java:45-59 reads
    ``.getExplanation()``); we add serving metadata."""

    explanation: Optional[str] = None
    provider_id: Optional[str] = None
    model_id: Optional[str] = None
    prompt_tokens: Optional[int] = None
    completion_tokens: Optional[int] = None
    cached: bool = False
    error: Optional[str] = None
    #: deadline-budget outcome: "completed" | "truncated" (output clamped
    #: to fit the residual budget) | "degraded" (overload ladder reduced
    #: analysis depth — distinct from deadline truncation) | "shed" (the
    #: ladder dropped the request; no AI text) | "deadline-exceeded" (no
    #: AI text; pipeline degrades to pattern-only).  None = budget not
    #: involved.
    deadline_outcome: Optional[str] = None
    #: which serving replica produced this response (operator_tpu/router/)
    #: — flight-recorder spans and routing forensics read it.  None =
    #: unrouted backend (template, in-process tpu-native).
    replica_id: Optional[str] = None
    #: cross-replica requeues the request survived before completing
    requeues: int = 0

    def to_dict(self) -> dict[str, Any]:
        return to_dict(self)

    @classmethod
    def parse(cls, data: dict[str, Any]) -> "AIResponse":
        return from_dict(cls, data)
