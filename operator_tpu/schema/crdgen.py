"""CRD YAML generation — the user-facing API surface of the system.

Produces CustomResourceDefinition manifests for Podmortem / AIProvider /
PatternLibrary with the same structural schema the reference ships by hand
(reference podmortem-crd.yaml, aiprovider-crd.yaml, patternlibrary-crd.yaml),
generated from one source of truth so code and API can't drift.
"""

from __future__ import annotations

from typing import Any

import yaml

from .crds import GROUP, VERSION


def _obj(properties: dict[str, Any], required: list[str] | None = None) -> dict[str, Any]:
    out: dict[str, Any] = {"type": "object", "properties": properties}
    if required:
        out["required"] = required
    return out


def _arr(items: dict[str, Any]) -> dict[str, Any]:
    return {"type": "array", "items": items}


_STR = {"type": "string"}
_INT = {"type": "integer"}
_NUM = {"type": "number"}
_BOOL = {"type": "boolean"}
_STR_ARR = _arr(_STR)
_STR_MAP = {"type": "object", "additionalProperties": _STR}


_LABEL_SELECTOR = _obj(
    {
        "matchLabels": _STR_MAP,
        "matchExpressions": _arr(
            _obj(
                {
                    "key": _STR,
                    "operator": {
                        "type": "string",
                        "enum": ["In", "NotIn", "Exists", "DoesNotExist"],
                    },
                    "values": _STR_ARR,
                },
                required=["key", "operator"],
            )
        ),
    }
)

_POD_FAILURE_STATUS = _obj(
    {
        "podName": _STR,
        "podNamespace": _STR,
        "failureTime": _STR,
        "analysisStatus": _STR,
        "explanation": _STR,
        "severity": _STR,
        "deadlineOutcome": {
            "type": "string",
            "enum": [
                "completed", "truncated", "degraded", "shed",
                "deadline-exceeded",
            ],
        },
        # incident-memory classification (operator_tpu/memory/): stable
        # failure fingerprint + fleet-wide recurrence accounting
        "recurrence": _obj(
            {
                "fingerprint": _STR,
                "seenCount": _INT,
                "firstSeen": _STR,
                "reusedAnalysis": _BOOL,
            }
        ),
        # flight-recorder trace id (operator_tpu/obs/): GET /traces/{id}
        # on the operator health port replays this analysis's span tree
        "traceId": _STR,
    }
)


def podmortem_crd() -> dict[str, Any]:
    """Parity: reference podmortem-crd.yaml:1-92."""
    spec_schema = _obj(
        {
            "podSelector": _LABEL_SELECTOR,
            "aiProviderRef": _obj({"name": _STR, "namespace": _STR}),
            "aiAnalysisEnabled": {"type": "boolean", "default": True},
            # end-to-end analysis budget ("90s"/"2m"/"1h30m", or bare
            # seconds); unset = the operator's 180 s default (the
            # reference's LLM envelope).  Every compound term requires a
            # unit — exactly the grammar parse_refresh_interval accepts,
            # so a value the apiserver admits can never silently fall
            # back to the default
            "analysisDeadline": {"type": "string", "pattern": r"^\d+$|^(\s*\d+\s*[smhd])+\s*$"},
        }
    )
    status_schema = _obj(
        {
            "phase": {"type": "string", "enum": ["Pending", "Ready", "Processing", "Error"]},
            "message": _STR,
            "lastUpdateTime": _STR,
            "recentFailures": _arr(_POD_FAILURE_STATUS),
            "observedGeneration": _INT,
        }
    )
    return _crd("podmortems", "Podmortem", "pm", spec_schema, status_schema)


def aiprovider_crd() -> dict[str, Any]:
    """Parity: reference aiprovider-crd.yaml:1-86 (defaults :36-62)."""
    spec_schema = _obj(
        {
            "providerId": _STR,
            "apiUrl": _STR,
            "modelId": _STR,
            "authenticationRef": _obj({"secretName": _STR, "secretKey": _STR}),
            "timeoutSeconds": {"type": "integer", "default": 30},
            "maxRetries": {"type": "integer", "default": 3},
            "cachingEnabled": {"type": "boolean", "default": True},
            "promptTemplate": _STR,
            "maxTokens": {"type": "integer", "default": 500},
            "temperature": {"type": "number", "default": 0.3},
            "additionalConfig": _STR_MAP,
        }
        # NB: no required fields — matches the reference, which declares none
        # (aiprovider-crd.yaml:16-62); validation happens in the reconciler.
    )
    status_schema = _obj(
        {
            "phase": {"type": "string", "enum": ["Pending", "Ready", "Failed"]},
            "message": _STR,
            "lastValidated": _STR,
            "observedGeneration": _INT,
        }
    )
    return _crd("aiproviders", "AIProvider", "aip", spec_schema, status_schema)


def patternlibrary_crd() -> dict[str, Any]:
    """Parity: reference patternlibrary-crd.yaml:1-99."""
    spec_schema = _obj(
        {
            "repositories": _arr(
                _obj(
                    {
                        "name": _STR,
                        "url": _STR,
                        "branch": {"type": "string", "default": "main"},
                        "credentials": _obj(
                            {"secretRef": _obj({"name": _STR, "namespace": _STR, "key": _STR})}
                        ),
                    },
                    required=["name", "url"],
                )
            ),
            "refreshInterval": {"type": "string", "default": "1h"},
            "enabledLibraries": _STR_ARR,
        }
    )
    status_schema = _obj(
        {
            "phase": {"type": "string", "enum": ["Pending", "Syncing", "Ready", "Failed"]},
            "message": _STR,
            "lastSyncTime": _STR,
            "syncedRepositories": _arr(
                _obj(
                    {
                        "name": _STR,
                        "lastSyncTime": _STR,
                        "lastSyncCommit": _STR,
                        "status": _STR,
                        "message": _STR,
                        "patternCount": _INT,
                    }
                )
            ),
            "availableLibraries": _STR_ARR,
        }
    )
    return _crd("patternlibraries", "PatternLibrary", "pl", spec_schema, status_schema)


def _crd(
    plural: str,
    kind: str,
    short: str,
    spec_schema: dict[str, Any],
    status_schema: dict[str, Any],
) -> dict[str, Any]:
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{plural}.{GROUP}"},
        "spec": {
            "group": GROUP,
            "names": {
                "kind": kind,
                "listKind": f"{kind}List",
                "plural": plural,
                "singular": kind.lower(),
                "shortNames": [short],
            },
            "scope": "Namespaced",
            "versions": [
                {
                    "name": VERSION,
                    "served": True,
                    "storage": True,
                    # status subresource, as in all three reference CRDs
                    "subresources": {"status": {}},
                    "schema": {
                        "openAPIV3Schema": _obj(
                            {"spec": spec_schema, "status": status_schema}
                        )
                    },
                }
            ],
        },
    }


def all_crds() -> list[dict[str, Any]]:
    return [podmortem_crd(), aiprovider_crd(), patternlibrary_crd()]


class _NoAliasDumper(yaml.SafeDumper):
    """The schema builders share leaf dicts (e.g. ``_STR``); without this the
    emitter would render them as YAML anchors/aliases, which is unreadable in
    a CRD manifest."""

    def ignore_aliases(self, data):  # noqa: ANN001
        return True


def render_all() -> str:
    """Multi-document YAML of all three CRDs (for ``kubectl apply -f -``)."""
    return yaml.dump_all(all_crds(), Dumper=_NoAliasDumper, sort_keys=False)


def check_manifest(path: str) -> bool:
    """True when the committed manifest at ``path`` matches the generated
    output (modulo trailing whitespace) — the drift check graftlint rule
    GL005 runs in CI; exposed here so ``--check`` works in regen loops."""
    import pathlib

    committed = pathlib.Path(path)
    if not committed.exists():
        return False
    return committed.read_text(encoding="utf-8").strip() == render_all().strip()


if __name__ == "__main__":
    import sys

    if "--check" in sys.argv[1:]:
        import pathlib

        args = [a for a in sys.argv[1:] if a != "--check"]
        target = args[0] if args else "deploy/crds/podmortem-crds.yaml"
        if not pathlib.Path(target).exists():
            # a path error must not read as a drift diagnosis
            print(f"{target} not found (run from the repo root, or pass "
                  f"the manifest path)", file=sys.stderr)
            sys.exit(2)
        if check_manifest(target):
            print(f"{target} matches crdgen output")
            sys.exit(0)
        print(
            f"{target} drifted from crdgen output — regenerate with "
            f"`python -m operator_tpu.schema.crdgen > {target}`",
            file=sys.stderr,
        )
        sys.exit(1)
    try:
        print(render_all())
    except BrokenPipeError:
        sys.stderr.close()
