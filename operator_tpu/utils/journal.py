"""Shared durable-journal helper — one crash-safe JSONL discipline.

``memory/store.py`` (incident journal), ``operator/claims.py`` (claim
ledger) and the flight recorder all follow the same append-only pattern;
before this module the first two each carried their own ~80-line copy, so
a durability fix had to land twice (PR 5 review).  :class:`Journal` is
that pattern, once:

- **load** — torn-line tolerance: a crash mid-append tears at most the
  final line; corrupt lines are counted and skipped, never the file;
- **append** — one JSON object per line, ``write`` + ``flush`` so the
  record is in the page cache before the caller proceeds;
- **compact** — rewrite to a temp file then atomic ``os.replace``; a
  crash mid-compaction leaves the old journal intact.

Two write modes:

- ``async_writes=False`` (incident store): IO runs on the calling thread
  — the store's mutations already run off the event loop
  (``asyncio.to_thread``), so direct writes block nobody that matters.
- ``async_writes=True`` (claim ledger): IO rides a dedicated writer
  thread (the ``obs/record.py`` pattern) and ``append`` returns after
  *enqueueing* — an NFS-class compaction stall holds the writer thread,
  never the event loop, so routine ledger traffic can no longer stall
  the lease renew loop and depose a healthy leader.  ``append(...,
  wait=True)`` blocks until the line is flushed: ``try_claim`` uses it
  to keep the durable-before-analysis-starts contract (which means that
  ONE wait can still queue behind an in-flight compaction on wedged
  storage — durability and non-blocking are irreconcilable there; the
  exposure shrinks from every append to the rare claim-during-
  compaction).  The single writer thread preserves append/compact order
  exactly as submitted.

Thread-safety contract: callers serialize their own ``append``/``compact``
calls (both adopters hold their store lock across every mutation); the
Journal adds no second lock of its own.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Callable, Optional

log = logging.getLogger(__name__)

__all__ = ["Journal"]


class Journal:
    """Append-only JSONL with torn-line-tolerant load and atomic
    compaction; see module docstring for the write modes."""

    def __init__(
        self,
        path: Optional[str],
        *,
        label: str = "journal",
        async_writes: bool = False,
    ) -> None:
        self.path = path
        self.label = label
        self._handle = None
        self._lines = 0
        #: set by :meth:`abandon` — the SIGKILL-simulation / deposed-
        #: leader state where further IO (INCLUDING jobs already queued
        #: on the writer thread) is discarded, mutating only the
        #: caller's memory
        self._abandoned = False
        self._async_writes = bool(path and async_writes)
        #: created by :meth:`open`, torn down by :meth:`close` — a closed
        #: journal must not park an idle writer thread for the process
        #: lifetime
        self._writer = None

    @property
    def lines(self) -> int:
        """Appended-line count since load/compaction — the caller's
        compaction-trigger input (approximate across threads is fine)."""
        return self._lines

    # -- load ----------------------------------------------------------
    def load(self, replay: Callable[[dict], None]) -> int:
        """Replay every parseable line through ``replay``; corrupt or
        torn lines are skipped with a warning (losing at most the one
        mutation that was mid-write).  Returns the loaded count and
        resets the line counter to it."""
        self._lines = 0
        if not self.path or not os.path.exists(self.path):
            return 0
        loaded = dropped = 0
        with open(self.path, encoding="utf-8", errors="replace") as handle:
            for line in handle:
                line = line.strip()
                if not line:
                    continue
                try:
                    replay(json.loads(line))
                    loaded += 1
                except (ValueError, KeyError, TypeError):
                    dropped += 1
        self._lines = loaded
        if dropped:
            log.warning("%s %s: skipped %d corrupt line(s)",
                        self.label, self.path, dropped)
        return loaded

    # -- handle lifecycle ---------------------------------------------
    def open(self) -> None:
        """(Re)open the append handle, creating parent directories; in
        writer-thread mode, (re)starts the writer too."""
        if not self.path:
            return
        self._abandoned = False
        if self._async_writes and self._writer is None:
            import concurrent.futures

            self._writer = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix=f"journal-{self.label}"
            )
        if self._writer is not None:
            self._submit(self._open_sync)
        else:
            self._open_sync()

    def _open_sync(self) -> None:
        assert self.path is not None
        directory = os.path.dirname(self.path)
        if directory:
            os.makedirs(directory, exist_ok=True)
        self._handle = open(self.path, "a", encoding="utf-8")

    def close(self, *, flush: bool = True) -> None:
        """Close the handle; with a writer thread, drains queued writes
        first (``flush=True``), then SHUTS the writer down — a closed
        ledger must not leak a parked thread per instance.  :meth:`open`
        restarts it (the reload path)."""
        if self._writer is not None:
            if flush:
                self.flush()
            self._submit(self._close_sync)
            self._writer.shutdown(wait=True)  # barrier incl. the close job
            self._writer = None
        else:
            self._close_sync()

    def _close_sync(self) -> None:
        if self._handle is not None:
            self._handle.close()
            self._handle = None

    def abandon(self) -> None:
        """Drop the handle WITHOUT flushing queued writes — the on-disk
        state a SIGKILL (or a deposed leader) leaves behind.  The flag
        is honoured ON the writer thread too: appends and compactions
        already queued when abandon() runs are discarded at execution
        (a deposed leader's stale compaction must never ``os.replace``
        the journal the new leader is writing).  :meth:`open` resumes."""
        self._abandoned = True
        if self._writer is not None:
            self._submit(self._close_sync)
        else:
            self._close_sync()

    # -- writes --------------------------------------------------------
    def append(self, record: dict, *, wait: bool = False) -> None:
        """Append one record.  Serialized NOW (the record may be live
        state mutated under the caller's lock); written on the calling
        thread, or enqueued to the writer thread when one is configured.
        ``wait=True`` blocks until the line is flushed — the
        durable-before-proceeding form ``try_claim`` relies on."""
        if not self.path or self._abandoned:
            return
        line = json.dumps(record, sort_keys=True) + "\n"
        self._lines += 1
        if self._writer is not None:
            future = self._writer.submit(self._append_sync, line)
            if wait:
                future.result()  # durable append: IO failure propagates
            else:
                future.add_done_callback(self._log_failure)
        else:
            self._append_sync(line)

    def _append_sync(self, line: str) -> None:
        if self._handle is None or self._abandoned:
            return
        self._handle.write(line)
        self._handle.flush()

    def compact(self, records: "list[dict]") -> None:
        """Rewrite the journal as exactly ``records`` — temp file, close
        the old handle, atomic ``os.replace``, reopen.  Serialized NOW;
        the IO runs wherever appends do (writer thread when configured,
        so a compaction stall on slow storage never blocks the caller)."""
        if not self.path or self._abandoned:
            return
        lines = [json.dumps(r, sort_keys=True) + "\n" for r in records]
        self._lines = len(lines)
        if self._writer is not None:
            self._submit(self._compact_sync, lines)
        else:
            self._compact_sync(lines)

    def _compact_sync(self, lines: "list[str]") -> None:
        if self._abandoned:  # queued before abandon(): discard, see abandon
            return
        assert self.path is not None
        tmp = f"{self.path}.tmp"
        with open(tmp, "w", encoding="utf-8") as handle:
            handle.writelines(lines)
        self._close_sync()
        os.replace(tmp, self.path)
        self._open_sync()

    # -- barriers ------------------------------------------------------
    def flush(self, timeout: Optional[float] = 5.0) -> None:
        """Barrier: every previously submitted write has hit disk (no-op
        without a writer thread — direct writes already flushed)."""
        if self._writer is not None:
            self._writer.submit(lambda: None).result(timeout)

    def _submit(self, fn, *args) -> None:
        assert self._writer is not None
        future = self._writer.submit(fn, *args)
        # surface IO failures in the log instead of swallowing them in a
        # never-examined Future (a full disk must be visible, and must
        # not fail the mutation that was being journaled)
        future.add_done_callback(self._log_failure)

    def _log_failure(self, future) -> None:
        exc = future.exception()
        if exc is not None and not isinstance(exc, AssertionError):
            log.warning("%s %s: journal IO failed: %s",
                        self.label, self.path, exc)
