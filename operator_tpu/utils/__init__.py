"""Cross-cutting utilities: config, timing/metrics."""

from .config import OperatorConfig
from .timing import METRICS, MetricsRegistry, StageStats

__all__ = ["OperatorConfig", "METRICS", "MetricsRegistry", "StageStats"]
