"""Cross-cutting utilities: config, timing/metrics, deadline budgets,
fault injection."""

from .config import OperatorConfig
from .deadline import Deadline
from .faultinject import FaultAction, FaultPlan
from .timing import METRICS, MetricsRegistry, StageStats

__all__ = [
    "OperatorConfig", "METRICS", "MetricsRegistry", "StageStats",
    "Deadline", "FaultAction", "FaultPlan",
]
