"""Per-stage latency metrics.

The reference has no tracing at all (SURVEY.md §5); the rebuild's north star
is a latency SLO (p50 < 2s), so stage timing is built in: every pipeline run
records detect→collect→parse→prefill→decode→store durations, and the
registry keeps streaming percentiles for the bench harness.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left, insort
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Iterator, Optional, Sequence

#: default bucket upper bounds (ms) for latency histograms: sub-millisecond
#: decode steps through multi-second queue waits under storm load
DEFAULT_BUCKETS_MS: "tuple[float, ...]" = (
    0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
    250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0,
)


def _le(bound: float) -> str:
    """Prometheus ``le`` label rendering (``0.5``, ``1``, ``2.5`` — no
    trailing zeros, so both exposition flavours parse it as a float)."""
    return f"{bound:g}"


@dataclass
class StageStats:
    """Rolling latency record for one named stage (bounded memory)."""

    name: str
    count: int = 0
    total_ms: float = 0.0
    max_ms: float = 0.0
    _sorted: list[float] = field(default_factory=list, repr=False)
    _cap: int = 4096

    def record(self, duration_ms: float) -> None:
        self.count += 1
        self.total_ms += duration_ms
        self.max_ms = max(self.max_ms, duration_ms)
        if len(self._sorted) >= self._cap:
            # drop a middle sample to stay bounded while keeping the tails
            del self._sorted[len(self._sorted) // 2]
        insort(self._sorted, duration_ms)

    @property
    def mean_ms(self) -> float:
        return self.total_ms / self.count if self.count else 0.0

    def percentile(self, q: float) -> float:
        if not self._sorted:
            return 0.0
        idx = min(len(self._sorted) - 1, int(q / 100.0 * len(self._sorted)))
        return self._sorted[idx]

    @property
    def p50_ms(self) -> float:
        return self.percentile(50)

    @property
    def p99_ms(self) -> float:
        return self.percentile(99)


class HistogramStats:
    """Fixed-bucket latency histogram (Prometheus semantics: each bucket
    counts observations ``<= le``; exposition renders the cumulative
    counts plus ``+Inf``/``_sum``/``_count``).  Per-bucket counts are
    stored raw and cumulated at render so `observe` stays O(log buckets)
    with constant memory — unlike StageStats there is no sample list to
    cap, which is what makes histograms the right shape for per-step
    and per-token observations at serving rates."""

    __slots__ = ("name", "bounds", "counts", "sum", "count")

    def __init__(
        self, name: str, bounds: Sequence[float] = DEFAULT_BUCKETS_MS
    ) -> None:
        self.name = name
        self.bounds: tuple[float, ...] = tuple(sorted(float(b) for b in bounds))
        self.counts: list[int] = [0] * (len(self.bounds) + 1)  # [-1] = +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        self.count += 1
        self.sum += value
        self.counts[bisect_left(self.bounds, value)] += 1

    def cumulative(self) -> "list[tuple[str, int]]":
        """``[(le_label, cumulative_count), ..., ("+Inf", count)]``."""
        out: list[tuple[str, int]] = []
        running = 0
        for bound, bucket in zip(self.bounds, self.counts):
            running += bucket
            out.append((_le(bound), running))
        out.append(("+Inf", self.count))
        return out


class MetricsRegistry:
    """Thread-safe registry of stage stats + counters."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._stages: dict[str, StageStats] = {}
        self._counters: dict[str, int] = {}
        # fixed-bucket histograms (step duration, host gap, queue wait,
        # TTFT, per-token latency — docs/METRICS.md "Histograms")
        self._histograms: dict[str, HistogramStats] = {}
        # last-value gauges (e.g. supervisor_restart_ready_seconds):
        # point-in-time observations where only the latest value matters
        self._gauges: dict[str, float] = {}
        # most recent exemplar per counter (a trace id, obs/record.py):
        # rendered OpenMetrics-style so an alert on a counter links
        # straight to the trace that last bumped it
        self._exemplars: dict[str, str] = {}
        # labeled counter series: name -> {sorted (k, v) label tuple: count}.
        # Flat counters stay in _counters; a labeled incr ALSO bumps the
        # flat total so existing counter() readers keep working.
        self._labeled: dict[str, dict[tuple, int]] = {}

    def stage(self, name: str) -> StageStats:
        with self._lock:
            stats = self._stages.get(name)
            if stats is None:
                stats = StageStats(name)
                self._stages[name] = stats
            return stats

    def record(self, name: str, duration_ms: float) -> None:
        with self._lock:
            stats = self._stages.get(name)
            if stats is None:
                stats = StageStats(name)
                self._stages[name] = stats
            stats.record(duration_ms)

    @contextmanager
    def timed(self, name: str) -> Iterator[None]:
        started = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, (time.perf_counter() - started) * 1e3)

    def observe(
        self,
        name: str,
        value: float,
        *,
        buckets: Optional[Sequence[float]] = None,
    ) -> None:
        """One histogram observation; the first call fixes the bucket
        bounds (later ``buckets=`` arguments are ignored — Prometheus
        cannot re-bucket a live series)."""
        with self._lock:
            hist = self._histograms.get(name)
            if hist is None:
                hist = HistogramStats(name, buckets or DEFAULT_BUCKETS_MS)
                self._histograms[name] = hist
            hist.observe(value)

    def histogram(self, name: str) -> Optional[HistogramStats]:
        with self._lock:
            return self._histograms.get(name)

    def incr(
        self,
        name: str,
        amount: int = 1,
        *,
        exemplar: Optional[str] = None,
        labels: Optional[dict] = None,
    ) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + amount
            if exemplar:
                self._exemplars[name] = exemplar
            if labels:
                series = self._labeled.setdefault(name, {})
                key = tuple(sorted((str(k), str(v)) for k, v in labels.items()))
                series[key] = series.get(key, 0) + amount

    def counter(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def labeled(self, name: str) -> "dict[tuple, int]":
        """Per-series counts for a labeled counter (keyed by the sorted
        ``(label, value)`` tuple); empty when never bumped with labels."""
        with self._lock:
            return dict(self._labeled.get(name, {}))

    def labeled_total(
        self, name: str, *, where: Optional[dict] = None
    ) -> int:
        """Sum of a labeled counter's series, optionally filtered to the
        series whose labels include every ``where`` pair."""
        with self._lock:
            series = self._labeled.get(name, {})
            if not where:
                return sum(series.values())
            need = {(str(k), str(v)) for k, v in where.items()}
            return sum(
                n for key, n in series.items() if need.issubset(set(key))
            )

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def gauge(self, name: str) -> Optional[float]:
        with self._lock:
            return self._gauges.get(name)

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "stages": {
                    name: {
                        "count": s.count,
                        "mean_ms": round(s.mean_ms, 3),
                        "p50_ms": round(s.p50_ms, 3),
                        "p99_ms": round(s.p99_ms, 3),
                        "max_ms": round(s.max_ms, 3),
                    }
                    for name, s in self._stages.items()
                },
                "counters": dict(self._counters),
            }
            if self._labeled:
                out["labeled"] = {
                    name: {
                        ",".join(f"{k}={v}" for k, v in key): n
                        for key, n in series.items()
                    }
                    for name, series in self._labeled.items()
                }
            if self._histograms:
                out["histograms"] = {
                    name: {
                        "buckets": dict(h.cumulative()),
                        "sum": round(h.sum, 3),
                        "count": h.count,
                    }
                    for name, h in self._histograms.items()
                }
            if self._gauges:
                out["gauges"] = {k: round(v, 6) for k, v in self._gauges.items()}
            if self._exemplars:
                # trace-id exemplars ride the JSON surface unconditionally
                # (no format constraints there, unlike the text exposition)
                out["exemplars"] = dict(self._exemplars)
            return out

    def prometheus(self, *, openmetrics: bool = False) -> str:
        """Prometheus text exposition (version 0.0.4) of the same data, so
        any standard scraper can consume the operator's metrics; stage
        latencies render as summaries with p50/p99 quantiles.

        ``openmetrics=True`` renders the OpenMetrics flavour instead
        (trailing ``# EOF``, counter exemplars): exemplars are ONLY legal
        there — a mid-line ``#`` in classic text makes the legacy parser
        reject the whole scrape, so the default exposition never emits
        them.  Servers switch on content negotiation
        (``Accept: application/openmetrics-text``)."""

        def sane(name: str) -> str:
            return "".join(c if c.isalnum() or c == "_" else "_" for c in name)

        lines: list[str] = []
        with self._lock:
            if self._stages:
                metric = "podmortem_stage_duration_milliseconds"
                lines.append(f"# HELP {metric} Per-stage latency (detect->store pipeline).")
                lines.append(f"# TYPE {metric} summary")
                for name, s in sorted(self._stages.items()):
                    stage = sane(name)
                    lines.append(f'{metric}{{stage="{stage}",quantile="0.5"}} {s.p50_ms:.3f}')
                    lines.append(f'{metric}{{stage="{stage}",quantile="0.99"}} {s.p99_ms:.3f}')
                    lines.append(f'{metric}_sum{{stage="{stage}"}} {s.total_ms:.3f}')
                    lines.append(f'{metric}_count{{stage="{stage}"}} {s.count}')
            for name, h in sorted(self._histograms.items()):
                # histograms are legal (and identical) in BOTH flavours:
                # cumulative le-buckets ending at +Inf, then _sum/_count
                metric = f"podmortem_{sane(name)}"
                lines.append(f"# TYPE {metric} histogram")
                for le, cumulative in h.cumulative():
                    lines.append(f'{metric}_bucket{{le="{le}"}} {cumulative}')
                lines.append(f"{metric}_sum {h.sum:.3f}")
                lines.append(f"{metric}_count {h.count}")
            for name, value in sorted(self._counters.items()):
                family = f"podmortem_{sane(name)}"
                metric = f"{family}_total"
                if openmetrics:
                    # OpenMetrics names the counter FAMILY without the
                    # _total suffix (the sample keeps it); declaring the
                    # family as ..._total makes the reference parser
                    # reject the exemplar-carrying sample — and the whole
                    # scrape with it
                    lines.append(f"# TYPE {family} counter")
                else:
                    lines.append(f"# TYPE {metric} counter")
                series = self._labeled.get(name)
                if series:
                    # labeled counters expose one sample per label set (the
                    # flat total stays on the JSON surface via counter());
                    # emitting BOTH would double every sum() over the family
                    for key in sorted(series):
                        labels = ",".join(
                            f'{sane(k)}="{v}"' for k, v in key
                        )
                        lines.append(f"{metric}{{{labels}}} {series[key]}")
                    continue
                exemplar = self._exemplars.get(name) if openmetrics else None
                if exemplar:
                    lines.append(
                        f'{metric} {value} # {{trace_id="{sane(exemplar)}"}} 1'
                    )
                else:
                    lines.append(f"{metric} {value}")
            for name, value in sorted(self._gauges.items()):
                metric = f"podmortem_{sane(name)}"
                lines.append(f"# TYPE {metric} gauge")
                lines.append(f"{metric} {value:.6g}")
            if openmetrics:
                lines.append("# EOF")
        return "\n".join(lines) + "\n"


#: process-wide default registry (dependency-inject a fresh one in tests)
METRICS = MetricsRegistry()
