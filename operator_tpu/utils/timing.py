"""Per-stage latency metrics.

The reference has no tracing at all (SURVEY.md §5); the rebuild's north star
is a latency SLO (p50 < 2s), so stage timing is built in: every pipeline run
records detect→collect→parse→prefill→decode→store durations, and the
registry keeps streaming percentiles for the bench harness.
"""

from __future__ import annotations

import threading
import time
from bisect import insort
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Iterator, Optional


@dataclass
class StageStats:
    """Rolling latency record for one named stage (bounded memory)."""

    name: str
    count: int = 0
    total_ms: float = 0.0
    max_ms: float = 0.0
    _sorted: list[float] = field(default_factory=list, repr=False)
    _cap: int = 4096

    def record(self, duration_ms: float) -> None:
        self.count += 1
        self.total_ms += duration_ms
        self.max_ms = max(self.max_ms, duration_ms)
        if len(self._sorted) >= self._cap:
            # drop a middle sample to stay bounded while keeping the tails
            del self._sorted[len(self._sorted) // 2]
        insort(self._sorted, duration_ms)

    @property
    def mean_ms(self) -> float:
        return self.total_ms / self.count if self.count else 0.0

    def percentile(self, q: float) -> float:
        if not self._sorted:
            return 0.0
        idx = min(len(self._sorted) - 1, int(q / 100.0 * len(self._sorted)))
        return self._sorted[idx]

    @property
    def p50_ms(self) -> float:
        return self.percentile(50)

    @property
    def p99_ms(self) -> float:
        return self.percentile(99)


class MetricsRegistry:
    """Thread-safe registry of stage stats + counters."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._stages: dict[str, StageStats] = {}
        self._counters: dict[str, int] = {}

    def stage(self, name: str) -> StageStats:
        with self._lock:
            stats = self._stages.get(name)
            if stats is None:
                stats = StageStats(name)
                self._stages[name] = stats
            return stats

    def record(self, name: str, duration_ms: float) -> None:
        with self._lock:
            stats = self._stages.get(name)
            if stats is None:
                stats = StageStats(name)
                self._stages[name] = stats
            stats.record(duration_ms)

    @contextmanager
    def timed(self, name: str) -> Iterator[None]:
        started = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, (time.perf_counter() - started) * 1e3)

    def incr(self, name: str, amount: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + amount

    def counter(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "stages": {
                    name: {
                        "count": s.count,
                        "mean_ms": round(s.mean_ms, 3),
                        "p50_ms": round(s.p50_ms, 3),
                        "p99_ms": round(s.p99_ms, 3),
                        "max_ms": round(s.max_ms, 3),
                    }
                    for name, s in self._stages.items()
                },
                "counters": dict(self._counters),
            }

    def prometheus(self) -> str:
        """Prometheus text exposition (version 0.0.4) of the same data, so
        any standard scraper can consume the operator's metrics; stage
        latencies render as summaries with p50/p99 quantiles."""

        def sane(name: str) -> str:
            return "".join(c if c.isalnum() or c == "_" else "_" for c in name)

        lines: list[str] = []
        with self._lock:
            if self._stages:
                metric = "podmortem_stage_duration_milliseconds"
                lines.append(f"# HELP {metric} Per-stage latency (detect->store pipeline).")
                lines.append(f"# TYPE {metric} summary")
                for name, s in sorted(self._stages.items()):
                    stage = sane(name)
                    lines.append(f'{metric}{{stage="{stage}",quantile="0.5"}} {s.p50_ms:.3f}')
                    lines.append(f'{metric}{{stage="{stage}",quantile="0.99"}} {s.p99_ms:.3f}')
                    lines.append(f'{metric}_sum{{stage="{stage}"}} {s.total_ms:.3f}')
                    lines.append(f'{metric}_count{{stage="{stage}"}} {s.count}')
            for name, value in sorted(self._counters.items()):
                metric = f"podmortem_{sane(name)}_total"
                lines.append(f"# TYPE {metric} counter")
                lines.append(f"{metric} {value}")
        return "\n".join(lines) + "\n"


#: process-wide default registry (dependency-inject a fresh one in tests)
METRICS = MetricsRegistry()
