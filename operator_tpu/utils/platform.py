"""Backend-pinning helper for every CPU-capable entry point.

A container sitecustomize may force-register the TPU plugin and set
``jax_platforms`` to it in every python process, so the environment
variable ``JAX_PLATFORMS=cpu`` alone does NOT stop ``jax.devices()``
from probing the TPU tunnel — and a dead or claimed tunnel hangs that
probe with no output.  Only a live ``jax.config`` update before any
backend query reliably pins another platform.

One shared site (scripts/_cpu_pin.py and the serving CLI both call
this) so the workaround cannot drift between entry points.
"""

from __future__ import annotations

import os


def pin_cpu_if_requested(force: bool = False) -> bool:
    """Pin jax to the cpu platform when requested; returns True if pinned.

    ``force=True`` pins unconditionally (for smoke modes that must never
    touch the tunnel even when the env var is unset).  Must run before
    any jax backend query.
    """
    if force or os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
        return True
    return False
