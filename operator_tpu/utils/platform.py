"""Backend-pinning helper for every CPU-capable entry point.

A container sitecustomize may force-register the TPU plugin and set
``jax_platforms`` to it in every python process, so the environment
variable ``JAX_PLATFORMS=cpu`` alone does NOT stop ``jax.devices()``
from probing the TPU tunnel — and a dead or claimed tunnel hangs that
probe with no output.  Only a live ``jax.config`` update before any
backend query reliably pins another platform.

One shared site (scripts/_cpu_pin.py and the serving CLI both call
this) so the workaround cannot drift between entry points.
"""

from __future__ import annotations

import os


def pin_cpu_if_requested(force: bool = False) -> bool:
    """Pin jax to the cpu platform when requested; returns True if pinned.

    ``force=True`` pins unconditionally (for smoke modes that must never
    touch the tunnel even when the env var is unset).  Must run before
    any jax backend query.
    """
    if force or os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
        return True
    return False


def enable_persistent_compilation_cache(path: str | None = None) -> str | None:
    """Enable jax's persistent executable cache so XLA programs survive
    process restarts (``path`` or env ``OPERATOR_TPU_XLA_CACHE_DIR``; no-op
    when neither is set).

    The payoff is on TPU, where the serving program grid costs minutes of
    Mosaic/XLA compiles per process: the experiment series pays it once
    across all its bench steps, an operator restart re-warms from disk
    instead of recompiling, and the driver's bench run shares the series'
    cache.  Returns the cache dir when enabled."""
    path = (path or os.environ.get("OPERATOR_TPU_XLA_CACHE_DIR", "")).strip()
    if not path:
        return None
    import jax

    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_enable_compilation_cache", True)
        jax.config.update("jax_compilation_cache_dir", path)
        # skip sub-second compiles: their disk round-trip costs more than
        # the recompile (measured on the cpu backend)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except OSError as exc:
        # an optimisation must never block startup: an unwritable cache
        # dir (dropped volume mount, read-only fs) just disables it
        import logging

        logging.getLogger(__name__).warning(
            "persistent XLA cache disabled: %s unusable (%s)", path, exc
        )
        return None
    return path
