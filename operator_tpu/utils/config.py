"""Operator configuration — the MicroProfile-Config equivalent.

Three tiers, mirroring the reference (SURVEY.md §5 config entry):
static defaults < environment variables < CR spec (runtime behaviour such as
AI on/off and provider params lives in the CRDs, not here).

Env mapping follows the reference's keys where they exist:
``podmortem.watch.namespaces`` -> ``PODMORTEM_WATCH_NAMESPACES``
(reference PodFailureWatcher.java:52-53), ``pattern.cache.directory`` ->
``PATTERN_CACHE_DIRECTORY`` (application.properties:4-5).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field, fields
from typing import Optional


@dataclass
class OperatorConfig:
    # --- watch / reconcile ------------------------------------------------
    watch_namespaces: list[str] = field(default_factory=list)  # empty = all
    watch_restart_delay_s: float = 5.0  # reference PodFailureWatcher.java:574
    reconcile_interval_s: float = 60.0

    # --- pattern cache / sync --------------------------------------------
    pattern_cache_directory: str = "/shared/patterns"  # application.properties:4-5
    git_binary: str = "git"
    sync_timeout_s: float = 120.0
    # budget for single control-loop apiserver calls outside an analysis
    # envelope (pattern-library status patches, secret reads, list sweeps):
    # enforced so a wedged apiserver connection stalls one reconcile tick,
    # not the whole reconciler forever (graftlint GL003)
    kube_call_timeout_s: float = 15.0

    # --- storage (reference AnalysisStorageService.java:48,74-76) ---------
    max_recent_failures: int = 10
    conflict_max_retries: int = 5
    conflict_backoff_base_s: float = 0.1  # 100ms * 2^n

    # --- events (reference EventService.java:32,81) -----------------------
    reporting_controller: str = "podmortem.operator"
    event_message_limit: int = 1024

    # --- analysis budgets (application.properties:7-11) -------------------
    parse_timeout_s: float = 30.0
    ai_timeout_s: float = 180.0
    log_tail_bytes: int = 1_000_000  # cap on fetched pod log
    # end-to-end deadline budget (utils/deadline.py): born when a failure
    # is CLAIMED, enforced at every hop; the reference's whole envelope is
    # its 180 s external-LLM read budget, so that is the default.  A
    # Podmortem CR overrides per-CR via spec.analysisDeadline.
    analysis_deadline_s: float = 180.0
    # slice of the remaining budget log collection may spend before the
    # pipeline degrades to events-only evidence
    collect_budget_fraction: float = 0.2
    # per-provider circuit breaker (operator/providers.py CircuitBreaker):
    # consecutive-failure trip -> open (AI skipped, pattern-only results)
    # -> half-open probe after the reset window
    breaker_failure_threshold: int = 5
    breaker_reset_s: float = 30.0

    # --- multi-replica data plane (operator_tpu/router/, docs/ROBUSTNESS.md)
    # the failover router in front of N serving replicas: an AIProvider
    # apiUrl naming several endpoints (comma-separated, or the per-pod DNS
    # of the headless serving Service) is dispatched with consistent-hash
    # affinity, per-replica breakers, load-fed shedding, and requeue-ONCE
    # failover carrying the residual deadline
    router_vnodes: int = 64
    # queue pressure (queued + inflight) past which the affinity owner is
    # considered overloaded and the router sheds to a lighter replica
    router_shed_pressure: int = 8
    # per-REPLICA breaker: tighter than the per-provider one — with N
    # replicas a sick one should drain fast (siblings absorb the traffic),
    # and its half-open probe re-admits it quickly once healthy
    router_replica_failure_threshold: int = 3
    router_replica_reset_s: float = 10.0
    # background /healthz polling (operator/app.py): the operator probes
    # every routed serving replica at this cadence and feeds the router's
    # HealthBoard, so load-fed shedding works even when no request
    # traffic is producing load reports; each probe is bounded by
    # kube_call_timeout_s.  0 = off (passive breaker-only gating).
    router_health_poll_s: float = 15.0
    # this serving replica's identity on GET /healthz ("" = POD_NAME, then
    # hostname) — what the router's probes and AIResponse.replica_id carry
    serving_replica_id: str = ""

    # --- HA / survivable control plane (docs/ROBUSTNESS.md) ----------------
    # lease-based leader election (operator/lease.py): watcher, reconcilers,
    # pattern sync, and the pipeline run ONLY while this replica holds the
    # coordination.k8s.io Lease; standbys keep probes + engine warm and take
    # over (re-list + claim resume) when the leader's renewTime expires.
    # Off by default so single-replica installs and tests are unchanged.
    leader_election: bool = False
    lease_name: str = "podmortem-tpu-operator"
    lease_namespace: str = ""  # "" = the api's namespace (or "default")
    lease_duration_s: float = 15.0
    lease_renew_period_s: float = 5.0
    lease_retry_period_s: float = 2.0
    # this replica's holder identity; the deployment injects POD_NAME via
    # the downward API, "" falls back to hostname-pid
    pod_name: str = ""
    # durable claim ledger (operator/claims.py): crash-safe JSONL of
    # claim→stage→terminal transitions; a restarted (or newly elected)
    # operator replays it and resumes non-terminal analyses with their
    # REMAINING deadline budget.  None = in-memory only (the pre-HA
    # dedupe semantics).  The shipped deployment points it at the
    # pattern-cache PVC next to the incident journal.
    claims_path: Optional[str] = None
    claims_max_entries: int = 10_000
    # graceful drain (SIGTERM): in-flight analyses get this long to finish
    # (their own deadlines usually end them sooner); then tasks are
    # cancelled, journals flushed, and the lease released
    shutdown_grace_s: float = 30.0
    # serving httpserver drain: after the listener closes, in-flight HTTP
    # handlers (and the engine waves they ride) get this long to complete.
    # Size it UNDER terminationGracePeriodSeconds minus the preStop sleep
    # and shutdown_grace_s, or the HTTP drain can eat the whole SIGTERM
    # budget before the analysis drain and journal flushes run
    serving_drain_grace_s: float = 30.0

    # --- serving-engine supervisor (serving/engine.py) ---------------------
    # watchdog over the decode loop: a step making no progress within the
    # stall budget — or a loop death — triggers an engine reset; in-flight
    # requests are requeued ONCE with their residual deadline, then failed
    # (podmortem_supervisor_{restart,requeue,gaveup}_total)
    engine_supervisor: bool = True
    # generous default: a step can legitimately hide a multi-second in-band
    # XLA compile (novel bucket) — only a genuinely wedged device should trip
    supervisor_stall_s: float = 120.0
    # how long the supervisor waits for an abandoned (stalled) decode thread
    # to come back before resetting device state under it anyway
    supervisor_join_grace_s: float = 10.0

    # --- incident memory (operator_tpu/memory/, docs/MEMORY.md) -----------
    # recall across failures: exact fingerprint hit reuses the stored
    # analysis (AI leg skipped), near hit injects prior incidents into the
    # prompt, miss analyzes then remembers
    memory_enabled: bool = True
    # JSONL journal path (crash-safe append); unset = in-memory only.
    # The shipped deployment points it at the pattern-cache PVC.
    memory_path: Optional[str] = None
    memory_max_entries: int = 2048
    memory_ttl_s: float = 604800.0  # 7d; 0 = no TTL (LRU bound only)
    # near-miss similarity threshold; 0 = the embedder's own default
    # (lexical hashing 0.3, MiniLM 0.45 — patterns/semantic.py)
    recall_threshold: float = 0.0
    recall_top_k: int = 3
    # ConfigMap name for PVC-less durability (snapshot flushed at most
    # every memory_flush_interval_s); empty = off
    memory_configmap: str = ""
    memory_flush_interval_s: float = 30.0
    # bearer token required by GET /incidents* on the health port ("" =
    # open, like the probes) — incident records quote log evidence, which
    # can carry secrets, so fleets with untrusted pod networks set this
    incidents_api_token: str = ""

    # --- observability (operator_tpu/obs/, docs/OBSERVABILITY.md) ---------
    # per-analysis tracing + flight recorder: every analysis produces a
    # span tree; deadline-exceeded / breaker-open / engine-error analyses
    # additionally dump a black-box record
    obs_enabled: bool = True
    # bounded in-memory ring of recent traces (GET /traces)
    trace_ring_capacity: int = 256
    # append-only JSONL of every completed trace (crash-safe, same
    # discipline as the incident journal); unset = ring only
    trace_journal_path: Optional[str] = None
    # black-box dumps (full trace + deadline ledger + fault-plan seed on
    # deadline-exceeded / breaker-open / engine-error); unset = the
    # trace journal path (or ring only when that is unset too)
    trace_blackbox_path: Optional[str] = None

    # --- storage text caps ------------------------------------------------
    # Kubernetes rejects objects whose TOTAL annotations exceed 256 KiB;
    # the stored AI text is truncated at this cap with an explicit
    # "…[truncated]" marker (full text still goes to CR status, itself
    # capped below against the ~1.5 MiB etcd object limit)
    max_annotation_chars: int = 8192
    max_status_explanation_chars: int = 32768

    # --- health / metrics endpoint (reference operator-deployment.yaml:61-78
    # probes /q/health/*; ours serves /healthz/* + /metrics) ---------------
    health_host: str = "0.0.0.0"
    health_port: int = 8080  # 0 = ephemeral (tests), -1 = disabled

    # --- serving ----------------------------------------------------------
    model_id: str = "tinyllama-1.1b"
    checkpoint_dir: Optional[str] = None
    # MiniLM-class sentence encoder for semantic pattern matching (the
    # subsumed log-parser's neural scorer); unset = lexical HashingEmbedder
    encoder_checkpoint_dir: Optional[str] = None
    max_batch_size: int = 32  # BASELINE config 4: 32 events -> one prefill
    # paged KV cache (ops/paged_attention.py): allocate HBM by actual
    # sequence need instead of max_seq per slot — the batch-32-at-8B-scale
    # memory fix (SURVEY.md §7 hard part c).  kv_pages=0 means worst-case
    # sizing (no oversubscription).
    kv_cache_mode: str = "paged"  # "paged" | "contiguous"
    kv_page_size: int = 64
    kv_pages: int = 0
    # decode steps fused per host round-trip (serving/engine.py): hides host
    # latency on K-1 of K tokens; admissions join at block boundaries
    decode_block: int = 4
    # decode-ahead lookahead (serving/engine.py step()): blocks left in
    # flight while the host processes older tokens; 2 hides the per-block
    # host<->device round trip, 1 = synchronous
    pipeline_depth: int = 2
    # chunked prefill (Sarathi-style): prefill at most this many prompt
    # tokens per engine round so long prefills don't stall in-flight
    # decodes; 0 = one-shot prefill (power of two when set)
    prefill_chunk: int = 0
    # continuous-batching scheduler (serving/sched/, docs/SERVING.md):
    # "continuous" (the DEFAULT since the decode-ahead/speculation PR)
    # replaces the wave machinery with the explicit
    # schedule→dispatch→commit loop over ONE ragged mixed prefill+decode
    # program — token-level admission into the running wave, per-token
    # slot/page recycling, decode-ahead pipelining and prompt-lookup
    # speculation.  Requires paged KV, no mesh, no guided/LoRA traffic
    # (provider falls back to wave with a loud warning).  "wave" is the
    # explicit opt-out and still owns guided/LoRA/mesh serving.
    sched_mode: str = "continuous"  # "continuous" | "wave"
    # max prefill tokens ONE row contributes to a step (Sarathi chunk)
    sched_chunk: int = 64
    # flat token axis of the mixed program (>= max_batch_size so a full
    # decode batch always fits); 0 = max(sched_chunk, max_batch_size)
    sched_token_budget: int = 0
    # decode-ahead pipelining (sched/scheduler.py): dispatched steps left
    # in flight while the next wave is planned from predicted row state;
    # 2 hides the per-step host round-trip, 1 = synchronous commit
    sched_pipeline_depth: int = 2
    # prompt-lookup self-speculation (sched/draft.py): greedy rows verify
    # up to spec_lookup_k draft tokens from their own prompt+generated
    # context per step — multiple committed tokens per host round-trip,
    # byte-identical greedy output by construction
    spec_decode: bool = True
    spec_lookup_k: int = 4
    # shared-prefix KV caching (engine.set_shared_prefix): the default
    # prompt template's static preamble is prefilled once and admissions
    # forward only their suffix; paged mode only, exact (causal) reuse
    prefix_cache: bool = True
    # automatic block-hash prefix caching for the continuous scheduler
    # (serving/kvstore.py): page-granular APC keyed by rolling hash over
    # page-aligned token blocks — admissions reuse any cached prompt
    # prefix, not just a registered template preamble
    kv_prefix_cache: bool = True
    # host-RAM offload tier for evicted prefix blocks (ops/kv_transfer.py):
    # pinned numpy pool size in MB; 0 = eviction simply forgets blocks
    kv_host_pool_mb: int = 0
    # token-level streaming resume (router/resume.py): journal path for
    # per-request generated-token checkpoints; on failover the survivor
    # re-prefills prompt+generated-so-far instead of restarting the
    # stream.  None/"" = off
    resume_checkpoint_path: Optional[str] = None
    # program-grid precompile at warmup (engine.precompile_grid): compile
    # every prefill/decode program admission can select BEFORE readiness
    # flips — a mid-run XLA compile is a multi-second p99 outlier.
    # "serving" = unguided grid; "full" adds guided variants; "off" = the
    # pre-r5 behavior (first bucket hit pays its compile in-band)
    warmup_grid: str = "serving"
    # nucleus-sampling candidate set (engine SAMPLE_TOP_K): top-p filtering
    # runs inside the top-k — raise for high-temperature diversity
    sample_top_k: int = 64
    # serving dtype: "int8" (weight-only per-channel quant, models/quant.py)
    # or "bf16".  int8 is the DEFAULT behind the parity gate (token-identical
    # greedy on the tiny models, tests/test_quant_parity.py): it halves HBM
    # weight traffic — decode at serving batch sizes is bandwidth-bound —
    # and fits Mistral-7B per chip on v5e (config 5)
    serving_dtype: str = "int8"
    # legacy override (pre-PR-10 name): when non-empty it wins over
    # serving_dtype, so existing WEIGHT_DTYPE deployments keep their pin
    weight_dtype: str = ""
    # persisted AOT executable cache (serving/aotcache.py): a directory
    # (PVC-backed in deploy/) where compiled serving programs are stored
    # and restored on boot — warm bring-up skips the warmup compile
    # entirely.  None/"" = off
    aot_cache_path: Optional[str] = None
    # multi-chip serving (BASELINE configs 3/5): "" = single device,
    # "auto" = plan_for(all local devices), or explicit "dp=2,tp=4[,fsdp=1]"
    serving_mesh: str = ""
    # production safety: without a checkpoint the engine would generate
    # noise from random weights; the provider factory refuses unless this
    # is set (tests/benches opt in explicitly)
    allow_random_weights: bool = False
    # multi-LoRA serving: a directory of `<name>.safetensors` adapter files
    # (parallel/lora.py save_lora) loaded into the stacked registry at
    # engine build; requests select by name (SamplingParams.adapter /
    # AIProvider additionalConfig.lora_adapter / API model field)
    lora_dir: Optional[str] = None
    lora_alpha: float = 16.0
    # OpenAI-compatible completion API (serving/httpserver.py) served from
    # the operator process on the SAME engine the tpu-native provider uses;
    # -1 = disabled (default), 0 = ephemeral port (tests)
    completion_api_port: int = -1
    completion_api_host: str = "0.0.0.0"
    completion_api_token: str = ""  # "" = no auth required
    # step clock (serving/perf.py, docs/OBSERVABILITY.md "Step clock"):
    # bounded ring of per-step decode-attribution records behind
    # /healthz, /fleet, black-box dumps and bench step_attribution
    step_ring_capacity: int = 512
    # POST /profile?seconds=N on-demand jax.profiler capture on the
    # serving API (off by default: captures cost device attention+disk)
    profile_enabled: bool = False
    profile_dir: str = "/tmp/operator-tpu-profile"
    # SLO ledger (obs/sloledger.py, docs/OBSERVABILITY.md "SLO ledger"):
    # class:target-seconds pairs every analysis is admitted under, and an
    # optional journal path for terminal records ("" / None = in-memory)
    slo_classes: str = "interactive:2,standard:30,batch:120"
    slo_ledger_path: Optional[str] = None
    # open-loop load generation (operator_tpu/loadgen/): the seed every
    # arrival-schedule draw derives from — same seed, byte-identical storm
    loadgen_seed: int = 0
    # --- value-aware overload control (router/value.py, docs/ROBUSTNESS.md
    # "Degradation ladder"): shed-lowest-value-first + degrade-before-reject
    # queue pressure at which the ladder starts DEGRADING (reduced
    # max_tokens, finish_reason "degraded") before anything is rejected;
    # 0 = half of shed_pressure
    degrade_pressure: int = 0
    # fraction of max_tokens a degraded request keeps (truncated analysis
    # depth — the first ladder rung)
    degrade_max_tokens_frac: float = 0.25
    # per-class attainment floor: a class whose live attainment
    # (obs/sloledger.py attainment_by_class) is below this is PROTECTED —
    # never shed, only degraded
    slo_attainment_target: float = 0.9
    # value-score bar at exactly shed_pressure; the bar rises linearly
    # with pressure beyond it, so deeper overload sheds progressively
    # higher-value work (smooth decay, not a cliff)
    shed_value_floor: float = 1.0
    # ladder shed line: queue pressure past which below-bar requests are
    # dropped outright (router_shed_pressure stays the router's
    # move-to-lighter-replica line; this one actually sheds)
    shed_pressure: int = 8
    # continuous-scheduler submit queue bound: at this depth enqueue
    # evicts the lowest-value non-protected request (0 = unbounded)
    sched_queue_limit: int = 0

    # --- serverless fleet (router/discovery.py, operator/autoscale.py,
    # docs/SCALING.md) -----------------------------------------------------
    # endpoint-watch fleet membership: list+watch the headless serving
    # Service's Endpoints and mutate the router's consistent-hash ring
    # live — joins pre-warmed via a health probe before taking traffic,
    # departures drain through the breaker/failover path
    discovery_enabled: bool = False
    discovery_service: str = "podmortem-serving"
    discovery_namespace: str = ""  # "" = the api's namespace (or "default")
    discovery_port: str = "http"  # EndpointPort NAME to route to
    discovery_scheme: str = "http"
    # gate joins on a successful /healthz probe (which also primes the
    # replica's KV prefix store with a load report) before ring insertion
    discovery_prewarm: bool = True
    # SLO-judged autoscaler (leader-only control loop): scales the serving
    # Deployment via the scale subresource on router fleet pressure +
    # per-class SLO attainment — including to ZERO when idle
    autoscale_enabled: bool = False
    autoscale_interval_s: float = 15.0
    autoscale_min_replicas: int = 0
    autoscale_max_replicas: int = 8
    # least-loaded healthy replica's queue pressure past which the fleet
    # bursts out (OverloadPolicy's fleet_pressure is the same signal the
    # degradation ladder keys on — scale-up is the rung ABOVE degrade)
    autoscale_target_pressure: float = 4.0
    autoscale_deployment: str = "podmortem-serving"
    autoscale_namespace: str = ""  # "" = the api's namespace (or "default")
    # idle window before the fleet scales to zero (only when
    # autoscale_min_replicas == 0); pending arrivals wake it back up
    scale_to_zero_idle_s: float = 600.0

    # --- fleet KV fabric (operator_tpu/fabric/, docs/FABRIC.md) -----------
    # peer-to-peer KV page transfer: an admission-time prefix miss
    # consults the fleet block index and fetches pages from a holder's
    # host pool over GET /kv/blocks/{hash} instead of recomputing.
    # Requires kv_prefix_cache and kv_host_pool_mb > 0 (fetched pages
    # land in the host pool; the existing one-DMA restore path revives
    # them on match)
    kv_fabric: bool = False
    # per-fetch deadline (seconds), clamped to the request's residual
    # budget at the call — a failed fetch must never cost more than the
    # recompute it replaced
    kv_fabric_fetch_timeout_s: float = 2.0
    # concurrent page fetches in flight per replica (bounded client)
    kv_fabric_concurrency: int = 4
    # mirror newly-registered prompt blocks into the host pool at
    # prefill completion (inside the commit step's host-sync window) so
    # peers can fetch them without waiting for eviction to spill them
    kv_fabric_mirror: bool = True
    # comma-separated peer base URLs whose /healthz inventories feed
    # this replica's fabric index (fabric/peers.py).  Hostnames are
    # DNS-expanded every poll round, so the single headless-Service name
    # (http://podmortem-serving:8000) covers the whole fleet.  "" (the
    # default) starts no poller: an in-process harness feeds the index
    # directly, and a standalone replica without peers has no fabric to
    # fetch from — the empty-index gate skips the prefetch entirely
    kv_fabric_peers: str = ""
    # seconds between peer inventory poll rounds
    kv_fabric_poll_s: float = 5.0
    # prefill/decode disaggregation role advertised on /healthz
    # (fabric/disagg.py): "prefill" | "decode" | "mixed".  A routing
    # preference, never a filter — mixed (the default) serves both
    # phases and a role-less fleet behaves exactly as before
    replica_role: str = "mixed"

    @classmethod
    def from_env(cls, env: Optional[dict[str, str]] = None) -> "OperatorConfig":
        env = dict(os.environ if env is None else env)
        cfg = cls()
        for f in fields(cls):
            key = f.name.upper()
            if f.name == "watch_namespaces":
                key = "PODMORTEM_WATCH_NAMESPACES"
            raw = env.get(key)
            if raw is None:
                continue
            if f.name == "watch_namespaces":
                cfg.watch_namespaces = [ns.strip() for ns in raw.split(",") if ns.strip()]
            elif f.type in ("float", float):
                cfg.__setattr__(f.name, float(raw))
            elif f.type in ("int", int):
                cfg.__setattr__(f.name, int(raw))
            elif f.type in ("bool", bool):
                cfg.__setattr__(f.name, raw.strip().lower() in ("1", "true", "yes", "on"))
            else:
                cfg.__setattr__(f.name, raw)
        return cfg
