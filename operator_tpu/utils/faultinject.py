"""Deterministic fault-injection harness for chaos tests.

A :class:`FaultPlan` is a seeded, DECLARATIVE schedule of faults keyed by
SITE — a dotted string naming a seam, e.g. ``kube.patch_status``,
``kube.watch.Pod``, ``git.clone``, ``http.provider``, ``engine.step``.
Each rule owns an ordered queue of actions consumed one per matching call
through its site; an exhausted rule passes every later call.  Because each
site consumes its own queue in call order, the fired-fault sequence per
site is deterministic regardless of event-loop interleaving across sites —
run the same scenario twice with equal plans and
``plan_a.trace() == plan_b.trace()`` holds byte-identically
(tests/test_chaos.py asserts exactly that).

Seams (each an opt-in ``fault_plan`` attribute, zero cost when ``None``):

- :class:`operator.kubeapi.FakeKubeApi` — every API op
  (``kube.<op>``), watch-stream open (``kube.watch_open.<kind>``) and
  per-event delivery (``kube.watch.<kind>``);
- :class:`operator.patternsync.GitSyncService` — subprocess git verbs
  (``git.clone`` / ``git.fetch`` / ...);
- :class:`operator.providers.OpenAICompatProvider` — each outbound HTTP
  attempt (``http.provider``, ctx ``attempt`` + ``replica``: a rule
  matching one replica id is a replica kill, a rule matching every
  attempt against it is a partition);
- :class:`router.core.EngineRouter` — each routed dispatch attempt
  (``router.dispatch``, ctx ``replica`` + ``attempt``) — the
  transport-agnostic replica-kill/partition seam for the multi-engine
  data plane;
- :class:`serving.engine.BatchedGenerator.step` — the engine step loop
  (``engine.step``: stalls and simulated device errors).

The ``seed`` drives :meth:`FaultPlan.bernoulli` and :meth:`FaultPlan.jitter`
(probabilistic/latency schedules materialised AT BUILD TIME into a fixed
action list), so even randomised plans replay identically.

Beyond fail/drop, plans shape LATENCY: a :func:`delay_` action (or a
seeded :meth:`FaultPlan.jitter` schedule) holds the seam call for its
seconds and then lets it succeed.  Async seams consume the plan through
``await fault_plan.apply_async(site, ...)`` so the hold is an
``asyncio.sleep``, never a blocked event loop; worker-thread seams
``time.sleep`` the value :meth:`FaultPlan.apply` returns.
"""

from __future__ import annotations

import asyncio
import fnmatch
import hashlib
import random
import time
from dataclasses import dataclass, field
from typing import Callable, Optional


@dataclass(frozen=True)
class FaultAction:
    """One injected behaviour: raise an exception, stall, shape latency,
    or pass."""

    kind: str  # "raise" | "sleep" | "delay" | "ok"
    make: Optional[Callable[[], BaseException]] = None
    seconds: float = 0.0
    label: str = ""

    def fire(self) -> None:
        if self.kind == "raise":
            assert self.make is not None
            raise self.make()
        if self.kind == "sleep":
            # sync seams only (engine step runs on the decode worker
            # thread); async seams should inject errors, not stalls
            time.sleep(self.seconds)

    def __repr__(self) -> str:
        if self.label:
            return f"<{self.kind}:{self.label}>"
        if self.kind in ("sleep", "delay"):
            return f"<{self.kind}:{self.seconds}>"
        return f"<{self.kind}>"


def raise_(factory: Callable[[], BaseException], label: str = "") -> FaultAction:
    """Action that raises ``factory()`` at the seam."""
    return FaultAction("raise", make=factory, label=label or getattr(factory, "__name__", ""))


def sleep_(seconds: float) -> FaultAction:
    """Action that stalls a SYNC seam for ``seconds`` (engine step)."""
    return FaultAction("sleep", seconds=seconds)


def delay_(seconds: float) -> FaultAction:
    """Latency-shaping action: the seam call SUCCEEDS but is held for
    ``seconds`` first.  Unlike :func:`sleep_` the plan never blocks the
    event loop for it — ``apply`` RETURNS the delay and the seam applies
    it in its own idiom (``await fault_plan.apply_async`` on async
    seams, ``time.sleep`` on worker-thread seams)."""
    return FaultAction("delay", seconds=round(float(seconds), 6))


#: explicit no-op entry for readable sequences like [err, OK, err]
OK = FaultAction("ok", label="ok")


def times(n: int, action: FaultAction) -> list[FaultAction]:
    """``n`` consecutive copies of ``action`` (e.g. a 409 storm)."""
    return [action] * n


@dataclass
class _Rule:
    pattern: str
    actions: list[FaultAction]
    after: int = 0  # matching calls let through before consumption starts
    match: Optional[Callable[..., bool]] = None
    seen: int = 0

    def spent(self) -> bool:
        return not self.actions


class FaultPlan:
    def __init__(self, seed: int = 0) -> None:
        self.seed = seed
        #: rng for bernoulli(); all draws happen at plan BUILD time so the
        #: materialised action lists — not the draws — drive execution
        self.rng = random.Random(seed)
        self._rules: list[_Rule] = []
        self._trace: list[tuple[str, int, str]] = []
        self._site_seq: dict[str, int] = {}

    # ---- declaration -----------------------------------------------------
    def rule(
        self,
        pattern: str,
        actions: "FaultAction | list[FaultAction]",
        *,
        after: int = 0,
        match: Optional[Callable[..., bool]] = None,
    ) -> "FaultPlan":
        """Declare faults for sites matching ``pattern`` (fnmatch globs:
        ``kube.*`` hits every API op).  ``actions`` are consumed in order,
        one per matching call; ``after=N`` lets the first N matching calls
        through untouched (e.g. drop a watch stream after N events);
        ``match(**ctx)`` further filters on seam context (kind, name, ...).
        Returns self for chaining."""
        if isinstance(actions, FaultAction):
            actions = [actions]
        self._rules.append(_Rule(pattern, list(actions), after=after, match=match))
        return self

    def bernoulli(self, n: int, p: float, action: FaultAction) -> list[FaultAction]:
        """A length-``n`` action list where each entry is ``action`` with
        probability ``p`` (else OK), drawn NOW from the plan's seeded rng —
        a probabilistic schedule that still replays byte-identically."""
        return [action if self.rng.random() < p else OK for _ in range(n)]

    def jitter(self, n: int, lo: float, hi: float) -> list[FaultAction]:
        """A length-``n`` list of :func:`delay_` actions with uniform
        ``[lo, hi)`` seconds drawn NOW from the plan's seeded rng — the
        latency-shaping analogue of :meth:`bernoulli`: jittered tails
        that still replay byte-identically (the drawn values, rounded
        into the action repr, are part of the trace)."""
        return [delay_(self.rng.uniform(lo, hi)) for _ in range(n)]

    # ---- consumption (called from the seams) -----------------------------
    def apply(self, site: str, **ctx) -> float:
        """Consult the plan at a seam; may raise or stall.  Every FIRED
        action is recorded in the trace as (site, per-site call index,
        action repr).

        Returns the latency-shaping delay in seconds (0.0 when no delay
        action fired).  ``delay`` actions are never slept here — the
        seam owns the idiom: async seams ``await`` it via
        :meth:`apply_async`, worker-thread seams ``time.sleep`` the
        returned value.  A seam that ignores the return simply does not
        support latency shaping (the action is still traced)."""
        seq = self._site_seq.get(site, 0)
        self._site_seq[site] = seq + 1
        for rule in self._rules:
            if not fnmatch.fnmatch(site, rule.pattern):
                continue
            if rule.match is not None and not rule.match(**ctx):
                continue
            rule.seen += 1
            if rule.seen <= rule.after:
                continue  # still inside the pass-through window
            if rule.spent():
                continue  # exhausted: later calls pass (or hit later rules)
            action = rule.actions.pop(0)
            self._trace.append((site, seq, repr(action)))
            if action.kind == "delay":
                return action.seconds
            action.fire()
            return 0.0
        return 0.0

    async def apply_async(self, site: str, **ctx) -> None:
        """:meth:`apply` for async seams: a fired ``delay``/``jitter``
        action becomes a non-blocking ``asyncio.sleep`` so latency
        shaping never stalls the event loop.  Raise actions propagate
        exactly as from :meth:`apply`."""
        seconds = self.apply(site, **ctx)
        if seconds > 0:
            await asyncio.sleep(seconds)

    # ---- replay verification --------------------------------------------
    def trace(self) -> list[tuple[str, int, str]]:
        """Ordered (site, per-site call index, action) of every fired
        fault.  Two runs of one scenario with equal plans produce equal
        traces — the determinism contract chaos tests assert."""
        return list(self._trace)

    def fingerprint(self) -> str:
        """Stable hash of the trace for compact replay assertions."""
        basis = "\n".join(f"{s}#{i}:{a}" for s, i, a in self._trace)
        return hashlib.sha256(basis.encode()).hexdigest()

    def pending(self) -> dict[str, int]:
        """Unconsumed actions per rule pattern — lets a test assert its
        whole plan actually fired (a chaos test whose faults never hit
        their seams is vacuously green)."""
        out: dict[str, int] = {}
        for rule in self._rules:
            if rule.actions:
                out[rule.pattern] = out.get(rule.pattern, 0) + len(rule.actions)
        return out
