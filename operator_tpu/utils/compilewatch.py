"""Runtime XLA-compile observation: count and attribute every compile.

The 100/min soak showed a 5.87 s p99 against a 1.08 s p50 at 60/min —
a tail consistent with mid-run XLA compiles of program shapes (prefill
bucket x guided x prefix variants) not covered by warmup.  The reference
system has no analogue (its LLM leg is an external REST call,
AIInterfaceRestClient.java:37-39); in a compiled-serving design the
SLO-relevant discipline is instead: **every program the admission policy
can select must be compiled before readiness flips**.  This watcher makes
violations observable: it taps jax's ``jax_log_compiles`` channel and
records every "Compiling jit(NAME) ..." event with a timestamp, so a
soak/bench can assert ``midrun_compiles == 0`` after its warmup mark.

Usage::

    watcher = CompileWatcher()          # installs the log tap
    ... build + warm the engine ...
    watcher.mark()                      # warmup/steady-state boundary
    ... measured window ...
    watcher.events_since_mark()         # [(t_since_mark_s, name), ...]
"""

from __future__ import annotations

import logging
import re
import threading
import time
from typing import List, Optional, Tuple

_COMPILING = re.compile(r"Compiling\s+(\S+)\s+with global shapes")
_FINISHED = re.compile(
    r"Finished XLA compilation of\s+(\S+)\s+in\s+([0-9.]+)\s+sec"
)


class _TapHandler(logging.Handler):
    def __init__(self, watcher: "CompileWatcher") -> None:
        super().__init__(level=logging.DEBUG)
        self._watcher = watcher

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:  # pragma: no cover - malformed record
            return
        m = _COMPILING.search(msg)
        if m:
            self._watcher._record_start(m.group(1))
            return
        m = _FINISHED.search(msg)
        if m:
            self._watcher._record_finish(m.group(1), float(m.group(2)))


class CompileWatcher:
    """Tap the jax compile log and expose (timestamp, program) events.

    Thread-safe: jax may log compiles from executor threads.  The tap is
    installed on the ``jax`` logger at DEBUG without touching its
    propagation or other handlers, and ``jax_log_compiles`` is enabled as
    a side effect (harmless: the records land only on this handler unless
    the application configured DEBUG logging itself).
    """

    def __init__(self) -> None:
        import jax

        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self._mark: Optional[float] = None
        # (t_monotonic, name, duration_s|None) - duration filled by the
        # paired "Finished" record (same name, last unfinished wins)
        self._events: List[List] = []
        jax.config.update("jax_log_compiles", True)
        self._logger = logging.getLogger("jax")
        self._prior_level = self._logger.level
        if self._logger.level > logging.DEBUG or self._logger.level == 0:
            # NOTSET(0) inherits root (WARNING by default): pin to DEBUG so
            # the records reach handlers at all; the tap filters to compile
            # messages and other handlers keep their own level gates
            self._logger.setLevel(logging.DEBUG)
        self._handler = _TapHandler(self)
        self._logger.addHandler(self._handler)

    # -- record -----------------------------------------------------------
    def _record_start(self, name: str) -> None:
        with self._lock:
            self._events.append([time.monotonic(), name, None])

    def _record_finish(self, name: str, seconds: float) -> None:
        with self._lock:
            for ev in reversed(self._events):
                if ev[1] == name and ev[2] is None:
                    ev[2] = seconds
                    return
            # "Finished" without a matched start (pre-install compile or
            # name drift): record it anyway so nothing is silently dropped
            self._events.append([time.monotonic(), name, seconds])

    # -- query ------------------------------------------------------------
    def mark(self) -> None:
        """Set the warmup/steady-state boundary for events_since_mark()."""
        with self._lock:
            self._mark = time.monotonic()

    def events(self) -> List[Tuple[float, str, Optional[float]]]:
        with self._lock:
            return [(t - self._t0, n, d) for t, n, d in self._events]

    def events_since_mark(self) -> List[Tuple[float, str, Optional[float]]]:
        with self._lock:
            if self._mark is None:
                return [(t - self._t0, n, d) for t, n, d in self._events]
            return [
                (t - self._mark, n, d)
                for t, n, d in self._events
                if t >= self._mark
            ]

    def count_since_mark(self) -> int:
        return len(self.events_since_mark())

    def close(self) -> None:
        self._logger.removeHandler(self._handler)
        self._logger.setLevel(self._prior_level)
