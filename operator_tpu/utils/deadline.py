"""Deadline budgets — end-to-end latency accounting for the analysis path.

The reference's only latency contract is a flat 180 s external-LLM read
budget (application.properties:8-9) applied to one hop.  Here a
:class:`Deadline` is born the moment a pod failure is CLAIMED
(operator/pipeline.py process_failure_group) and flows through every hop:

- log collection gets a SLICE of the remaining budget,
- the pattern parse is capped by the remainder,
- the AI leg gets whatever is left (``AnalysisRequest.deadline_s``), and
- the serving engine's admission layer clamps ``max_tokens`` or rejects
  requests whose roofline decode estimate cannot fit the residual budget
  (serving/admission.py ``deadline_policy``).

The clock is injectable so chaos tests (tests/test_chaos.py, paired with
utils/faultinject.py) replay deterministically without sleeping through
real budgets.
"""

from __future__ import annotations

import time
from typing import Callable, Optional


class Deadline:
    """A monotonic time budget with stage slicing.

    All arithmetic is on the injected clock (default ``time.monotonic``),
    never wall-clock, so NTP steps and suspend/resume cannot corrupt a
    budget mid-flight.
    """

    __slots__ = ("total_s", "_clock", "_born")

    def __init__(self, total_s: float, clock: Optional[Callable[[], float]] = None) -> None:
        self._clock = clock or time.monotonic
        self.total_s = max(0.0, float(total_s))
        self._born = self._clock()

    @classmethod
    def start(cls, total_s: float, *, clock: Optional[Callable[[], float]] = None) -> "Deadline":
        return cls(total_s, clock)

    def elapsed(self) -> float:
        return self._clock() - self._born

    def remaining(self) -> float:
        return max(0.0, self.total_s - self.elapsed())

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def slice(self, fraction: float, *, floor_s: float = 0.0,
              cap_s: Optional[float] = None) -> float:
        """A stage's share of the REMAINING budget.

        ``fraction`` of what is left, floored at ``floor_s`` (so a nearly
        spent budget still hands the stage a usable window while any budget
        remains) and optionally capped — but never more than the remainder
        itself.  Returns 0.0 once expired.
        """
        remaining = self.remaining()
        share = max(remaining * fraction, floor_s)
        if cap_s is not None:
            share = min(share, cap_s)
        return min(share, remaining)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Deadline(total={self.total_s:.3f}s remaining={self.remaining():.3f}s)"
