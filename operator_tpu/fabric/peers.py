"""Peer inventory poller: feeds a replica-local fabric index.

In-process fleets (loadgen storms, bench, tests) can point a replica's
:class:`~operator_tpu.fabric.fetch.FabricFetcher` straight at the
router's ``health.kv_index``, which the router's own ``/healthz`` polls
keep fresh.  A **standalone** replica (``python -m
operator_tpu.serving``, the k8s serving Deployment) has no router in
its process — without a feeder its private index stays empty forever
and every "fabric" fetch is a silent no-op that still pays the probe.

``KV_FABRIC_PEERS`` closes that loop: a comma-separated list of peer
base URLs this poller GETs ``/healthz`` from (auth-exempt, the same
endpoint the router polls), feeding each answer's ``replica`` id and
``load.kvBlocks`` inventory into the index with the router's exact
replace-on-report freshness.  A hostname entry is DNS-expanded every
round, so the single headless-Service name
(``http://podmortem-serving:8000``) covers the whole fleet as pods come
and go — no k8s API access, no static peer list to maintain.

Freshness mirrors :class:`~operator_tpu.router.health.HealthBoard`:

- replace-on-report — a block the peer stopped advertising is gone the
  moment its next answer lands;
- a peer that fails to answer a round (or drops out of DNS) is removed
  from the index that same round — a dead peer is never offered as a
  holder, and the fetch path's 404 feedback covers the gap in between.

The poller itself never touches the engine or the store: it is pure
index plumbing on the event loop, started by
:meth:`~operator_tpu.serving.engine.ServingEngine.start` and cancelled
on engine close.
"""

from __future__ import annotations

import asyncio
import json
import logging
import socket
import urllib.error
import urllib.parse
import urllib.request
from typing import Optional, Sequence

from ..utils.timing import METRICS
from .index import FabricIndex

log = logging.getLogger(__name__)

__all__ = ["PeerPoller"]


class PeerPoller:
    """Poll peer ``/healthz`` inventories into a :class:`FabricIndex`."""

    def __init__(
        self,
        index: FabricIndex,
        *,
        peers: Sequence[str],
        self_id: str = "",
        poll_s: float = 5.0,
        timeout_s: float = 2.0,
        metrics=None,
        transport=None,
        resolver=None,
    ) -> None:
        self.index = index
        #: base URLs, each possibly a DNS name expanding to many pods
        self.peers = [u.rstrip("/") for u in peers if u.strip()]
        self.self_id = self_id
        self.poll_s = float(poll_s)
        self.timeout_s = float(timeout_s)
        self.metrics = metrics if metrics is not None else METRICS
        #: injectable ``async (url, timeout_s) -> (status, bytes)`` for
        #: tests (None = real HTTP GET on a thread)
        self._transport = transport
        #: injectable ``(host, port) -> list[(host, port)]`` for tests
        #: (None = socket.getaddrinfo)
        self._resolver = resolver
        #: replica ids fed last round — the staleness diff: anything
        #: here that the current round did not re-observe is removed
        self._last_seen: set[str] = set()

    # -- transport ------------------------------------------------------
    async def _http_get(self, url: str) -> tuple[int, bytes]:
        def fetch() -> tuple[int, bytes]:
            req = urllib.request.Request(url, method="GET")
            try:
                with urllib.request.urlopen(
                    req, timeout=self.timeout_s
                ) as resp:
                    return resp.status, resp.read()
            except urllib.error.HTTPError as exc:
                return exc.code, b""

        return await asyncio.wait_for(
            asyncio.to_thread(fetch), timeout=self.timeout_s + 0.25
        )

    def _resolve(self, host: str, port: int) -> list[tuple[str, int]]:
        if self._resolver is not None:
            return list(self._resolver(host, port))
        infos = socket.getaddrinfo(host, port, type=socket.SOCK_STREAM)
        seen: list[tuple[str, int]] = []
        for _family, _type, _proto, _canon, addr in infos:
            pair = (str(addr[0]), int(addr[1]))
            if pair not in seen:
                seen.append(pair)
        return seen

    async def _expand(self) -> list[str]:
        """Every pollable base URL this round: each peer entry's host is
        DNS-expanded (bounded by the poll timeout) so a headless-Service
        name yields one URL per ready pod."""
        urls: list[str] = []
        loop = asyncio.get_running_loop()
        for base in self.peers:
            parsed = urllib.parse.urlsplit(base)
            host = parsed.hostname or ""
            port = parsed.port or (443 if parsed.scheme == "https" else 80)
            try:
                addrs = await asyncio.wait_for(
                    loop.run_in_executor(None, self._resolve, host, port),
                    timeout=self.timeout_s,
                )
            except asyncio.CancelledError:
                raise
            except Exception:
                self.metrics.incr("fabric_peer_resolve_error", exemplar=host)
                continue
            for addr_host, addr_port in addrs:
                netloc_host = (
                    f"[{addr_host}]" if ":" in addr_host else addr_host
                )
                urls.append(f"{parsed.scheme}://{netloc_host}:{addr_port}")
        return urls

    # -- one round ------------------------------------------------------
    async def poll_once(self) -> int:
        """Poll every resolved peer once; returns replicas indexed.

        The staleness diff runs against the whole round: a replica fed
        in an earlier round that no resolved URL answered for this round
        is removed from the index (dead pod, DNS departure, or an
        unreachable peer — all the same verdict: not a holder).
        """
        seen: set[str] = set()
        for url in await self._expand():
            try:
                if self._transport is not None:
                    status, data = await self._transport(
                        f"{url}/healthz", self.timeout_s
                    )
                else:
                    status, data = await self._http_get(f"{url}/healthz")
            except asyncio.CancelledError:
                raise
            except Exception:
                self.metrics.incr("fabric_peer_poll_error", exemplar=url)
                continue
            if status != 200:
                self.metrics.incr("fabric_peer_poll_error", exemplar=url)
                continue
            try:
                body = json.loads(data.decode("utf-8"))
                rid = str(body.get("replica") or "")
                load = body.get("load") or {}
                raw_blocks = load.get("kvBlocks")
            except (ValueError, AttributeError):
                self.metrics.incr("fabric_peer_poll_error", exemplar=url)
                continue
            if not rid or rid == self.self_id:
                continue  # never index ourselves as a fetch target
            blocks = (
                [str(h) for h in raw_blocks]
                if isinstance(raw_blocks, list) else None
            )
            self.index.update(rid, blocks, url=url)
            seen.add(rid)
            self.metrics.incr("fabric_peer_poll_ok", exemplar=rid)
        for rid in self._last_seen - seen:
            self.index.remove(rid)
            self.metrics.incr("fabric_peer_removed", exemplar=rid)
        self._last_seen = seen
        return len(seen)

    # -- the loop -------------------------------------------------------
    async def run(self) -> None:
        """Poll forever at ``poll_s``; cancelled by engine close.  A
        failed round logs and keeps going — the index just ages via the
        fetch path's 404 feedback until polling recovers."""
        while True:
            try:
                await self.poll_once()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - polling must outlive any one bad round
                log.debug("fabric peer poll round failed; retrying",
                          exc_info=True)
            await asyncio.sleep(self.poll_s)
