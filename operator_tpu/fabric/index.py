"""Fabric block index: which replica holds which KV blocks.

This promotes the router's passive ``HealthBoard.holders()`` scan into a
first-class index with two freshness mechanisms the scan could not
express:

- **Replace-on-report (staleness tombstones).**  Each health poll
  replaces a replica's advertised set wholesale, so a holder that
  stopped advertising a block is dropped the moment its next report
  lands — not after some TTL.  A replica that leaves the ring (or whose
  breaker opens) is removed outright, taking its whole inventory with
  it before the next poll round trips.
- **Fetch-outcome feedback.**  A 404 from a supposed holder evicts that
  single (replica, block) entry immediately; the rest of the replica's
  inventory stays matchable until its next report.  Timeouts and
  transport errors are softer evidence — a black-holed peer never
  answers at all, so it can never 404 — and decay the entry instead:
  ``failure_threshold`` CONSECUTIVE failures against one (replica,
  block) pair evict it just like a 404 would, so a dead-but-still-
  listed holder stops winning the kv-hint re-rank.  Any success, or a
  fresh health report from the replica, resets its counters.

The index is plain in-process state fed by the router's health poll —
no clock, no background task.  Entries carry the replica's URL so the
fetch client can hit ``GET /kv/blocks/{hash}`` without a second lookup.
Block hashes are the 32-hex digest strings from the ``/healthz``
``kvBlocks`` inventory (see serving/kvstore.py ``block_hashes``).
"""

from __future__ import annotations

from typing import Iterable, Optional


class FabricIndex:
    """replica_id -> (advertised block set, base URL)."""

    def __init__(self, *, failure_threshold: int = 3) -> None:
        self._blocks: dict[str, frozenset[str]] = {}
        self._urls: dict[str, str] = {}
        #: consecutive non-404 fetch failures per (replica, block) pair
        self._failures: dict[tuple[str, str], int] = {}
        #: consecutive failures before a (replica, block) entry decays
        self.failure_threshold = max(1, int(failure_threshold))
        #: fetch-feedback evictions since construction (stats only)
        self.evictions = 0

    def update(
        self, replica_id: str, blocks: Optional[Iterable[str]], *, url: str = ""
    ) -> None:
        """Replace ``replica_id``'s advertised set (staleness tombstone:
        anything it stopped advertising is gone as of this call).  A
        fresh report is fresh evidence the replica is alive, so its
        failure counters reset too."""
        self._blocks[replica_id] = frozenset(blocks or ())
        if url:
            self._urls[replica_id] = url
        self._clear_failures(replica_id)

    def remove(self, replica_id: str) -> None:
        """Drop the replica and its whole inventory (ring leave, breaker
        open, scale-down)."""
        self._blocks.pop(replica_id, None)
        self._urls.pop(replica_id, None)
        self._clear_failures(replica_id)

    def evict(self, replica_id: str, block_hash: str) -> bool:
        """Fetch-outcome feedback: the holder 404'd this block.  Returns
        True when an entry was actually dropped."""
        held = self._blocks.get(replica_id)
        if held is None or block_hash not in held:
            return False
        self._blocks[replica_id] = held - {block_hash}
        self._failures.pop((replica_id, block_hash), None)
        self.evictions += 1
        return True

    def note_failure(self, replica_id: str, block_hash: str) -> bool:
        """Fetch-outcome feedback for timeouts/transport errors: decay
        the (replica, block) entry after ``failure_threshold``
        CONSECUTIVE failures (a black-holed peer never 404s, so without
        this it would stay advertised forever).  Returns True when the
        entry was evicted by this failure."""
        held = self._blocks.get(replica_id)
        if held is None or block_hash not in held:
            return False
        key = (replica_id, block_hash)
        count = self._failures.get(key, 0) + 1
        if count >= self.failure_threshold:
            self._failures.pop(key, None)
            self._blocks[replica_id] = held - {block_hash}
            self.evictions += 1
            return True
        self._failures[key] = count
        return False

    def note_success(self, replica_id: str, block_hash: str) -> None:
        """A successful fetch resets the pair's consecutive-failure
        count (decay needs CONSECUTIVE evidence, not lifetime totals)."""
        self._failures.pop((replica_id, block_hash), None)

    def _clear_failures(self, replica_id: str) -> None:
        for key in [k for k in self._failures if k[0] == replica_id]:
            del self._failures[key]

    def empty(self) -> bool:
        """True when no replica currently advertises any block — the
        cheap pre-tokenize gate for the admission-time prefetch (an
        unfed index must cost a request nothing, not a re-tokenize)."""
        return not any(self._blocks.values())

    def holders(self, block_hash: str) -> list[str]:
        """Replica ids currently advertising ``block_hash``, sorted for
        deterministic fetch ordering."""
        return sorted(
            rid for rid, held in self._blocks.items() if block_hash in held
        )

    def holder_urls(self, block_hash: str) -> list[tuple[str, str]]:
        """``(replica_id, url)`` pairs for holders with a known URL."""
        return [
            (rid, self._urls[rid])
            for rid in self.holders(block_hash)
            if self._urls.get(rid)
        ]

    def blocks(self, replica_id: str) -> frozenset[str]:
        return self._blocks.get(replica_id, frozenset())

    def replicas(self) -> list[str]:
        return sorted(self._blocks)

    def stats(self) -> dict:
        return {
            "replicas": len(self._blocks),
            "entries": sum(len(held) for held in self._blocks.values()),
            "evictions": self.evictions,
            "decaying": len(self._failures),
        }
