"""Wire format for one KV block on the fabric.

A block travels as::

    PMKV1\\n
    {"hash": <32 hex>, "k": {...}, "v": {...}, "sha256": <payload hex>}\\n
    <raw k bytes><raw v bytes>

The header is a single JSON line so a reader can split on the first
newline after the magic without framing state; the payload is the two
arrays' contiguous bytes back to back.  The checksum covers the payload
only — the header is self-validating (shape/dtype must reconstruct to
exactly the payload length).  Pages live in the holder's HostKVPool as
host numpy, so encoding is two ``tobytes()`` calls.  Decoding COPIES
each array out of the response buffer: a ``frombuffer`` view would be
read-only and would pin the entire wire blob (header + both arrays)
alive for as long as the adopted page sits in the pool, silently
breaking the pool's ``k.nbytes + v.nbytes`` accounting.
"""

from __future__ import annotations

import hashlib
import json

import numpy as np

MAGIC = b"PMKV1\n"


class CorruptBlock(ValueError):
    """The bytes on the wire do not reconstruct the advertised block."""


def _spec(arr: np.ndarray) -> dict:
    return {"dtype": str(arr.dtype), "shape": list(arr.shape)}


def encode_block(block_hash: bytes, k: np.ndarray, v: np.ndarray) -> bytes:
    """Serialize one block's (k, v) page pair for the wire."""
    k = np.ascontiguousarray(k)
    v = np.ascontiguousarray(v)
    payload = k.tobytes() + v.tobytes()
    header = {
        "hash": block_hash.hex(),
        "k": _spec(k),
        "v": _spec(v),
        "sha256": hashlib.sha256(payload).hexdigest(),
    }
    return MAGIC + json.dumps(header, sort_keys=True).encode("utf-8") + b"\n" + payload


def _dtype(name) -> np.dtype:
    # plain numpy does not know the accelerator dtypes (bfloat16,
    # float8_*) by name — ml_dtypes registers them, and the serving KV
    # cache is bfloat16 by default, so the production page dtype MUST
    # resolve here or every real fetch dies as "corrupt"
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, str(name)))


def _reconstruct(spec: dict, payload: bytes, offset: int) -> tuple[np.ndarray, int]:
    try:
        dtype = _dtype(spec["dtype"])
        shape = tuple(int(d) for d in spec["shape"])
    except (KeyError, TypeError, ValueError, AttributeError) as exc:
        raise CorruptBlock(f"bad array spec: {exc}") from exc
    count = int(np.prod(shape)) if shape else 1
    nbytes = count * dtype.itemsize
    if offset + nbytes > len(payload):
        raise CorruptBlock("payload shorter than header claims")
    arr = np.frombuffer(payload, dtype=dtype, count=count, offset=offset)
    # copy: a frombuffer view is read-only and keeps the whole wire blob
    # alive behind a page-sized pool entry (see module docstring)
    return arr.reshape(shape).copy(), offset + nbytes


def decode_block(data: bytes) -> tuple[bytes, np.ndarray, np.ndarray]:
    """Parse wire bytes back into ``(block_hash, k, v)``.

    Raises :class:`CorruptBlock` on any mismatch — magic, header shape,
    payload length, or checksum.  Callers treat that exactly like a
    fetch miss and fall back to recompute.
    """
    if not data.startswith(MAGIC):
        raise CorruptBlock("bad magic")
    newline = data.find(b"\n", len(MAGIC))
    if newline < 0:
        raise CorruptBlock("truncated header")
    try:
        header = json.loads(data[len(MAGIC):newline].decode("utf-8"))
        block_hash = bytes.fromhex(header["hash"])
        advertised = header["sha256"]
    except (KeyError, TypeError, ValueError) as exc:
        raise CorruptBlock(f"bad header: {exc}") from exc
    payload = data[newline + 1:]
    if hashlib.sha256(payload).hexdigest() != advertised:
        raise CorruptBlock("payload checksum mismatch")
    k, offset = _reconstruct(header.get("k", {}), payload, 0)
    v, offset = _reconstruct(header.get("v", {}), payload, offset)
    if offset != len(payload):
        raise CorruptBlock("trailing bytes after payload")
    return block_hash, k, v
