"""Fleet KV fabric: peer-to-peer page transfer + prefill/decode roles.

PR 14 built the single-replica KV economy and a *passive* fleet layer:
replicas advertise block-hash inventory on ``/healthz`` and the router
merely prefers holders (``dispatch(kv_hint=...)``).  This package makes
the fleet tier *active* — three composing planes:

- :mod:`.wire` — the one-block wire format served by the replica's
  ``GET /kv/blocks/{hash}`` endpoint (JSON header + raw numpy payload,
  sha256 checksummed end to end).
- :mod:`.index` — the fabric block index: replica -> advertised block
  set, replace-on-report semantics (staleness tombstones for free) plus
  fetch-outcome feedback (a 404 from a supposed holder evicts that
  entry immediately).
- :mod:`.fetch` — the bounded-concurrency fetch client: admission-time
  prefix misses consult the index and pull pages from a holder's host
  pool instead of recomputing, under a per-fetch deadline clamped to
  the request's residual budget.  A failed fetch must never be slower
  than the recompute it replaced.
- :mod:`.disagg` — prefill/decode disaggregation: replica roles, the
  role-aware candidate ordering the router uses, and the two-leg
  prefill->decode dispatch helper built on token-level resume.
- :mod:`.peers` — the index feeder for router-less replicas: polls peer
  ``/healthz`` inventories (``KV_FABRIC_PEERS``, DNS-expanded each
  round so one headless-Service name covers the fleet) into the local
  index with the router's replace-on-report freshness.

See docs/FABRIC.md for the protocol, deadline policy, and knobs.
"""

from .disagg import DECODE, MIXED, PREFILL, VALID_ROLES, disaggregated_dispatch
from .fetch import FabricFetcher
from .index import FabricIndex
from .peers import PeerPoller
from .wire import CorruptBlock, decode_block, encode_block

__all__ = [
    "CorruptBlock",
    "DECODE",
    "FabricFetcher",
    "FabricIndex",
    "MIXED",
    "PREFILL",
    "PeerPoller",
    "VALID_ROLES",
    "decode_block",
    "disaggregated_dispatch",
    "encode_block",
]
