"""Prefill/decode disaggregation on top of the fabric.

Storm-shaped load — huge log prompts, short analyses — forces every
replica to be sized for both phases.  Roles split that: a *prefill*
replica runs prompts and registers the resulting pages in the fabric
(scheduler mirror -> host pool -> ``/healthz`` inventory), a *decode*
replica pulls pages over the fabric and decodes, and *mixed* (the
default) serves both phases exactly as before — a fleet with no roles
configured behaves identically to the pre-fabric fleet.

The two-leg dispatch below generalizes token-level resume
(router/resume.py) from failover-only to steady-state: the prefill leg
generates exactly one token (forcing the full prompt through prefill
and the mirror), then the decode leg resumes from that token on a
decode replica whose admission-time prefetch pulls the prompt's pages
instead of recomputing them.  Roles are a *preference*, never a hard
filter — a fleet with no decode replica degrades to mixed candidates
rather than rejecting (degrade-before-reject, PR 18's rule).
"""

from __future__ import annotations

from typing import Optional

from ..utils.timing import METRICS

PREFILL = "prefill"
DECODE = "decode"
MIXED = "mixed"
VALID_ROLES = frozenset((PREFILL, DECODE, MIXED))


def normalize_role(role: Optional[str]) -> str:
    """Validate a configured role; empty/None means mixed."""
    if not role:
        return MIXED
    role = role.strip().lower()
    if role not in VALID_ROLES:
        raise ValueError(
            f"invalid replica role {role!r}: expected one of "
            f"{sorted(VALID_ROLES)}"
        )
    return role


def role_preference(candidate_role: Optional[str], wanted: str) -> int:
    """Candidate ordering key for a role-aware route: exact match first,
    then mixed/unknown (they can serve anything), then the opposite
    role — degrade, never reject."""
    if candidate_role == wanted:
        return 0
    if candidate_role in (None, "", MIXED):
        return 1
    return 2


async def disaggregated_dispatch(
    router,
    prefill_send,
    decode_send,
    *,
    key: str = "",
    request_id: str = "",
    deadline=None,
    tokens: int = 256,
    kv_hint=None,
    metrics=None,
):
    """Run one request as a prefill leg + a decode leg over the fabric.

    ``prefill_send(replica, attempt, budget_s)`` must run the prompt for
    exactly one generated token and return a result exposing
    ``token_ids``; ``decode_send(replica, attempt, budget_s,
    prefix_tokens)`` resumes from those tokens for the remaining budget.
    Both legs ride the ordinary ``router.dispatch`` machinery (breakers,
    failover, requeue) with a role preference; the shared deadline means
    the decode leg sees whatever budget the prefill leg left behind.

    Returns ``(prefill_outcome, decode_outcome)``.
    """
    m = metrics if metrics is not None else METRICS
    prefill_out = await router.dispatch(
        prefill_send,
        key=key,
        request_id=f"{request_id}:prefill" if request_id else "",
        deadline=deadline,
        tokens=1,
        kv_hint=kv_hint,
        role=PREFILL,
    )
    prefix = list(getattr(prefill_out.response, "token_ids", ()) or ())

    async def _decode_leg(replica, attempt, budget_s):
        return await decode_send(replica, attempt, budget_s, prefix)

    decode_out = await router.dispatch(
        _decode_leg,
        key=key,
        request_id=request_id,
        deadline=deadline,
        tokens=tokens,
        kv_hint=kv_hint,
        role=DECODE,
    )
    m.incr("fabric_disagg_handoff")
    return prefill_out, decode_out
