"""Bounded-concurrency KV page fetch client.

The admission-time flow: a replica whose prefix match misses locally
asks the fabric index who holds the missing blocks and pulls the pages
from a holder's host pool over ``GET /kv/blocks/{hash}`` instead of
recomputing them.  Two invariants shape everything here:

- **A failed fetch must never be slower than the recompute it
  replaced.**  Every fetch runs under ``min(kv_fabric_fetch_timeout_s,
  residual request budget)``; timeout, miss, corruption, or a dead
  holder all degrade to the ordinary recompute path — the request never
  sees a fabric error.
- **Adoption is prefix-contiguous.**  The prefix matcher walks blocks
  in order, so a fetched block behind a gap is unmatchable; prefetch
  adopts the longest contiguous run of fetched blocks and drops the
  rest on the floor (they were cheap host numpy, not device pages).

Fetch outcomes feed back into the index: a 404 from a supposed holder
evicts that (replica, block) entry immediately, and timeouts/transport
errors decay it after ``FabricIndex.failure_threshold`` consecutive
failures (a black-holed peer can never 404 — see index.py).
"""

from __future__ import annotations

import asyncio
import time
import urllib.error
import urllib.request
from typing import Optional, Sequence

from ..utils.timing import METRICS
from .index import FabricIndex
from .wire import CorruptBlock, decode_block

#: slack added to the asyncio.wait_for guard over the threaded HTTP GET:
#: the socket timeout is authoritative, the wait_for only covers thread
#: scheduling delay so a wedged executor cannot outlive the budget
_THREAD_SLACK_S = 0.25


def _is_timeout(exc: BaseException) -> bool:
    if isinstance(exc, (asyncio.TimeoutError, TimeoutError)):
        return True
    if isinstance(exc, urllib.error.URLError):
        return isinstance(exc.reason, TimeoutError)
    return False


class FabricFetcher:
    """Pulls missing prefix blocks from fleet holders into the local
    host pool, bounded in concurrency and clamped in time."""

    def __init__(
        self,
        index: FabricIndex,
        *,
        api_token: Optional[str] = None,
        timeout_s: float = 2.0,
        concurrency: int = 4,
        self_id: str = "",
        metrics=None,
        fault_plan=None,
        transport=None,
        clock=None,
    ) -> None:
        self.index = index
        self.api_token = api_token
        self.timeout_s = float(timeout_s)
        self.self_id = self_id
        self.metrics = metrics if metrics is not None else METRICS
        self.fault_plan = fault_plan
        #: injectable transport for tests (None = real HTTP GET)
        self._transport = transport
        self._clock = clock if clock is not None else time.monotonic
        self._sem = asyncio.Semaphore(max(1, int(concurrency)))

    # -- transport ------------------------------------------------------
    async def _http_get(self, url: str, budget_s: float) -> tuple[int, bytes]:
        def fetch() -> tuple[int, bytes]:
            req = urllib.request.Request(url, method="GET")
            if self.api_token:
                req.add_header("Authorization", f"Bearer {self.api_token}")
            try:
                with urllib.request.urlopen(req, timeout=budget_s) as resp:
                    return resp.status, resp.read()
            except urllib.error.HTTPError as exc:
                return exc.code, b""

        return await asyncio.wait_for(
            asyncio.to_thread(fetch), timeout=budget_s + _THREAD_SLACK_S
        )

    def _note_failure(self, rid: str, block_hash: str) -> None:
        """Feed a non-404 fetch failure into the index's consecutive-
        failure decay; counts the eviction when the threshold tripped."""
        if self.index.note_failure(rid, block_hash):
            self.metrics.incr("fabric_index_decayed", exemplar=rid)

    # -- one block ------------------------------------------------------
    async def fetch_block(self, block_hash: str, *, budget_s: Optional[float] = None):
        """Fetch one block from any current holder.

        Returns ``(k, v)`` host arrays or ``None`` — every failure mode
        (no holder, exhausted budget, timeout, 404, corruption) is a
        ``None``, and the caller recomputes.
        """
        budget = (
            self.timeout_s
            if budget_s is None
            else min(self.timeout_s, float(budget_s))
        )
        if budget <= 0:
            self.metrics.incr("fabric_fetch_fallback", exemplar=block_hash)
            return None
        holders = [
            (rid, url)
            for rid, url in self.index.holder_urls(block_hash)
            if rid != self.self_id
        ]
        if not holders:
            self.metrics.incr("fabric_fetch_fallback", exemplar=block_hash)
            return None
        deadline = self._clock() + budget
        async with self._sem:
            for rid, url in holders:
                remaining = deadline - self._clock()
                if remaining <= 0:
                    break
                if self.fault_plan is not None:
                    try:
                        await self.fault_plan.apply_async(
                            "fabric.fetch", replica=rid, block=block_hash
                        )
                    except asyncio.CancelledError:
                        raise
                    except Exception as exc:
                        if _is_timeout(exc):
                            self.metrics.incr(
                                "fabric_fetch_timeout", exemplar=rid
                            )
                        else:
                            self.metrics.incr(
                                "fabric_fetch_error", exemplar=rid
                            )
                        self._note_failure(rid, block_hash)
                        continue
                block_url = f"{url.rstrip('/')}/kv/blocks/{block_hash}"
                try:
                    if self._transport is not None:
                        status, data = await self._transport(
                            block_url, remaining
                        )
                    else:
                        status, data = await self._http_get(
                            block_url, remaining
                        )
                except asyncio.CancelledError:
                    raise
                except Exception as exc:
                    if _is_timeout(exc):
                        self.metrics.incr("fabric_fetch_timeout", exemplar=rid)
                    else:
                        self.metrics.incr("fabric_fetch_error", exemplar=rid)
                    self._note_failure(rid, block_hash)
                    continue
                if status == 404:
                    if self.index.evict(rid, block_hash):
                        self.metrics.incr("fabric_index_evicted", exemplar=rid)
                    self.metrics.incr("fabric_fetch_miss", exemplar=rid)
                    continue
                if status != 200:
                    self.metrics.incr("fabric_fetch_error", exemplar=rid)
                    self._note_failure(rid, block_hash)
                    continue
                try:
                    got_hash, k, v = decode_block(data)
                except CorruptBlock:
                    self.metrics.incr("fabric_fetch_corrupt", exemplar=rid)
                    self._note_failure(rid, block_hash)
                    continue
                if got_hash.hex() != block_hash:
                    self.metrics.incr("fabric_fetch_corrupt", exemplar=rid)
                    self._note_failure(rid, block_hash)
                    continue
                self.index.note_success(rid, block_hash)
                self.metrics.incr("fabric_fetch_ok", exemplar=rid)
                return k, v
        self.metrics.incr("fabric_fetch_fallback", exemplar=block_hash)
        return None

    # -- the admission-time entry point ---------------------------------
    async def prefetch(
        self,
        tokens: Sequence[int],
        *,
        store,
        budget_s: Optional[float] = None,
        executor=None,
    ) -> int:
        """Pull the prompt's missing prefix blocks into the local host
        pool so the ordinary one-DMA restore path turns the fabric hit
        into a prefix-cache hit.

        Returns the number of blocks adopted.  Requires the store to
        carry a non-empty host pool (``kv_host_pool_mb > 0``) — without
        one there is nowhere to land a page without touching device
        memory off the commit window.

        ``executor`` is the engine's single-thread decode executor (the
        thread the scheduler mutates the store from): when given, the
        store probe and the adoption loop run THERE, so every store
        mutation serializes with enqueue/step and the check-then-forget
        in adoption is atomic w.r.t. a concurrent restore flipping the
        block back to device residency.  None (tests, in-process
        harnesses with no scheduler thread) runs them inline.
        """
        pool = getattr(store, "host_pool", None)
        if pool is None or getattr(pool, "capacity_bytes", 0) <= 0:
            return 0
        if executor is not None:
            probe = await asyncio.get_running_loop().run_in_executor(
                executor, store.probe, tokens
            )
        else:
            probe = store.probe(tokens)
        wanted = [
            (i, block_hash)
            for i, (block_hash, resident) in enumerate(probe)
            if not resident and self.index.holders(block_hash.hex())
        ]
        if not wanted:
            return 0
        results = await asyncio.gather(
            *(self.fetch_block(h.hex(), budget_s=budget_s) for _, h in wanted)
        )
        fetched = {
            i: page for (i, _h), page in zip(wanted, results) if page is not None
        }

        def adopt() -> int:
            page_size = store.page_size
            adopted = 0
            parent: Optional[bytes] = None
            for i, (block_hash, resident) in enumerate(probe):
                if resident:
                    parent = block_hash
                    continue
                page = fetched.get(i)
                if page is None:
                    break  # gap: later blocks are unmatchable, stop here
                k, v = page
                dropped = pool.put(block_hash, k, v)
                if dropped is None:
                    break  # pool refused (disabled or page > pool)
                for old in dropped:
                    entry = store.get(old)
                    if entry is not None and entry.page < 0:
                        store.forget(old)
                store.adopt_host(
                    block_hash, parent,
                    tokens[i * page_size:(i + 1) * page_size],
                )
                adopted += 1
                parent = block_hash
            return adopted

        if executor is not None:
            adopted = await asyncio.get_running_loop().run_in_executor(
                executor, adopt
            )
        else:
            adopted = adopt()
        if adopted:
            self.metrics.incr("fabric_prefetch_adopted", adopted)
        return adopted
