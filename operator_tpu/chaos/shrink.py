"""Fault-plan shrinking: ddmin a failing scenario to a minimal repro.

A composed game day that violates an invariant is a terrible bug
report: six injections, three fleet actions, hundreds of arrivals.
The shrinker reduces it to the smallest injection subset that still
fails, using Zeller's ddmin over the scenario's flat injection index
space (:meth:`ChaosScenario.injections` /
:meth:`ChaosScenario.with_injections` — phases and fleet actions are
structural context and are preserved verbatim; only injections shrink).

The caller supplies the failing PREDICATE — ``async probe(scenario) ->
bool``, True when the reduced scenario STILL fails (e.g. "run it under
the conductor with the mutation armed and check
``report['violations']``").  Because injections fire on per-site call
counters and every random draw happens at compile time, the predicate
is a deterministic function of the injection subset — ddmin's
monotonicity assumption actually holds here, and the minimal repro
replays byte-identically (same fingerprint, same verdict) every time.

Results are cached per subset, so the probe never runs twice for one
candidate; the final subset is re-verified before being returned.  The
minimal scenario is emitted as runnable JSON —

    LOADGEN_GAMEDAY=1 LOADGEN_SCENARIO=<path> python -m operator_tpu.loadgen

— the exact artifact to commit under ``tests/scenarios/`` as a
regression game day (docs/ROBUSTNESS.md, "committing a repro").
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Awaitable, Callable

from ..utils.timing import METRICS
from .scenario import ChaosScenario

Probe = Callable[[ChaosScenario], Awaitable[bool]]


@dataclass(frozen=True)
class ShrinkResult:
    """The minimal failing reproducer and how we got there."""

    scenario: ChaosScenario
    #: surviving indices into the ORIGINAL scenario's injections()
    indices: "tuple[int, ...]"
    #: probe invocations actually run (cache misses)
    probes: int
    #: injection count before / after
    original: int
    minimal: int

    def repro_json(self) -> str:
        return self.scenario.to_json()

    def repro_command(self, path: str) -> str:
        """The one-liner that replays the minimal repro from ``path``
        (write :meth:`repro_json` there first)."""
        return (
            f"LOADGEN_GAMEDAY=1 LOADGEN_SCENARIO={path} "
            "python -m operator_tpu.loadgen"
        )


def _chunks(items: "list[int]", n: int) -> "list[list[int]]":
    size, rem = divmod(len(items), n)
    out, start = [], 0
    for i in range(n):
        end = start + size + (1 if i < rem else 0)
        if end > start:
            out.append(items[start:end])
        start = end
    return out


async def shrink(
    scenario: ChaosScenario,
    probe: Probe,
    *,
    metrics=None,
) -> ShrinkResult:
    """ddmin ``scenario``'s injections down to a minimal set for which
    ``probe`` still returns True.  ``probe`` must return True for the
    full scenario (asserted — shrinking a passing scenario is a test
    bug, not a shrink)."""
    metrics = metrics if metrics is not None else METRICS
    total = len(scenario.injections())
    cache: "dict[tuple[int, ...], bool]" = {}
    runs = {"n": 0}

    async def failing(indices: "list[int]") -> bool:
        key = tuple(indices)
        if key not in cache:
            runs["n"] += 1
            metrics.incr("chaos_shrink_probe")
            cache[key] = await probe(scenario.with_injections(list(indices)))
        return cache[key]

    if not await failing(list(range(total))):
        raise ValueError(
            "shrink() needs a failing scenario: probe returned False for "
            "the full injection set"
        )

    indices = list(range(total))
    n = 2
    while len(indices) >= 2:
        parts = _chunks(indices, n)
        reduced = False
        # subsets first: a failing chunk is the biggest single cut
        for part in parts:
            if await failing(part):
                indices, n, reduced = part, 2, True
                break
        if not reduced:
            # complements: drop one chunk at a time
            for part in parts:
                dropped = set(part)
                complement = [i for i in indices if i not in dropped]
                if complement and await failing(complement):
                    indices, reduced = complement, True
                    n = max(2, n - 1)
                    break
        if not reduced:
            if n >= len(indices):
                break
            n = min(len(indices), 2 * n)

    assert await failing(indices)  # cached: the minimal set verified failing
    metrics.incr("chaos_shrink_done")
    return ShrinkResult(
        scenario=scenario.with_injections(indices),
        indices=tuple(indices),
        probes=runs["n"],
        original=total,
        minimal=len(indices),
    )
