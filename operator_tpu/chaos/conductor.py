"""Game-day conductor: run one :class:`ChaosScenario` end to end.

The conductor materialises a scenario into a live harness — the full
storm stack (loadgen/storm.py: FakeKubeApi -> AnalysisPipeline ->
EngineRouter -> synthetic replicas) plus three planes storms alone do
not exercise:

- a **fabric plane**: a seeded :class:`FabricIndex` of per-replica KV
  block inventories and a :class:`FabricFetcher` over an in-memory
  transport.  Recall-hot arrivals fetch a block before submitting, so
  ``fabric.fetch`` injections and the consecutive-failure decay path
  run under load; a KILLED replica's transport goes black-hole
  (timeouts, never 404 — the exact case index decay exists for).
- a **watch plane**: a background consumer of ``api.watch("Pod")`` so
  ``kube.watch_open.* / kube.watch.*`` drop/expire injections hit a
  live stream that must re-establish.
- a **leadership plane** (``scenario.leadership``): a real
  :class:`LeaseElector` pair against the stack's apiserver; arrivals
  route through ``process_failure_group`` (the claim ledger), and a
  ``depose_leader`` action is a graceful handover — release, standby
  acquires, ``resume_pending`` on the survivor.

Determinism contract (see chaos/scenario.py): injections live in ONE
compiled FaultPlan consumed per-site in call order; fleet actions fire
immediately before their phase's trigger ARRIVAL INDEX; every
probabilistic draw happened at compile time.  The scenario fingerprint
is materialisation identity — the CI gameday gate builds each scenario
twice and asserts fingerprint equality, then requires zero invariant
violations on both runs.

The :class:`InvariantAuditor` (chaos/invariants.py) is wired in
always-on: checked every ``BARRIER_EVERY`` arrivals mid-storm and once
at scenario end; violations black-box through the flight recorder
tagged with fingerprint + phase.

The ``mutation`` hook exists to prove the oracle: ``mutation =
"drop-settle-on-conflict"`` suppresses exactly one SLO-ledger settle
once a ``kube.patch_status`` conflict injection has fired, so a
scenario containing a 409 injection MUST produce an
arrival-conservation violation — the auditor-fires test and the
shrinker's failing predicate (chaos/shrink.py) both stand on it.
"""

from __future__ import annotations

import asyncio
import hashlib
from typing import Any, Optional

import numpy as np

from ..fabric.fetch import FabricFetcher
from ..fabric.index import FabricIndex
from ..fabric.wire import encode_block
from ..loadgen.arrivals import ArrivalEvent, ArrivalProcess
from ..loadgen.driver import run_open_loop
from ..loadgen.storm import SyntheticReplica, build_storm_stack, storm_log, storm_pod
from ..operator.kubeapi import WatchClosed
from ..operator.lease import LeaseElector
from ..utils.config import OperatorConfig
from ..utils.timing import METRICS
from .invariants import GameDayView, InvariantAuditor
from .scenario import ChaosScenario, FleetAction

#: run the "any"-barrier probes every N submitted arrivals (the serving
#: scheduler's commit-barrier hook covers the per-step cadence when a
#: real engine is in the fleet; this is the fleet-level heartbeat)
BARRIER_EVERY = 16

#: blocks advertised per initial replica in the fabric plane
BLOCKS_PER_REPLICA = 3

#: breaker reset applied to the storm router so "transient exclusion
#: must heal" is checkable within a compressed game day
BREAKER_RESET_S = 0.2


def _fabric_inventory(
    scenario: ChaosScenario,
) -> "dict[str, list[tuple[str, bytes]]]":
    """Deterministic per-replica block inventory: hash -> encoded wire
    payload, derived only from the scenario seed (part of no fingerprint
    — it is a pure function of inputs that already are)."""
    inventory: "dict[str, list[tuple[str, bytes]]]" = {}
    for i, _role in enumerate(scenario.fleet):
        rid = f"storm-replica-{i}"
        blocks = []
        for j in range(BLOCKS_PER_REPLICA):
            digest = hashlib.sha256(
                f"gameday:{scenario.seed}:{rid}:{j}".encode()
            ).digest()[:16]
            rng = np.random.default_rng(int.from_bytes(digest[:8], "big"))
            k = rng.standard_normal((2, 4), dtype=np.float32)
            v = rng.standard_normal((2, 4), dtype=np.float32)
            blocks.append((digest.hex(), encode_block(digest, k, v)))
        inventory[rid] = blocks
    return inventory


class _FabricPlane:
    """Index + fetcher over an in-memory transport; killed replicas
    black-hole (hang until the budget times out, never 404)."""

    def __init__(self, scenario: ChaosScenario, *, metrics, fault_plan) -> None:
        self.inventory = _fabric_inventory(scenario)
        self.dead: "set[str]" = set()
        self.index = FabricIndex()
        self._payloads: "dict[tuple[str, str], bytes]" = {}
        for rid, blocks in self.inventory.items():
            self.index.update(
                rid, [h for h, _ in blocks], url=f"fabric://{rid}"
            )
            for block_hash, payload in blocks:
                self._payloads[(rid, block_hash)] = payload
        #: every advertised hash, sorted — the recall-hot pick space
        self.all_blocks = sorted(
            {h for blocks in self.inventory.values() for h, _ in blocks}
        )
        self.fetcher = FabricFetcher(
            self.index,
            timeout_s=0.5,
            self_id="gameday-conductor",
            metrics=metrics,
            fault_plan=fault_plan,
            transport=self._transport,
        )

    async def _transport(self, url: str, budget_s: float) -> "tuple[int, bytes]":
        rid, _, rest = url.removeprefix("fabric://").partition("/")
        if rid in self.dead:
            # a black-holed peer never answers: the fetch burns its
            # budget and times out (the decay path, not the 404 path)
            await asyncio.sleep(max(0.0, budget_s))
            raise asyncio.TimeoutError(f"fabric peer {rid} black-holed")
        block_hash = rest.rsplit("/", 1)[-1]
        payload = self._payloads.get((rid, block_hash))
        if payload is None:
            return 404, b""
        return 200, payload

    async def touch(self, event: ArrivalEvent) -> None:
        """A recall-hot arrival warms one block over the fabric before
        its analysis — the deterministic stand-in for admission-time
        prefetch (pick rotates by arrival index)."""
        if not self.all_blocks:
            return
        block_hash = self.all_blocks[event.index % len(self.all_blocks)]
        await self.fetcher.fetch_block(block_hash, budget_s=0.25)


class _LeadershipPlane:
    """A live lease pair over the stack's apiserver.  ``a`` leads from
    the start; ``depose`` is the graceful half of failover — release,
    standby acquires, pending claims resume on the survivor.  (The
    SIGKILL half — abandon without release — is tests/test_leader.py's
    harness; a game day needs the fleet to keep serving through the
    handover, which the graceful path exercises under full load.)"""

    def __init__(self, stack, *, metrics) -> None:
        self.stack = stack
        self.stop = asyncio.Event()
        self.leader_id = "conductor-a"
        self._tasks: "list[asyncio.Task]" = []
        self.electors = {
            name: LeaseElector(
                stack.api,
                lease_name="gameday-leader",
                namespace=stack.namespace,
                identity=name,
                duration_s=2.0,
                renew_period_s=0.05,
                retry_period_s=0.05,
                metrics=metrics,
            )
            for name in ("conductor-a", "conductor-b")
        }

    async def start(self) -> None:
        a = self.electors["conductor-a"]
        self._tasks.append(asyncio.create_task(a.run(self.stop)))
        await asyncio.wait_for(a.wait_leading(self.stop), timeout=10.0)
        b = self.electors["conductor-b"]
        self._tasks.append(asyncio.create_task(b.run(self.stop)))

    async def depose(self) -> str:
        """Graceful handover to the standby; returns the new leader."""
        old = self.leader_id
        new = "conductor-b" if old == "conductor-a" else "conductor-a"
        await self.electors[old].release()
        await asyncio.wait_for(
            self.electors[new].wait_leading(self.stop), timeout=10.0
        )
        self.leader_id = new
        # the new leader adopts the old one's in-flight claims — under a
        # graceful handover there are usually none pending, and that is
        # the exactly-once point: resume must not double-analyze
        await self.stack.pipeline.resume_pending()
        return new

    async def close(self) -> None:
        self.stop.set()
        for task in self._tasks:
            task.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)


async def _watch_plane(api, namespace: str, metrics) -> None:
    """Consume the Pod watch stream forever, re-establishing on drops —
    the live stream ``kube.watch_open.* / kube.watch.*`` injections need
    to have something to break."""
    while True:
        try:
            async for _event in api.watch("Pod", namespace=namespace):
                metrics.incr("chaos_watch_event")
        except asyncio.CancelledError:
            raise
        except WatchClosed:
            metrics.incr("chaos_watch_reopen")
        except Exception:
            metrics.incr("chaos_watch_reopen")
        await asyncio.sleep(0.01)


async def run_scenario(
    scenario: ChaosScenario,
    *,
    mutation: Optional[str] = None,
    ledger_path: Optional[str] = None,
    claims_path: Optional[str] = None,
    recorder: Optional[Any] = None,
    auditor: Optional[InvariantAuditor] = None,
    metrics=None,
) -> dict:
    """Materialise and drive ``scenario``; returns the game-day report
    (driver accounting, SLO snapshot, fired-fault trace fingerprint,
    applied actions, and the auditor's verdict)."""
    metrics = metrics if metrics is not None else METRICS
    fingerprint = scenario.fingerprint()
    plan, _compiled = scenario.compile_plan()
    if auditor is None:
        auditor = InvariantAuditor(
            recorder=recorder,
            metrics=metrics,
            fingerprint=fingerprint,
            scenario=scenario.name,
        )
    else:
        auditor.fingerprint = fingerprint
        auditor.scenario = scenario.name
    metrics.incr("chaos_scenario", exemplar=scenario.name)

    replicas = [
        SyntheticReplica(
            f"storm-replica-{i}", time_scale=scenario.time_scale, role=role
        )
        for i, role in enumerate(scenario.fleet)
    ]
    config = OperatorConfig(
        pattern_cache_directory="/nonexistent",
        conflict_backoff_base_s=0.001,
        memory_enabled=True,
        claims_path=claims_path,
    )
    stack = await build_storm_stack(
        replicas=replicas,
        config=config,
        metrics=metrics,
        ledger_path=ledger_path,
        time_scale=scenario.time_scale,
        deadline_factor=scenario.deadline_factor,
        namespace="gameday",
        fault_plan=plan,
        disaggregate=scenario.disaggregate,
    )
    # compressed game day: exclusion must HEAL within the run, so the
    # breaker reset window shrinks with it (set before any breaker is
    # minted — BreakerBoard passes board values at for_key time)
    stack.backend.router.health.breakers.reset_s = BREAKER_RESET_S

    fabric = _FabricPlane(scenario, metrics=metrics, fault_plan=plan)
    watch_task = asyncio.create_task(
        _watch_plane(stack.api, stack.namespace, metrics)
    )

    leadership = None
    wants_leadership = scenario.leadership or any(
        action.kind == "depose_leader"
        for phase in scenario.phases
        for action in phase.actions
    )
    if wants_leadership:
        leadership = _LeadershipPlane(stack, metrics=metrics)
        await leadership.start()

    if mutation == "drop-settle-on-conflict":
        _arm_mutation(stack, plan, metrics)
    elif mutation is not None:
        raise ValueError(f"unknown mutation {mutation!r}")

    # -- fleet actions, keyed to arrival index ---------------------------
    phase_queue = sorted(scenario.phases, key=lambda p: (p.at_arrival, p.name))
    applied: "list[dict]" = []
    state = {"submitted": 0}

    async def apply_action(action: FleetAction) -> None:
        metrics.incr("chaos_action", exemplar=action.kind)
        entry: dict = {"kind": action.kind, "phase": auditor.phase}
        if action.kind == "kill_replica":
            live = sorted(stack.backend.replicas)
            rid = action.replica or (live[-1] if live else "")
            if rid in stack.backend.replicas:
                stack.backend.remove_replica(rid)
            fabric.dead.add(rid)
            entry["replica"] = rid
        elif action.kind == "add_replica":
            rid = action.replica or f"gameday-scale-{len(applied)}"
            stack.backend.add_replica(
                SyntheticReplica(
                    rid, time_scale=scenario.time_scale, role=action.role
                )
            )
            entry["replica"] = rid
        elif action.kind == "depose_leader":
            if leadership is not None:
                entry["leader"] = await leadership.depose()
        applied.append(entry)

    def make_view(*, expected: Optional[int] = None) -> GameDayView:
        return GameDayView(
            ledger=stack.ledger,
            expected_terminal=expected,
            claims=(stack.pipeline.claims if wants_leadership else None),
            router=stack.backend.router,
            replica_ids=sorted(stack.backend.replicas),
            metrics=metrics,
        )

    async def submit(event: ArrivalEvent) -> None:
        while phase_queue and event.index >= phase_queue[0].at_arrival:
            phase = phase_queue.pop(0)
            auditor.phase = phase.name
            metrics.incr("chaos_phase", exemplar=phase.name)
            for action in phase.actions:
                await apply_action(action)
        if event.recall_hot:
            await fabric.touch(event)
        state["submitted"] += 1
        # capture the ordinal NOW: by the time the analysis await below
        # resumes, every other in-flight submit has bumped the counter
        # and a post-await read would skip (almost) every barrier
        ordinal = state["submitted"]
        # materialise the failing pod IN the apiserver (stack.submit only
        # passes the object) so the watch plane sees one event per
        # arrival — kube.create and kube.watch.* seams run under load
        pod = storm_pod(event, namespace=stack.namespace)
        try:
            await stack.api.create("Pod", pod.to_dict())
        except Exception:
            pass  # an injected create fault must not lose the arrival
        if wants_leadership:
            stack.api.set_pod_log(
                stack.namespace, pod.metadata.name, storm_log(event)
            )
            await stack.pipeline.process_failure_group(
                pod, [stack.podmortem],
                failure_time=f"storm-t{event.index}",
            )
        else:
            await stack.submit(event)
        if ordinal % BARRIER_EVERY == 0:
            auditor.check(make_view(), at="barrier")

    process = ArrivalProcess(scenario.arrivals, scenario.seed)
    try:
        report = await run_open_loop(
            submit, process,
            time_scale=scenario.time_scale, drain_s=scenario.drain_s,
        )
        # any breaker opened by the last injections still needs its
        # reset window to lapse before "exclusion healed" is checkable
        await asyncio.sleep(BREAKER_RESET_S + 0.05)
        # a clean drain is the only state where the ledger denominator
        # is exact; with cancelled arrivals the end probe still checks
        # pending==0 + terminality, just not the count
        expected = (
            state["submitted"]
            if report.get("drained") and not report.get("cancelled_at_drain")
            else None
        )
        auditor.phase = "end"
        auditor.check(make_view(expected=expected), at="end")
    finally:
        watch_task.cancel()
        await asyncio.gather(watch_task, return_exceptions=True)
        if leadership is not None:
            await leadership.close()
        stack.close()

    return {
        "scenario": scenario.name,
        "seed": scenario.seed,
        "fingerprint": fingerprint,
        "driver": report,
        "slo": stack.ledger.snapshot(),
        "violations": [v.to_dict() for v in auditor.violations],
        "invariant_checks": auditor.checks,
        "fault_trace_len": len(plan.trace()),
        "fault_fingerprint": plan.fingerprint(),
        "pending_faults": plan.pending(),
        "actions": applied,
        "fabric": fabric.index.stats(),
        "leader": (leadership.leader_id if leadership is not None else None),
    }


def _arm_mutation(stack, plan, metrics) -> None:
    """The deliberate bug behind the auditor-fires / shrinker tests:
    once any ``kube.patch_status`` conflict injection has FIRED, drop
    exactly one SLO-ledger settle.  Keyed to the fired-fault trace (per
    -site call order), so whether a scenario fails is a deterministic
    function of its injection set — exactly the predicate ddmin needs.
    """
    original_finish = stack.ledger.finish
    dropped = {"done": False}

    def finish(trace_id: str, **kwargs):
        if not dropped["done"] and any(
            site == "kube.patch_status" and "conflict" in action
            for site, _seq, action in plan.trace()
        ):
            dropped["done"] = True
            metrics.incr("chaos_mutation_dropped_settle")
            return None
        return original_finish(trace_id, **kwargs)

    stack.ledger.finish = finish
