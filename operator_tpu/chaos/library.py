"""The builtin game-day matrix.

Three composed scenarios the CI gameday smoke runs
(``LOADGEN_GAMEDAY=1 python -m operator_tpu.loadgen``), gated on zero
invariant violations and two-build fingerprint identity each.
``composed_storm`` is the acceptance scenario: six injections — watch
drop, apiserver jitter, a 409 storm on status writes, fabric fetch
timeouts, a replica partition — composed with a replica kill and a
leader depose, against the full operator -> router -> serving -> fabric
stack.

Seam names here are REGISTERED names (graftlint GL012 counts chaos
scenarios — python literals and ``tests/scenarios/*.json`` — as seam
naming sources and errors on any name missing from the registry).
"""

from __future__ import annotations

from ..loadgen.arrivals import ArrivalSpec
from .scenario import ChaosScenario, FleetAction, Injection, Phase


def composed_storm(seed: int = 2026) -> ChaosScenario:
    """Replica kill + peer partition + leader depose + watch drop +
    409 storm + fetch timeout, one scenario — the ISSUE's composed
    acceptance game day."""
    return ChaosScenario(
        name="composed-storm",
        seed=seed,
        arrivals=ArrivalSpec(
            name="storm",
            rate_per_min=600.0,
            duration_s=10.0,
            burst_factor=3.0,
            burst_every_s=4.0,
            burst_len_s=1.0,
            recall_hot_fraction=0.6,
        ),
        fleet=("mixed", "mixed", "mixed", "mixed"),
        leadership=True,
        phases=(
            Phase(
                name="baseline",
                at_arrival=0,
                injections=(
                    # latency-shaped apiserver reads from the start
                    Injection(
                        "kube.get", "jitter", count=8,
                        seconds=0.01, low=0.001,
                    ),
                    # drop the pod watch twice once it is established
                    Injection(
                        "kube.watch.Pod", "fail", error="watch-closed",
                        count=2, after=5,
                    ),
                ),
            ),
            Phase(
                name="degrade",
                at_arrival=20,
                injections=(
                    # 409 storm against Podmortem status writes
                    Injection(
                        "kube.patch_status", "fail", error="conflict",
                        count=6, after=10,
                    ),
                    # fabric fetches start timing out (decay path)
                    Injection(
                        "fabric.fetch", "fail", error="timeout",
                        count=4, after=6,
                    ),
                    # partition one replica for a bounded dispatch window
                    # (bounded so the exclusion HEALS — the
                    # no-permanent-exclusion invariant checks it did;
                    # the window sits early because the opened breaker
                    # steers dispatches AWAY from the partitioned
                    # replica, shrinking its matching-call budget)
                    Injection(
                        "router.dispatch", "fail", error="connection",
                        count=5, after=8,
                        match=(("replica", "storm-replica-1"),),
                    ),
                ),
            ),
            Phase(
                name="failover",
                at_arrival=45,
                injections=(
                    # the re-established watch stream dies at open once
                    Injection(
                        "kube.watch_open.Pod", "fail", error="watch-closed",
                        count=1, after=2,
                    ),
                ),
                actions=(
                    FleetAction("kill_replica", replica="storm-replica-3"),
                    FleetAction("depose_leader"),
                ),
            ),
        ),
    )


def scale_churn(seed: int = 7) -> ChaosScenario:
    """Elastic membership under fault load: scale up mid-storm, then
    kill a founding replica, with jittered dispatch and flaky log
    reads throughout."""
    return ChaosScenario(
        name="scale-churn",
        seed=seed,
        arrivals=ArrivalSpec(
            name="storm",
            rate_per_min=400.0,
            duration_s=8.0,
            burst_factor=2.5,
            burst_every_s=3.0,
            burst_len_s=1.0,
        ),
        fleet=("mixed", "mixed"),
        phases=(
            Phase(
                name="surge",
                at_arrival=0,
                injections=(
                    Injection(
                        "router.dispatch", "jitter", count=12,
                        seconds=0.008, low=0.001,
                    ),
                    Injection(
                        "kube.get_log", "fail", error="api-500",
                        count=3, after=4,
                    ),
                ),
            ),
            Phase(
                name="scale-up",
                at_arrival=15,
                actions=(FleetAction("add_replica", role="mixed"),),
            ),
            Phase(
                name="scale-down",
                at_arrival=35,
                injections=(
                    Injection(
                        "fabric.fetch", "fail", error="timeout", count=2,
                    ),
                ),
                actions=(
                    FleetAction("kill_replica", replica="storm-replica-1"),
                ),
            ),
        ),
    )


def disagg_fabric(seed: int = 13) -> ChaosScenario:
    """Disaggregated prefill/decode fleet with a hot fabric: fetch
    timeouts, delayed status writes, and a watch stream that expires
    its resume cursor."""
    return ChaosScenario(
        name="disagg-fabric",
        seed=seed,
        arrivals=ArrivalSpec(
            name="storm",
            rate_per_min=300.0,
            duration_s=8.0,
            recall_hot_fraction=0.7,
        ),
        fleet=("prefill", "decode", "mixed"),
        disaggregate=True,
        phases=(
            Phase(
                name="warm",
                at_arrival=0,
                injections=(
                    Injection(
                        "kube.patch", "delay", count=4, seconds=0.005,
                    ),
                    Injection(
                        "kube.watch_open.Pod", "fail",
                        error="watch-expired", count=1,
                    ),
                ),
            ),
            Phase(
                name="fabric-brownout",
                at_arrival=20,
                injections=(
                    Injection(
                        "fabric.fetch", "fail", error="timeout",
                        count=3, after=4,
                    ),
                ),
            ),
        ),
    )


def builtin_scenarios(seed: int = 0) -> "list[ChaosScenario]":
    """The seeded CI matrix; ``seed`` offsets every scenario's own seed
    so one knob reseeds the whole game day."""
    return [
        composed_storm(2026 + seed),
        scale_churn(7 + seed),
        disagg_fabric(13 + seed),
    ]
