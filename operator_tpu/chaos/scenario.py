"""Declarative game-day scenarios: composed fleet-wide chaos from one seed.

A :class:`ChaosScenario` is the unit a game day runs, shrinks, and
commits as a regression: an arrival shape (:class:`ArrivalSpec`), a
fleet layout, and an ordered list of :class:`Phase`\\ s, each carrying

- **injections** — faults addressed by REGISTERED SEAM NAME (the
  ``fault_plan`` seams graftlint GL012 audits: ``kube.*``,
  ``router.dispatch``, ``fabric.fetch``, ``http.provider``,
  ``engine.step``, ...), expressed in the :class:`FaultPlan` vocabulary
  extended with latency shaping (``delay``/``jitter``), and
- **fleet actions** — structural events no seam can express: kill a
  replica, add one (a scale event), depose the leader.

Determinism is the whole design.  Two different clocks exist in a run —
the arrival clock (scaled wall time) and each seam's CALL COUNTER — and
only the second is reproducible, so the two halves of a phase bind to
different triggers:

- **Injections are compiled into ONE FaultPlan at build time.**  Every
  probabilistic draw (jitter values, bernoulli picks) happens during
  :meth:`ChaosScenario.compile_plan` from the scenario seed, and each
  rule consumes per-site in call order; ``after=N`` call windows — not
  wall offsets — place a fault "later".  The per-site fired sequence is
  identical across runs regardless of event-loop interleaving.
- **Fleet actions trigger on ARRIVAL INDEX** (``Phase.at_arrival``):
  the conductor applies a phase's actions immediately before submitting
  arrival ``at_arrival``.  The arrival sequence is itself materialised
  from the seed, so "kill r1 at arrival 40" replays exactly even when
  wall time does not.

The scenario **fingerprint** is sha256 over the scenario dict, the
materialised arrival schedule, and the compiled plan rules — the same
materialisation-identity discipline as ``ArrivalSpec.fingerprint``.
Equal fingerprints mean the run is built from byte-identical inputs;
the CI gameday gate asserts fingerprint identity across two builds plus
zero invariant violations on both runs.

Scenarios round-trip through JSON (:meth:`to_json` / :meth:`from_json`)
so a shrunk minimal reproducer is a runnable artifact
(``LOADGEN_SCENARIO=repro.json python -m operator_tpu.loadgen``), and
:meth:`with_injections` re-derives a scenario from an injection subset —
the ddmin hook the shrinker (chaos/shrink.py) reduces over.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..loadgen.arrivals import ArrivalProcess, ArrivalSpec
from ..operator.kubeapi import (
    ApiError,
    ConflictError,
    NotFoundError,
    WatchClosed,
    WatchExpired,
)
from ..utils import faultinject
from ..utils.faultinject import FaultPlan

#: named error factories an injection may raise — names, not callables,
#: so scenarios serialise to JSON and replay from it.  Keep in sync with
#: docs/ROBUSTNESS.md's scenario-schema table.
ERRORS: dict = {
    "conflict": lambda: ConflictError("chaos: injected 409"),
    "api-500": lambda: ApiError("chaos: injected apiserver 500", 500),
    "not-found": lambda: NotFoundError("chaos: injected 404"),
    "watch-closed": lambda: WatchClosed("chaos: watch dropped"),
    "watch-expired": lambda: WatchExpired("chaos: resourceVersion expired"),
    "timeout": lambda: TimeoutError("chaos: injected timeout"),
    "connection": lambda: ConnectionError("chaos: connection refused"),
    "runtime": lambda: RuntimeError("chaos: injected fault"),
}

#: fleet action kinds the conductor knows how to apply
ACTION_KINDS = ("kill_replica", "add_replica", "depose_leader")


@dataclass(frozen=True)
class Injection:
    """One seam-addressed fault.

    ``kind``:

    - ``fail`` — raise ``ERRORS[error]`` at the seam, ``count`` times;
    - ``delay`` — hold the seam call ``seconds`` then succeed, ``count``
      times (never blocks the event loop — see faultinject.delay_);
    - ``jitter`` — ``count`` seeded uniform ``[low, seconds)`` delays
      drawn at compile time.

    ``after`` skips that many matching calls first (a call window, the
    deterministic stand-in for "later in the run").  ``match`` narrows
    by seam context, compared stringly so it survives JSON: a partition
    of replica r1 is ``Injection("router.dispatch", "fail",
    error="connection", count=999, match=(("replica", "r1"),))``.
    """

    seam: str
    kind: str = "fail"
    count: int = 1
    after: int = 0
    error: str = "runtime"
    seconds: float = 0.0
    low: float = 0.0
    match: "tuple[tuple[str, str], ...]" = ()

    def __post_init__(self) -> None:
        if self.kind not in ("fail", "delay", "jitter"):
            raise ValueError(f"unknown injection kind {self.kind!r}")
        if self.kind == "fail" and self.error not in ERRORS:
            raise ValueError(
                f"unknown error {self.error!r}; known: {sorted(ERRORS)}"
            )

    def to_dict(self) -> dict:
        out: dict = {"seam": self.seam, "kind": self.kind}
        if self.count != 1:
            out["count"] = self.count
        if self.after:
            out["after"] = self.after
        if self.kind == "fail":
            out["error"] = self.error
        else:
            out["seconds"] = self.seconds
            if self.kind == "jitter":
                out["low"] = self.low
        if self.match:
            out["match"] = {k: v for k, v in self.match}
        return out

    @classmethod
    def from_dict(cls, data: dict) -> "Injection":
        match = tuple(
            sorted((str(k), str(v)) for k, v in (data.get("match") or {}).items())
        )
        return cls(
            seam=data["seam"],
            kind=data.get("kind", "fail"),
            count=int(data.get("count", 1)),
            after=int(data.get("after", 0)),
            error=data.get("error", "runtime"),
            seconds=float(data.get("seconds", 0.0)),
            low=float(data.get("low", 0.0)),
            match=match,
        )

    def matcher(self) -> Optional[Callable[..., bool]]:
        if not self.match:
            return None
        pairs = self.match

        def _match(**ctx) -> bool:
            return all(str(ctx.get(k)) == v for k, v in pairs)

        return _match

    def compile_into(self, plan: FaultPlan) -> dict:
        """Append this injection's rule to ``plan``; returns the
        compiled-rule dict that feeds the scenario fingerprint (jitter
        values are drawn HERE, so they are part of the fingerprint)."""
        if self.kind == "fail":
            actions = faultinject.times(
                self.count, faultinject.raise_(ERRORS[self.error], self.error)
            )
            compiled = {"actions": [self.error] * self.count}
        elif self.kind == "delay":
            actions = faultinject.times(
                self.count, faultinject.delay_(self.seconds)
            )
            compiled = {"actions": [repr(a) for a in actions]}
        else:  # jitter: seeded draws happen NOW, from the plan rng
            actions = plan.jitter(self.count, self.low, self.seconds)
            compiled = {"actions": [repr(a) for a in actions]}
        plan.rule(self.seam, actions, after=self.after, match=self.matcher())
        compiled.update(
            {"seam": self.seam, "after": self.after, "match": dict(self.match)}
        )
        return compiled


@dataclass(frozen=True)
class FleetAction:
    """A structural fleet event applied at the owning phase's trigger
    arrival: ``kill_replica`` / ``add_replica`` (scale events against
    the serving backend) or ``depose_leader`` (graceful lease handover +
    claim resume on the standby)."""

    kind: str
    replica: str = ""
    role: str = "mixed"

    def __post_init__(self) -> None:
        if self.kind not in ACTION_KINDS:
            raise ValueError(
                f"unknown action kind {self.kind!r}; known: {ACTION_KINDS}"
            )

    def to_dict(self) -> dict:
        out: dict = {"kind": self.kind}
        if self.replica:
            out["replica"] = self.replica
        if self.role != "mixed":
            out["role"] = self.role
        return out

    @classmethod
    def from_dict(cls, data: dict) -> "FleetAction":
        return cls(
            kind=data["kind"],
            replica=data.get("replica", ""),
            role=data.get("role", "mixed"),
        )


@dataclass(frozen=True)
class Phase:
    """One act of the scenario: fleet ``actions`` fire immediately
    before arrival ``at_arrival`` is submitted; ``injections`` are
    compiled into the run's single FaultPlan at build time (their
    placement is their ``after`` call window, not the phase trigger —
    the phase is documentation + black-box attribution for them)."""

    name: str
    at_arrival: int = 0
    injections: "tuple[Injection, ...]" = ()
    actions: "tuple[FleetAction, ...]" = ()

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "at_arrival": self.at_arrival,
            "injections": [i.to_dict() for i in self.injections],
            "actions": [a.to_dict() for a in self.actions],
        }

    @classmethod
    def from_dict(cls, data: dict) -> "Phase":
        return cls(
            name=data["name"],
            at_arrival=int(data.get("at_arrival", 0)),
            injections=tuple(
                Injection.from_dict(i) for i in data.get("injections", ())
            ),
            actions=tuple(
                FleetAction.from_dict(a) for a in data.get("actions", ())
            ),
        )


@dataclass(frozen=True)
class ChaosScenario:
    """A full game day: arrivals + fleet layout + phased chaos.

    ``fleet`` is the synthetic replica roles to start with (length =
    initial fleet size); ``leadership`` routes submissions through the
    claim ledger under a live lease pair so ``depose_leader`` has a
    leader to depose (it is implied when any phase deposes).
    """

    name: str
    seed: int = 0
    arrivals: ArrivalSpec = field(default_factory=ArrivalSpec)
    phases: "tuple[Phase, ...]" = ()
    fleet: "tuple[str, ...]" = ("mixed", "mixed")
    disaggregate: bool = False
    leadership: bool = False
    time_scale: float = 0.02
    drain_s: float = 30.0
    deadline_factor: float = 4.0

    # -- serialisation -------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "seed": self.seed,
            "arrivals": self.arrivals.to_dict(),
            "phases": [p.to_dict() for p in self.phases],
            "fleet": list(self.fleet),
            "disaggregate": self.disaggregate,
            "leadership": self.leadership,
            "time_scale": self.time_scale,
            "drain_s": self.drain_s,
            "deadline_factor": self.deadline_factor,
        }

    @classmethod
    def from_dict(cls, data: dict) -> "ChaosScenario":
        spec_data = dict(data.get("arrivals", {}))
        if "class_mix" in spec_data:
            spec_data["class_mix"] = tuple(
                (str(n), float(w)) for n, w in spec_data["class_mix"]
            )
        return cls(
            name=data["name"],
            seed=int(data.get("seed", 0)),
            arrivals=ArrivalSpec(**spec_data),
            phases=tuple(Phase.from_dict(p) for p in data.get("phases", ())),
            fleet=tuple(data.get("fleet", ("mixed", "mixed"))),
            disaggregate=bool(data.get("disaggregate", False)),
            leadership=bool(data.get("leadership", False)),
            time_scale=float(data.get("time_scale", 0.02)),
            drain_s=float(data.get("drain_s", 30.0)),
            deadline_factor=float(data.get("deadline_factor", 4.0)),
        )

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "ChaosScenario":
        return cls.from_dict(json.loads(text))

    # -- shrinking surface ---------------------------------------------
    def injections(self) -> "list[Injection]":
        """All injections in phase order — the flat index space ddmin
        (chaos/shrink.py) reduces over."""
        return [i for phase in self.phases for i in phase.injections]

    def with_injections(self, indices: "list[int]") -> "ChaosScenario":
        """The same scenario keeping only the injections at ``indices``
        (into :meth:`injections` order).  Phases and fleet actions are
        preserved so the structural context of a shrunk repro is intact;
        empty phases stay as named markers."""
        keep = set(indices)
        phases = []
        cursor = 0
        for phase in self.phases:
            kept_list = []
            for inj in phase.injections:
                if cursor in keep:
                    kept_list.append(inj)
                cursor += 1
            kept = tuple(kept_list)
            phases.append(
                Phase(
                    name=phase.name,
                    at_arrival=phase.at_arrival,
                    injections=kept,
                    actions=phase.actions,
                )
            )
        return ChaosScenario(
            name=self.name,
            seed=self.seed,
            arrivals=self.arrivals,
            phases=tuple(phases),
            fleet=self.fleet,
            disaggregate=self.disaggregate,
            leadership=self.leadership,
            time_scale=self.time_scale,
            drain_s=self.drain_s,
            deadline_factor=self.deadline_factor,
        )

    # -- compilation ---------------------------------------------------
    def compile_plan(self) -> "tuple[FaultPlan, list[dict]]":
        """Materialise every injection into one seeded FaultPlan.  All
        probabilistic draws happen here; the returned compiled-rule
        list is the fingerprint's record of them."""
        plan = FaultPlan(seed=self.seed)
        compiled = [
            inj.compile_into(plan)
            for phase in self.phases
            for inj in phase.injections
        ]
        return plan, compiled

    def fingerprint(self) -> str:
        """sha256 over the scenario, its materialised arrival schedule,
        and its compiled plan — materialisation identity, the same
        discipline as ``ArrivalProcess.fingerprint``.  Equal
        fingerprints = the run is driven by byte-identical inputs."""
        _, compiled = self.compile_plan()
        basis = {
            "scenario": self.to_dict(),
            "arrivals": ArrivalProcess(self.arrivals, self.seed).fingerprint(),
            "plan": compiled,
        }
        return hashlib.sha256(
            json.dumps(basis, sort_keys=True).encode()
        ).hexdigest()
