"""Game-day chaos: composed scenarios, always-on invariants, shrinking.

Three composing pieces (docs/ROBUSTNESS.md, "Game days"):

- :mod:`.scenario` / :mod:`.conductor` — declarative seeded
  :class:`ChaosScenario` (phased injections by registered seam name +
  fleet actions) driven through the storm stack, byte-identical from
  one seed with a sha256 fingerprint;
- :mod:`.invariants` — the :class:`InvariantAuditor` checking
  fleet-wide conservation probes at commit barriers and scenario end,
  black-boxing violations through the flight recorder;
- :mod:`.shrink` — ddmin over a failing scenario's injection set down
  to a minimal runnable reproducer.
"""

from .conductor import run_scenario
from .invariants import GameDayView, InvariantAuditor, Violation
from .library import builtin_scenarios, composed_storm, disagg_fabric, scale_churn
from .scenario import ERRORS, ChaosScenario, FleetAction, Injection, Phase
from .shrink import ShrinkResult, shrink

__all__ = [
    "ChaosScenario",
    "ERRORS",
    "FleetAction",
    "GameDayView",
    "Injection",
    "InvariantAuditor",
    "Phase",
    "ShrinkResult",
    "Violation",
    "builtin_scenarios",
    "composed_storm",
    "disagg_fabric",
    "run_scenario",
    "scale_churn",
    "shrink",
]
