"""Fleet-wide conservation invariants checked DURING chaos, not after.

A game day is only as good as its oracle.  Pass/fail on "the run
finished" misses the bugs chaos is for — a leaked KV page, a double
incident, an arrival that silently vanished between shed and settle.
The :class:`InvariantAuditor` holds a catalogue of conservation PROBES
and is checked at two kinds of barrier:

- **commit barriers** — the serving scheduler calls its ``audit_hook``
  after every step's commit window (sched/scheduler.py), the one point
  where page accounting must balance exactly even mid-flight;
- **scenario end** — after drain, when every admitted arrival must have
  reached exactly one terminal outcome and every transient exclusion
  must have healed.

Each probe takes a :class:`GameDayView` — a duck-typed bag of whatever
planes the harness wired up — and returns ``None`` (holds), a detail
dict (VIOLATED), or skips itself when its plane is absent (a probe must
never invent a violation about state it cannot see).  Violations are
counted (``podmortem_invariant_violation``), kept on
:attr:`InvariantAuditor.violations`, and flight-recorded: the auditor
records a synthetic trace and black-boxes it tagged with the scenario
fingerprint + phase, so a violated run leaves the same forensic
artifact a deadline breach does (obs/record.py).

The catalogue (see docs/ROBUSTNESS.md for the prose contracts):

====================  ==========  ========================================
probe                 barrier     conservation law
====================  ==========  ========================================
kv-page-conservation  any         available + row + store + prefix pages
                                  == num_pages - 1, per scheduler
stream-monotonicity   any         per-request streamed token counts never
                                  decrease
fabric-checksum       any         adopted fabric blocks <= checksum-
                                  verified fetches (nothing adopted
                                  unverified)
arrival-conservation  end         ledger pending == 0; every record
                                  terminal; denominator == admitted
claim-exactly-once    end         no claim left pending; <= 1 status
                                  write per failure
no-permanent-         end         every live replica routable again after
exclusion                         breaker reset
====================  ==========  ========================================
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..obs.sloledger import TERMINAL_OUTCOMES
from ..utils.timing import METRICS


@dataclass(frozen=True)
class Violation:
    """One broken invariant, tagged for the black box."""

    name: str
    at: str  # "barrier" | "end"
    phase: str
    detail: dict

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "at": self.at,
            "phase": self.phase,
            "detail": self.detail,
        }


@dataclass
class GameDayView:
    """What the auditor can see.  Every field is optional — probes skip
    planes the harness did not wire (``None`` field = probe abstains).
    """

    #: obs.sloledger.SLOLedger
    ledger: Optional[Any] = None
    #: arrivals admitted to the ledger (the conservation denominator)
    expected_terminal: Optional[int] = None
    #: operator.claims.ClaimLedger — NOTE take_pending() drains, so the
    #: claim probe is end-only by construction
    claims: Optional[Any] = None
    #: failure-key -> successful Podmortem status writes
    status_write_counts: Optional[dict] = None
    #: serving schedulers exposing page_accounting()
    schedulers: "list[Any]" = field(default_factory=list)
    #: router.core.EngineRouter (health board read via .health)
    router: Optional[Any] = None
    #: replica ids that SHOULD be routable (still fleet members)
    replica_ids: "list[str]" = field(default_factory=list)
    #: utils.timing metrics registry (counter() reads)
    metrics: Optional[Any] = None
    #: request-id -> cumulative streamed token counts, append-only
    streams: Optional[dict] = None


class InvariantAuditor:
    """Run the probe catalogue at barriers; black-box what breaks."""

    def __init__(
        self,
        *,
        recorder: Optional[Any] = None,
        metrics=None,
        fingerprint: str = "",
        scenario: str = "",
    ) -> None:
        self.recorder = recorder
        self.metrics = metrics if metrics is not None else METRICS
        self.fingerprint = fingerprint
        self.scenario = scenario
        self.violations: "list[Violation]" = []
        self.checks = 0
        #: current phase name, set by the conductor as phases trigger so
        #: violations attribute to the act that broke them
        self.phase = ""
        self._seq = itertools.count(1)
        self._probes: "list[tuple[str, str, Callable[[GameDayView], Optional[dict]]]]" = []
        self._register_defaults()

    # -- catalogue -----------------------------------------------------
    def register(
        self,
        name: str,
        probe: Callable[[GameDayView], Optional[dict]],
        *,
        when: str = "any",
    ) -> None:
        """Add a probe.  ``when`` is ``any`` (every barrier) or ``end``
        (scenario end only — for laws that only hold at quiescence)."""
        if when not in ("any", "end"):
            raise ValueError(f"when must be 'any' or 'end', got {when!r}")
        self._probes.append((name, when, probe))

    def _register_defaults(self) -> None:
        self.register("kv-page-conservation", _probe_kv_pages)
        self.register("stream-monotonicity", _probe_stream_monotonic)
        self.register("fabric-checksum-adoption", _probe_fabric_checksum)
        self.register("arrival-conservation", _probe_arrivals, when="end")
        self.register("claim-exactly-once", _probe_claims, when="end")
        self.register(
            "no-permanent-exclusion", _probe_no_exclusion, when="end"
        )

    # -- checking ------------------------------------------------------
    def check(self, view: GameDayView, *, at: str = "barrier") -> "list[Violation]":
        """Run every probe eligible at this barrier; returns (and
        accumulates) the violations found."""
        self.checks += 1
        self.metrics.incr("invariant_check")
        found: "list[Violation]" = []
        for name, when, probe in self._probes:
            if when == "end" and at != "end":
                continue
            detail = probe(view)
            if detail is None:
                continue
            violation = Violation(
                name=name, at=at, phase=self.phase, detail=detail
            )
            found.append(violation)
            self.violations.append(violation)
            self.metrics.incr("invariant_violation", exemplar=name)
            self._black_box(violation)
        return found

    def barrier_hook(self, view_of: Callable[[Any], GameDayView]) -> Callable:
        """Adapt the auditor to the scheduler's ``audit_hook(sched)``
        shape: ``view_of(sched)`` builds the view each barrier."""

        def hook(sched) -> None:
            self.check(view_of(sched), at="barrier")

        return hook

    def report(self) -> dict:
        return {
            "scenario": self.scenario,
            "fingerprint": self.fingerprint,
            "checks": self.checks,
            "violations": [v.to_dict() for v in self.violations],
        }

    # -- forensics -----------------------------------------------------
    def _black_box(self, violation: Violation) -> None:
        """Leave the same artifact a deadline breach does: the recorder
        ring only dumps traces it holds, so record a synthetic trace for
        the violation FIRST, then black-box it (obs/record.py)."""
        if self.recorder is None:
            return
        trace_id = f"invariant-{violation.name}-{next(self._seq)}"
        self.recorder.record(
            {
                "traceId": trace_id,
                "name": f"invariant/{violation.name}",
                "scenario": self.scenario,
                "fingerprint": self.fingerprint,
                "phase": violation.phase,
                "detail": violation.detail,
            }
        )
        self.recorder.black_box(
            trace_id,
            f"invariant-violation:{violation.name}",
            {
                "scenario": self.scenario,
                "fingerprint": self.fingerprint,
                "phase": violation.phase,
                "at": violation.at,
                **violation.detail,
            },
        )


# -- the default probes ------------------------------------------------


def _probe_kv_pages(view: GameDayView) -> Optional[dict]:
    """Every page is exactly one of: free, granted to a row, pinned by
    the prefix cache, or held for the system prefix."""
    bad = []
    for i, sched in enumerate(view.schedulers):
        acct = sched.page_accounting()
        held = (
            acct["available"]
            + acct["row_pages"]
            + acct["store_pages"]
            + acct["prefix_pages"]
        )
        if held != acct["total"]:
            bad.append({"scheduler": i, **acct, "sum": held})
    return {"imbalanced": bad} if bad else None


def _probe_stream_monotonic(view: GameDayView) -> Optional[dict]:
    if not view.streams:
        return None
    bad = {
        rid: counts
        for rid, counts in view.streams.items()
        if any(b < a for a, b in zip(counts, counts[1:]))
    }
    return {"regressed": bad} if bad else None


def _probe_fabric_checksum(view: GameDayView) -> Optional[dict]:
    """Adoption implies verification: prefetch only adopts blocks whose
    checksum round-tripped, so adopted can never exceed verified-ok."""
    if view.metrics is None:
        return None
    adopted = view.metrics.counter("fabric_prefetch_adopted")
    ok = view.metrics.counter("fabric_fetch_ok")
    if adopted > ok:
        return {"adopted": adopted, "fetch_ok": ok}
    return None


def _probe_arrivals(view: GameDayView) -> Optional[dict]:
    """Every admitted arrival reaches EXACTLY ONE terminal outcome: no
    pending stragglers after drain, no non-terminal records, and the
    ledger denominator equals what the harness admitted."""
    ledger = view.ledger
    if ledger is None:
        return None
    detail: dict = {}
    if ledger.pending:
        detail["pending"] = ledger.pending
    records = ledger.records
    non_terminal = [
        r.trace_id for r in records if r.outcome not in TERMINAL_OUTCOMES
    ]
    if non_terminal:
        detail["non_terminal"] = non_terminal[:10]
    if (
        view.expected_terminal is not None
        and len(records) + ledger.pending != view.expected_terminal
    ):
        detail["ledger_total"] = len(records) + ledger.pending
        detail["expected"] = view.expected_terminal
    return detail or None


def _probe_claims(view: GameDayView) -> Optional[dict]:
    detail: dict = {}
    if view.claims is not None:
        leftover = view.claims.take_pending()
        if leftover:
            detail["unresumed_claims"] = [c.key for c in leftover]
    if view.status_write_counts:
        doubled = {
            key: n for key, n in view.status_write_counts.items() if n > 1
        }
        if doubled:
            detail["double_status_writes"] = doubled
    return detail or None


def _probe_no_exclusion(view: GameDayView) -> Optional[dict]:
    """Transient exclusion must heal: after the breaker reset window,
    every replica still in the fleet is routable again.  A replica the
    scenario KILLED is gone from ``replica_ids`` — this is about healed
    peers, not corpses."""
    if view.router is None or not view.replica_ids:
        return None
    health = getattr(view.router, "health", None)
    if health is None:
        return None
    excluded = [
        rid for rid in view.replica_ids if not health.can_route(rid)
    ]
    return {"permanently_excluded": excluded} if excluded else None
