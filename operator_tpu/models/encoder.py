"""BERT-style sentence encoder (all-MiniLM-L6-v2 class) in functional JAX.

The semantic pattern path (SURVEY.md §7 stage 3) embeds log windows and
pattern descriptions into one vector space; this is the encoder that does
it.  Architecture per the public MiniLM config (6 post-LN transformer
layers, hidden 384, 12 heads, GELU MLP), with the sentence-transformers
convention on top: masked mean pooling then L2 normalisation, so cosine
similarity is a dot product and the similarity kernel
(ops/similarity.py) needs no extra normalisation pass.

Same TPU-first choices as the decoder (models/llama.py): per-layer params
stacked on a leading axis and scanned with ``lax.scan``; bf16 matmuls with
f32 accumulation; LayerNorm statistics in f32.

Reference-system context: the external log-parser service owned all
scoring (reference LogParserRestClient.java:37-39); its rebuilt semantic
scorer runs this encoder on TPU instead of calling out.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, Iterable, Mapping, Optional

import jax
import jax.numpy as jnp

Params = dict[str, Any]


@dataclass(frozen=True)
class EncoderConfig:
    name: str
    vocab_size: int = 30522
    hidden_size: int = 384
    intermediate_size: int = 1536
    num_layers: int = 6
    num_heads: int = 12
    max_positions: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


MINILM_L6 = EncoderConfig(name="minilm-l6")

#: laptop-sized config for tests (real architecture, tiny widths)
ENCODER_TINY_TEST = EncoderConfig(
    name="encoder-tiny-test",
    vocab_size=512,
    hidden_size=64,
    intermediate_size=128,
    num_layers=2,
    num_heads=4,
    max_positions=128,
)


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------


def init_encoder_params(
    config: EncoderConfig, key: jax.Array, dtype: jnp.dtype = jnp.float32
) -> Params:
    h, f, n = config.hidden_size, config.intermediate_size, config.num_layers
    keys = jax.random.split(key, 12)

    def dense(k: jax.Array, shape: tuple[int, ...]) -> jax.Array:
        scale = shape[-2] ** -0.5 if len(shape) >= 2 else 0.02
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    layers = {
        "wq": dense(keys[0], (n, h, h)),
        "bq": jnp.zeros((n, h), dtype),
        "wk": dense(keys[1], (n, h, h)),
        "bk": jnp.zeros((n, h), dtype),
        "wv": dense(keys[2], (n, h, h)),
        "bv": jnp.zeros((n, h), dtype),
        "wo": dense(keys[3], (n, h, h)),
        "bo": jnp.zeros((n, h), dtype),
        "ln_attn_scale": jnp.ones((n, h), dtype),
        "ln_attn_bias": jnp.zeros((n, h), dtype),
        "w_in": dense(keys[4], (n, h, f)),
        "b_in": jnp.zeros((n, f), dtype),
        "w_out": dense(keys[5], (n, f, h)),
        "b_out": jnp.zeros((n, h), dtype),
        "ln_mlp_scale": jnp.ones((n, h), dtype),
        "ln_mlp_bias": jnp.zeros((n, h), dtype),
    }
    return {
        "tok_embed": dense(keys[6], (config.vocab_size, h)),
        "pos_embed": dense(keys[7], (config.max_positions, h)),
        "type_embed": dense(keys[8], (config.type_vocab_size, h)),
        "ln_embed_scale": jnp.ones((h,), dtype),
        "ln_embed_bias": jnp.zeros((h,), dtype),
        "layers": layers,
    }


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _layer_norm(x: jax.Array, scale: jax.Array, bias: jax.Array, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    normed = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (normed * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(x.dtype)


def encode_tokens(
    params: Params,
    config: EncoderConfig,
    token_ids: jax.Array,  # [B, T] int32
    attention_mask: jax.Array,  # [B, T] 1 for real tokens
) -> jax.Array:
    """Token-level hidden states [B, T, H] (post-LN BERT stack)."""
    b, t = token_ids.shape
    x = (
        jnp.take(params["tok_embed"], token_ids, axis=0)
        + params["pos_embed"][None, :t]
        + params["type_embed"][0][None, None, :]
    )
    x = _layer_norm(x, params["ln_embed_scale"], params["ln_embed_bias"], config.layer_norm_eps)

    nh, d = config.num_heads, config.head_dim
    # additive mask [B, 1, 1, T] — padded keys get -inf before softmax
    bias = jnp.where(attention_mask[:, None, None, :] > 0, 0.0, -1e30).astype(jnp.float32)

    def layer_step(x: jax.Array, w: dict[str, jax.Array]):
        q = (x @ w["wq"] + w["bq"]).reshape(b, t, nh, d)
        k = (x @ w["wk"] + w["bk"]).reshape(b, t, nh, d)
        v = (x @ w["wv"] + w["bv"]).reshape(b, t, nh, d)
        scores = jnp.einsum("bthd,bshd->bhts", q, k, preferred_element_type=jnp.float32)
        scores = scores * (d**-0.5) + bias
        probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
        attn = jnp.einsum("bhts,bshd->bthd", probs, v).reshape(b, t, nh * d)
        x = _layer_norm(
            x + attn @ w["wo"] + w["bo"], w["ln_attn_scale"], w["ln_attn_bias"],
            config.layer_norm_eps,
        )
        mlp = jax.nn.gelu(x @ w["w_in"] + w["b_in"], approximate=False)
        x = _layer_norm(
            x + mlp @ w["w_out"] + w["b_out"], w["ln_mlp_scale"], w["ln_mlp_bias"],
            config.layer_norm_eps,
        )
        return x, None

    x, _ = jax.lax.scan(layer_step, x, params["layers"])
    return x


def encode(
    params: Params,
    config: EncoderConfig,
    token_ids: jax.Array,
    attention_mask: jax.Array,
) -> jax.Array:
    """Sentence embeddings [B, H]: masked mean pool + L2 normalise."""
    hidden = encode_tokens(params, config, token_ids, attention_mask)
    mask = attention_mask[..., None].astype(jnp.float32)
    summed = jnp.sum(hidden.astype(jnp.float32) * mask, axis=1)
    counts = jnp.maximum(jnp.sum(mask, axis=1), 1.0)
    pooled = summed / counts
    return pooled / jnp.maximum(jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-12)


# ---------------------------------------------------------------------------
# HF BERT checkpoint conversion (all-MiniLM-L6-v2 layout)
# ---------------------------------------------------------------------------

_BERT_LAYER_RE = re.compile(r"(?:bert\.)?encoder\.layer\.(\d+)\.(.+)")

#: HF sub-name -> (our stacked name, transpose?)
_BERT_LAYER_MAP = {
    "attention.self.query.weight": ("wq", True),
    "attention.self.query.bias": ("bq", False),
    "attention.self.key.weight": ("wk", True),
    "attention.self.key.bias": ("bk", False),
    "attention.self.value.weight": ("wv", True),
    "attention.self.value.bias": ("bv", False),
    "attention.output.dense.weight": ("wo", True),
    "attention.output.dense.bias": ("bo", False),
    "attention.output.LayerNorm.weight": ("ln_attn_scale", False),
    "attention.output.LayerNorm.bias": ("ln_attn_bias", False),
    "intermediate.dense.weight": ("w_in", True),
    "intermediate.dense.bias": ("b_in", False),
    "output.dense.weight": ("w_out", True),
    "output.dense.bias": ("b_out", False),
    "output.LayerNorm.weight": ("ln_mlp_scale", False),
    "output.LayerNorm.bias": ("ln_mlp_bias", False),
}

_BERT_TOP_MAP = {
    "embeddings.word_embeddings.weight": "tok_embed",
    "embeddings.position_embeddings.weight": "pos_embed",
    "embeddings.token_type_embeddings.weight": "type_embed",
    "embeddings.LayerNorm.weight": "ln_embed_scale",
    "embeddings.LayerNorm.bias": "ln_embed_bias",
}


def convert_hf_bert_state_dict(
    state: "Mapping[str, Any] | Iterable[tuple[str, Any]]",
    config: EncoderConfig,
    dtype: jnp.dtype = jnp.float32,
) -> Params:
    """Map a HF BERT state dict to the stacked pytree ``encode`` uses."""
    import numpy as np

    from .loader import _to_numpy

    n = config.num_layers
    per_layer: dict[str, list[Optional[Any]]] = {
        ours: [None] * n for ours, _ in _BERT_LAYER_MAP.values()
    }
    top: dict[str, jax.Array] = {}
    items = state.items() if hasattr(state, "items") else state
    for name, raw in items:
        bare = name.removeprefix("bert.")
        if bare in _BERT_TOP_MAP:
            top[_BERT_TOP_MAP[bare]] = jnp.asarray(_to_numpy(raw), dtype)
            continue
        match = _BERT_LAYER_RE.fullmatch(name)
        if not match:
            continue
        idx, sub = int(match.group(1)), match.group(2)
        mapped = _BERT_LAYER_MAP.get(sub)
        if mapped is None or idx >= n:
            continue
        ours, transpose = mapped
        array = _to_numpy(raw)
        per_layer[ours][idx] = array.T if transpose else array

    missing = [
        f"{ours}[{i}]"
        for ours, slots in per_layer.items()
        for i, s in enumerate(slots)
        if s is None
    ]
    if missing:
        raise ValueError(f"encoder checkpoint missing {len(missing)} tensors, e.g. {missing[:4]}")
    layers = {ours: jnp.asarray(np.stack(slots), dtype) for ours, slots in per_layer.items()}
    missing_top = [k for k in _BERT_TOP_MAP.values() if k not in top]
    if missing_top:
        raise ValueError(f"encoder checkpoint missing {missing_top}")
    return {**top, "layers": layers}


def encoder_config_from_hf_json(checkpoint_dir: str) -> EncoderConfig:
    """Build an :class:`EncoderConfig` from a HF ``config.json`` (the
    all-MiniLM-L6-v2 layout); falls back to MINILM_L6 when absent."""
    import json
    import os

    path = os.path.join(checkpoint_dir, "config.json")
    if not os.path.exists(path):
        return MINILM_L6
    with open(path) as f:
        raw = json.load(f)
    return EncoderConfig(
        name=raw.get("_name_or_path") or os.path.basename(checkpoint_dir) or "hf-encoder",
        vocab_size=int(raw.get("vocab_size", MINILM_L6.vocab_size)),
        hidden_size=int(raw.get("hidden_size", MINILM_L6.hidden_size)),
        intermediate_size=int(raw.get("intermediate_size", MINILM_L6.intermediate_size)),
        num_layers=int(raw.get("num_hidden_layers", MINILM_L6.num_layers)),
        num_heads=int(raw.get("num_attention_heads", MINILM_L6.num_heads)),
        max_positions=int(raw.get("max_position_embeddings", MINILM_L6.max_positions)),
        type_vocab_size=int(raw.get("type_vocab_size", MINILM_L6.type_vocab_size)),
        layer_norm_eps=float(raw.get("layer_norm_eps", MINILM_L6.layer_norm_eps)),
    )


def load_encoder_params(
    checkpoint_dir: str,
    config: Optional[EncoderConfig] = None,
    dtype: jnp.dtype = jnp.float32,
) -> tuple[Params, EncoderConfig]:
    """Load a MiniLM-class safetensors checkpoint directory.

    Completes the subsumed log-parser's semantic path (reference contract
    LogParserRestClient.java:37-39): with this, NeuralEmbedder runs on real
    sentence-transformer weights instead of random init.  Returns
    ``(params, config)`` with the config read from the directory's
    ``config.json`` unless one is passed.
    """
    from .loader import iter_safetensors

    config = config or encoder_config_from_hf_json(checkpoint_dir)
    params = convert_hf_bert_state_dict(iter_safetensors(checkpoint_dir), config, dtype)
    return params, config
