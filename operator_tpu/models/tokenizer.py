"""Tokenizers.

Two implementations behind one minimal interface (encode/decode/ids):

- ``HFTokenizer`` wraps a local ``transformers`` tokenizer directory (the
  production path for TinyLlama / Llama-3 / Mistral checkpoints on the PVC);
- ``ByteTokenizer`` is a dependency-free byte-level fallback (vocab 256 + a
  few specials) used by tests and air-gapped environments — this repo's CI
  has zero egress, so nothing may require a hub download.
"""

from __future__ import annotations

from typing import Optional, Protocol, Sequence


class Tokenizer(Protocol):
    vocab_size: int
    bos_id: Optional[int]
    eos_id: Optional[int]
    pad_id: int

    def encode(self, text: str, *, add_bos: bool = True) -> list[int]: ...

    def decode(self, ids: Sequence[int]) -> str: ...


class ByteTokenizer:
    """UTF-8 bytes shifted by the special-token block."""

    SPECIALS = 3  # pad=0, bos=1, eos=2

    def __init__(self) -> None:
        self.pad_id = 0
        self.bos_id = 1
        self.eos_id = 2
        self.vocab_size = 256 + self.SPECIALS

    def encode(self, text: str, *, add_bos: bool = True) -> list[int]:
        ids = [b + self.SPECIALS for b in text.encode("utf-8")]
        return ([self.bos_id] if add_bos else []) + ids

    def decode(self, ids: Sequence[int]) -> str:
        # ids beyond the byte range are skipped (a model vocab can exceed
        # the tokenizer's 259 ids; sampling may legally pick those)
        data = bytes(
            i - self.SPECIALS for i in ids if self.SPECIALS <= i < 256 + self.SPECIALS
        )
        return data.decode("utf-8", errors="replace")


class HFTokenizer:
    """Wrapper over a *local* transformers tokenizer (no hub access)."""

    def __init__(self, path: str) -> None:
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        # len(tokenizer) includes added/special tokens (Llama-3 puts bos at
        # 128000, beyond tokenizer.vocab_size=128000's base vocab)
        self.vocab_size = int(len(self._tok))
        self.bos_id = self._tok.bos_token_id
        self.eos_id = self._tok.eos_token_id
        pad = self._tok.pad_token_id
        self.pad_id = int(pad if pad is not None else (self.eos_id or 0))

    def encode(self, text: str, *, add_bos: bool = True) -> list[int]:
        ids = self._tok.encode(text, add_special_tokens=False)
        if add_bos and self.bos_id is not None:
            ids = [self.bos_id] + ids
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=True)


def load_tokenizer(path: Optional[str]) -> Tokenizer:
    """Resolve a tokenizer spec:

    - ``"builtin-bpe"`` — the shipped log-trained byte-level BPE
      (models/bpe.py, vocab 4096; no egress needed);
    - a directory path — local transformers tokenizer (production
      checkpoints on the PVC);
    - ``None``/``"byte"``/load failure — the byte fallback.
    """
    if path == "byte":
        return ByteTokenizer()
    if path == "builtin-bpe":
        from .bpe import load_builtin_bpe

        bpe = load_builtin_bpe()
        if bpe is not None:
            return bpe
        import logging

        logging.getLogger(__name__).warning(
            "builtin BPE vocab missing; using byte fallback"
        )
        return ByteTokenizer()
    if path:
        try:
            return HFTokenizer(path)
        except Exception:  # noqa: BLE001 - degrade to bytes
            import logging

            logging.getLogger(__name__).warning(
                "failed to load tokenizer from %s; using byte fallback", path, exc_info=True
            )
    return ByteTokenizer()
