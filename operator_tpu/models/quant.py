"""Int8 weight-only quantization for serving.

Decode at batch sizes this system runs (8-32 slots) is HBM-bandwidth bound:
every step streams the full weight set through the MXU once, so halving
weight bytes is close to halving step time — and it is what fits
Mistral-7B-per-chip DP on a 16 GB v5e (BASELINE config 5) with KV headroom.

Scheme: symmetric per-output-channel absmax.  For a stored ``[in, out]``
matrix ``W``::

    s   = absmax(W, axis=in) / 127          # [out]
    q   = round(W / s)  as int8             # [in, out]
    x @ W  ≈  (x @ q) * s                   # scale folds in AFTER the matmul

Per-output-channel scales commute with the contraction, so the dequant is
one fused multiply on the [B, T, out] activation — XLA fuses it into the
matmul epilogue; the int8->bf16 cast happens in-register.  The seven layer
matrices (wq/wk/wv/wo/w_gate/w_up/w_down — the overwhelming parameter mass)
are quantized; embeddings, lm_head and norms stay in the float dtype
(embedding quality is vocab-critical and the tied-embedding transpose would
need per-row scales on the head side).

TP sharding composes cleanly: scales are per-output-channel, so they shard
exactly like the matrix's output axis (parallel/mesh.py mirrors the
{q, s} tree).

The reference has no quantization (or any ML) — this is pure tpu-native
performance work against the north-star throughput target (BASELINE.md).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from .configs import ModelConfig

Params = dict[str, Any]

#: layer matrices that get quantized (stored [n_layers, in, out])
QUANTIZED_LAYER_MATRICES = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def is_quantized(params: Params) -> bool:
    """True if ANY layer matrix is an int8 {q, s} group — partially-merged
    trees (e.g. LoRA merged into a quantized base, which dequantizes only
    its targets) count as quantized."""
    return any(
        isinstance(leaf, dict) and "q" in leaf
        for leaf in params.get("layers", {}).values()
    )


def dequantize_params(params: Params, dtype: jnp.dtype = jnp.bfloat16) -> Params:
    """Expand every int8 {q, s} group back to a float matrix (e.g. before
    save_params, whose HF layout has no quantized convention)."""
    layers = {
        name: (
            (leaf["q"].astype(jnp.float32) * leaf["s"][..., None, :]).astype(dtype)
            if isinstance(leaf, dict) and "q" in leaf
            else leaf
        )
        for name, leaf in params["layers"].items()
    }
    return {**params, "layers": layers}


def quantize_matrix(w: jax.Array) -> dict[str, jax.Array]:
    """[..., in, out] float -> {q: int8 [..., in, out], s: [..., out]}."""
    w32 = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=-2)  # [..., out]
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w32 / scale[..., None, :]), -127, 127).astype(jnp.int8)
    return {"q": q, "s": scale}


def quantize_params(params: Params, config: ModelConfig) -> Params:
    """Quantize the layer matrices of a loaded/initialised param tree."""
    layers = dict(params["layers"])
    for name in QUANTIZED_LAYER_MATRICES:
        layers[name] = quantize_matrix(layers[name])
    return {**params, "layers": layers}


def mm(x: jax.Array, w: "jax.Array | dict[str, jax.Array]") -> jax.Array:
    """``x @ W`` for plain or quantized weights.

    The int8 matrix is cast to the activation dtype going INTO the matmul
    (the MXU has no int8xbf16 path; the cast is free relative to the HBM
    read we saved) and the per-channel scale folds into the epilogue.
    """
    if isinstance(w, dict):
        return (x @ w["q"].astype(x.dtype)) * w["s"].astype(x.dtype)
    return x @ w


def quantized_bytes(params: Params) -> int:
    return sum(int(p.size * p.dtype.itemsize) for p in jax.tree_util.tree_leaves(params))


def init_params_quantized(
    config: ModelConfig, key: jax.Array, dtype: jnp.dtype = jnp.bfloat16
) -> Params:
    """Random-init an ALREADY-quantized tree without ever materialising the
    full float tree.

    ``init_params`` + ``quantize_params`` peaks at float-tree + int8-tree
    simultaneously — for llama-3-8b that is ~16 GB of bf16 alone, i.e. an
    OOM before quantization can start on a 16 GB chip.  Here each stacked
    layer matrix is initialised and quantized in its own jitted call (the
    float tensor is a transient XLA frees immediately), so peak memory is
    the final int8 tree plus ONE bf16 matrix stack (~1 GB at 8B scale).

    Matches ``quantize_params(init_params(config, key, dtype), config)`` to
    within one quantization level / one bf16 ulp (same per-matrix PRNG keys
    and distribution; XLA rounds fused init slightly differently across jit
    boundaries, so bit-exactness is not promised) — tests/test_quant.py
    pins the tolerance.
    """
    from .llama import dense_init, init_params

    h = config.hidden_size
    # dense-init and quantize are SEPARATE jits on purpose: fused, XLA elides
    # the f32->bf16->f32 round trip and quantizes unrounded values — bit
    # drift vs the two-step reference path this function promises to match
    init_dense = jax.jit(
        lambda key, shape: dense_init(key, shape, h, dtype),
        static_argnames=("shape",),
    )
    quantize = jax.jit(quantize_matrix)

    def init_quantized_matrix(key: jax.Array, shape: tuple[int, ...]) -> Any:
        # block per matrix so the bf16 transient frees before the next one
        return jax.block_until_ready(quantize(init_dense(key, shape=shape)))

    return init_params(
        config, key, dtype, layer_matrix_init=init_quantized_matrix
    )


def parity_report(
    params_float: Params,
    params_quant: Params,
    config: ModelConfig,
    prompts: "list[list[int]]",
    *,
    max_new_tokens: int = 16,
) -> dict:
    """The int8-by-default parity gate (docs/SERVING.md "Bring-up").

    Greedy-decodes each token-id prompt under the float params and the
    quantized params on a fresh single-sequence KV cache each, and reports

    - ``greedy_match``: every prompt produced token-identical output,
    - ``max_logit_diff``: max abs difference between the two logit streams
      along the float path's greedy trajectory (teacher-forced with the
      float tokens, so the comparison never diverges and the number stays
      meaningful even when an argmax near-tie flips a token).

    Tiny models must pass ``greedy_match``; 1B-class configs gate on
    ``max_logit_diff`` instead (absolute threshold), because a near-tie
    argmax flip on a long generation is expected at that scale while the
    logit error stays bounded by the quantization step.
    """
    from .llama import forward

    def last_logits(params: Params, ids: list[int]) -> jax.Array:
        arr = jnp.asarray([ids], jnp.int32)
        pos = jnp.arange(len(ids), dtype=jnp.int32)[None]
        logits, _ = forward(params, config, arr, pos)
        return logits[0, -1]

    def greedy(params: Params, prompt: list[int]) -> tuple[list[int], list[jax.Array]]:
        # cache-free full-sequence forward per step: O(T^2) but the gate
        # runs tiny configs only, and it exercises the same numerics
        ids = list(prompt)
        toks: list[int] = []
        steps: list[jax.Array] = []
        for _ in range(max_new_tokens):
            logits = last_logits(params, ids)
            steps.append(logits)
            tok = int(jnp.argmax(logits))
            toks.append(tok)
            ids.append(tok)
        return toks, steps

    def forced(params: Params, prompt: list[int], driven: list[int]) -> list[jax.Array]:
        # teacher-forced along the FLOAT path's tokens: logit comparison
        # stays step-aligned even if the quantized argmax flips somewhere
        ids = list(prompt)
        steps: list[jax.Array] = []
        for tok in driven:
            steps.append(last_logits(params, ids))
            ids.append(tok)
        return steps

    matches = []
    max_diff = 0.0
    for prompt in prompts:
        float_toks, float_steps = greedy(params_float, prompt)
        quant_toks, _ = greedy(params_quant, prompt)
        matches.append(quant_toks == float_toks)
        quant_steps = forced(params_quant, prompt, float_toks)
        for a, b in zip(float_steps, quant_steps):
            diff = float(jnp.max(jnp.abs(
                a.astype(jnp.float32) - b.astype(jnp.float32)
            )))
            max_diff = max(max_diff, diff)
    return {
        "greedy_match": all(matches),
        "prompts": len(prompts),
        "mismatched_prompts": sum(1 for m in matches if not m),
        "max_logit_diff": max_diff,
    }
