"""Byte-level BPE: trainer + tokenizer, dependency-free and egress-free.

The serving/bench path needs a *real* subword tokenizer — byte-level token
counts inflate prompt lengths ~4x vs a production BPE vocab, which distorts
tok/s and context budgets (the reference's AI leg tokenizes server-side with
the provider's tokenizer; AIProviderConfig only carries maxTokens,
aiprovider-crd.yaml:47-50, so the operator never shipped one).  This module
trains a compact BPE on recorded failure logs + repo prose and ships the
result as a JSON vocab (``bpe_vocab/logbpe-4k.json``), so an air-gapped
environment still tokenizes like production.

Scheme (GPT-2 family, minus the regex zoo):

- ids ``0..2``: specials (pad/bos/eos); ids ``3..258``: raw bytes;
  id ``259+r``: the r-th merge.
- pre-tokenization splits on letter/digit/punct runs with the leading space
  attached (so ``" error"`` is one unit — the single most valuable property
  of GPT-style BPE on prose/logs).
- encoding greedily applies the lowest-rank merge within each pre-token;
  decoding concatenates byte strings (specials skipped).

The trainer keeps an inverted pair->words index so each merge touches only
the words containing it — a 4k vocab trains in seconds on a ~1 MB corpus.
"""

from __future__ import annotations

import json
import os
import re
from collections import Counter, defaultdict
from typing import Iterable, Optional, Sequence

PAD_ID, BOS_ID, EOS_ID = 0, 1, 2
NUM_SPECIALS = 3
FIRST_MERGE_ID = NUM_SPECIALS + 256

_PRETOKEN_RE = re.compile(
    rb" ?[A-Za-z]+| ?[0-9]+| ?[^ A-Za-z0-9]+| +"
)

BUILTIN_VOCAB = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "bpe_vocab", "logbpe-4k.json"
)


def _pretokenize(data: bytes) -> list[bytes]:
    return _PRETOKEN_RE.findall(data)


def _word_ids(word: bytes) -> tuple[int, ...]:
    return tuple(b + NUM_SPECIALS for b in word)


def train_bpe(
    texts: Iterable[str], vocab_size: int, *, min_pair_count: int = 2
) -> list[tuple[int, int]]:
    """Learn merges until ``vocab_size`` ids exist (or pairs run dry).

    Returns the merge list: rank r merges id pair ``merges[r]`` into id
    ``FIRST_MERGE_ID + r``.
    """
    assert vocab_size > FIRST_MERGE_ID, "vocab must exceed the byte alphabet"
    words = Counter()
    for text in texts:
        for w in _pretokenize(text.encode("utf-8")):
            words[_word_ids(w)] += 1
    seqs: list[list[int]] = [list(w) for w in words]
    counts: list[int] = [words[w] for w in words]

    pair_counts: Counter = Counter()
    pair_words: defaultdict[tuple[int, int], set[int]] = defaultdict(set)
    for idx, seq in enumerate(seqs):
        c = counts[idx]
        for pair in zip(seq, seq[1:]):
            pair_counts[pair] += c
            pair_words[pair].add(idx)

    merges: list[tuple[int, int]] = []
    max_merges = vocab_size - FIRST_MERGE_ID
    while len(merges) < max_merges and pair_counts:
        pair, best = max(pair_counts.items(), key=lambda kv: (kv[1], kv[0]))
        if best < min_pair_count:
            break
        new_id = FIRST_MERGE_ID + len(merges)
        merges.append(pair)
        touched = pair_words.pop(pair, set())
        del pair_counts[pair]
        for idx in touched:
            seq, c = seqs[idx], counts[idx]
            # retract this word's contribution, merge, re-add
            for p in zip(seq, seq[1:]):
                if p != pair:
                    pair_counts[p] -= c
                    if pair_counts[p] <= 0:
                        del pair_counts[p]
                    pair_words[p].discard(idx)
            merged: list[int] = []
            i = 0
            while i < len(seq):
                if i + 1 < len(seq) and (seq[i], seq[i + 1]) == pair:
                    merged.append(new_id)
                    i += 2
                else:
                    merged.append(seq[i])
                    i += 1
            seqs[idx] = merged
            for p in zip(merged, merged[1:]):
                if p == pair:  # the pair can never recur post-merge
                    continue
                pair_counts[p] += c
                pair_words[p].add(idx)
    return merges


class BPETokenizer:
    """Greedy-merge byte-level BPE over a trained merge table."""

    def __init__(self, merges: Sequence[tuple[int, int]]) -> None:
        self.merges = [tuple(m) for m in merges]
        self.ranks = {pair: r for r, pair in enumerate(self.merges)}
        self.pad_id = PAD_ID
        self.bos_id = BOS_ID
        self.eos_id = EOS_ID
        self.vocab_size = FIRST_MERGE_ID + len(self.merges)
        # id -> bytes for decoding
        self._bytes: list[bytes] = [b""] * self.vocab_size
        for b in range(256):
            self._bytes[b + NUM_SPECIALS] = bytes([b])
        for r, (a, b) in enumerate(self.merges):
            self._bytes[FIRST_MERGE_ID + r] = self._bytes[a] + self._bytes[b]

    # -- persistence ----------------------------------------------------
    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            json.dump(
                {"format": "logbpe-v1", "merges": [list(m) for m in self.merges]},
                f, separators=(",", ":"),
            )

    @classmethod
    def load(cls, path: str) -> "BPETokenizer":
        with open(path) as f:
            data = json.load(f)
        assert data.get("format") == "logbpe-v1", f"unknown vocab format in {path}"
        return cls([tuple(m) for m in data["merges"]])

    @classmethod
    def load_builtin(cls) -> "BPETokenizer":
        return cls.load(BUILTIN_VOCAB)

    # -- encode/decode --------------------------------------------------
    def _encode_word(self, word: bytes) -> list[int]:
        seq = [b + NUM_SPECIALS for b in word]
        while len(seq) > 1:
            best_rank, best_i = None, -1
            for i in range(len(seq) - 1):
                rank = self.ranks.get((seq[i], seq[i + 1]))
                if rank is not None and (best_rank is None or rank < best_rank):
                    best_rank, best_i = rank, i
            if best_rank is None:
                break
            seq[best_i : best_i + 2] = [FIRST_MERGE_ID + best_rank]
        return seq

    def encode(self, text: str, *, add_bos: bool = True) -> list[int]:
        ids: list[int] = [self.bos_id] if add_bos else []
        for word in _pretokenize(text.encode("utf-8")):
            ids.extend(self._encode_word(word))
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        data = b"".join(
            self._bytes[i] for i in ids if NUM_SPECIALS <= i < self.vocab_size
        )
        return data.decode("utf-8", errors="replace")


def load_builtin_bpe() -> Optional[BPETokenizer]:
    """The shipped log-trained vocab, or None when the file is absent."""
    try:
        return BPETokenizer.load_builtin()
    except (OSError, AssertionError, KeyError, ValueError):
        return None
