"""JAX model implementations (SURVEY.md §7 stages 3-4): the Llama-family
decoder (TinyLlama-1.1B / Llama-3-8B / Mistral-7B) and, in ``encoder``, the
MiniLM-class sentence-embedding encoder for semantic pattern matching.

Import of this package must not require an accelerator; jax is imported at
module level but devices are only touched when arrays are created."""

from .configs import (
    LLAMA_3_8B,
    MISTRAL_7B,
    TINY_TEST,
    TINYLLAMA_1_1B,
    ModelConfig,
    get_config,
    register_config,
    scaled,
)
from .llama import (
    KVCache,
    decode_step,
    forward,
    init_params,
    param_count,
    rms_norm,
)
from .loader import convert_hf_state_dict, load_params, save_params
from .tokenizer import ByteTokenizer, HFTokenizer, Tokenizer, load_tokenizer

__all__ = [name for name in dir() if not name.startswith("_")]
