"""Checkpoint loading: HF-format weights -> our stacked JAX param pytree.

Sources:
- a local directory of ``*.safetensors`` files (with or without the
  ``model.safetensors.index.json`` shard index) in Hugging Face Llama
  layout, or
- any in-memory mapping of HF parameter names to arrays (used by the parity
  tests, which convert a freshly-initialised ``transformers`` model).

The HF layout stores projections as ``[out_features, in_features]``; we
transpose once at load so runtime is always ``x @ W`` (llama.py docstring),
and stack the per-layer tensors along a leading axis for ``lax.scan``.

The rebuild's "checkpoint restore" is loading weights into TPU HBM
(SURVEY.md §5 checkpoint entry): tensors stream lazily out of the shard
files, each stacked layer group is placed on device (optionally straight to
its mesh sharding) the moment its last layer arrives, and the host copies
are freed — peak host memory is the not-yet-complete groups plus one stack
temporary, not 2x the model.
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
from typing import Any, Callable, Iterable, Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .configs import ModelConfig
from .llama import Params

log = logging.getLogger(__name__)

_LAYER_RE = re.compile(r"model\.layers\.(\d+)\.(.+)\.weight")
_BIAS_RE = re.compile(r"model\.layers\.(\d+)\.self_attn\.([qkv])_proj\.bias")

#: HF sub-name -> (our stacked name, transpose?)
_LAYER_MAP = {
    "self_attn.q_proj": ("wq", True),
    "self_attn.k_proj": ("wk", True),
    "self_attn.v_proj": ("wv", True),
    "self_attn.o_proj": ("wo", True),
    "mlp.gate_proj": ("w_gate", True),
    "mlp.up_proj": ("w_up", True),
    "mlp.down_proj": ("w_down", True),
    "input_layernorm": ("ln_attn", False),
    "post_attention_layernorm": ("ln_mlp", False),
}


def _to_numpy(value: Any) -> np.ndarray:
    """Accept numpy / jax arrays and torch tensors (incl. bfloat16)."""
    if isinstance(value, np.ndarray):
        return value
    if hasattr(value, "detach"):  # torch tensor, without importing torch here
        value = value.detach()
        if str(value.dtype) == "torch.bfloat16":
            return value.to(dtype=__import__("torch").float32).cpu().numpy()
        return value.cpu().numpy()
    return np.asarray(value)


def convert_hf_state_dict(
    state: "Mapping[str, Any] | Iterable[tuple[str, Any]]",
    config: ModelConfig,
    dtype: jnp.dtype = jnp.bfloat16,
    *,
    put: Optional[Callable[[str, np.ndarray], jax.Array]] = None,
) -> Params:
    """Map HF Llama names to the stacked pytree ``llama.init_params`` uses.

    ``state`` may be a dict (e.g. a torch ``state_dict()``) or a lazy
    ``(name, tensor)`` iterable (``iter_safetensors``).  ``put(name, array)``
    controls device placement (default: jnp.asarray with ``dtype``); native
    checkpoint dtypes are preserved until ``put`` converts them.
    """
    if put is None:
        def put(name: str, array: np.ndarray) -> jax.Array:  # noqa: ANN001
            return jnp.asarray(array, dtype)

    n = config.num_layers
    per_layer: dict[str, list[Optional[np.ndarray]]] = {
        ours: [None] * n for ours, _ in _LAYER_MAP.values()
    }
    if config.attention_bias:
        per_layer.update({f"b{axis}": [None] * n for axis in "qkv"})
    filled: dict[str, int] = {ours: 0 for ours in per_layer}
    layers: dict[str, jax.Array] = {}
    top: dict[str, jax.Array] = {}
    def record(ours: str, idx: int, array: np.ndarray) -> None:
        per_layer[ours][idx] = array
        filled[ours] += 1
        if filled[ours] == n:
            # group complete: stack (native dtype), place, free host refs
            layers[ours] = put(ours, np.stack(per_layer[ours]))
            per_layer[ours] = []

    items = state.items() if hasattr(state, "items") else state
    for name, raw in items:
        if name == "model.embed_tokens.weight":
            top["embed"] = put("embed", _to_numpy(raw))
        elif name == "model.norm.weight":
            top["ln_final"] = put("ln_final", _to_numpy(raw))
        elif name == "lm_head.weight":
            top["lm_head"] = put("lm_head", _to_numpy(raw).T)
        else:
            bias_match = _BIAS_RE.fullmatch(name)
            if bias_match:
                idx = int(bias_match.group(1))
                if not config.attention_bias:
                    log.debug("config has no attention_bias; ignoring %s", name)
                elif idx < n:
                    record(f"b{bias_match.group(2)}", idx, _to_numpy(raw))
                continue
            match = _LAYER_RE.fullmatch(name)
            if not match:
                log.debug("ignoring unknown checkpoint tensor %s", name)
                continue
            idx, sub = int(match.group(1)), match.group(2)
            mapped = _LAYER_MAP.get(sub)
            if mapped is None:
                log.debug("ignoring unknown layer tensor %s", name)
                continue
            ours, transpose = mapped
            if idx >= n:
                continue  # scaled-down config loads a prefix of the layers
            array = _to_numpy(raw)
            record(ours, idx, array.T if transpose else array)

    missing = [
        f"{ours}[{i}]"
        for ours, slots in per_layer.items()
        if ours not in layers
        for i, s in enumerate(slots)
        if s is None
    ]
    if missing:
        raise ValueError(f"checkpoint is missing {len(missing)} tensors, e.g. {missing[:4]}")
    params: Params = {"embed": top["embed"], "layers": layers, "ln_final": top["ln_final"]}
    if config.tie_embeddings:
        if "lm_head" in top:
            log.info("config ties embeddings; ignoring checkpoint lm_head")
    else:
        if "lm_head" not in top:
            raise ValueError("checkpoint has no lm_head.weight but config does not tie embeddings")
        params["lm_head"] = top["lm_head"]
    return params


# --------------------------------------------------------------------------
# safetensors directory loading
# --------------------------------------------------------------------------


def iter_safetensors(checkpoint_dir: str):
    """Yield ``(name, tensor)`` lazily across all shard files, so the loader
    holds at most the layer tensors not yet flushed to device (completed
    groups are stacked + placed + freed as soon as their last layer
    arrives — see convert_hf_state_dict)."""
    from safetensors import safe_open

    index_path = os.path.join(checkpoint_dir, "model.safetensors.index.json")
    files: list[str]
    if os.path.exists(index_path):
        with open(index_path) as f:
            index = json.load(f)
        files = sorted({os.path.join(checkpoint_dir, v) for v in index["weight_map"].values()})
    else:
        files = sorted(
            os.path.join(checkpoint_dir, f)
            for f in os.listdir(checkpoint_dir)
            if f.endswith(".safetensors")
        )
    if not files:
        raise FileNotFoundError(f"no .safetensors files under {checkpoint_dir}")

    for path in files:
        with safe_open(path, framework="np") as f:
            for name in f.keys():
                yield name, f.get_tensor(name)


# inverse of _LAYER_MAP: ours -> (hf name, transpose) — derived so the two
# directions can never drift
_HF_LAYER_NAMES = {ours: (hf, t) for hf, (ours, t) in _LAYER_MAP.items()}


def save_params(
    params: Params,
    checkpoint_dir: str,
    config: ModelConfig,
    *,
    shard_bytes: int = 4 << 30,
) -> list[str]:
    """Write our stacked pytree back out as a sharded HF-layout safetensors
    checkpoint (with ``model.safetensors.index.json``) that ``load_params``
    — or any HF Llama loader — reads back.

    Completes the checkpoint/resume story for the fine-tune flows
    (parallel/train.py, parallel/lora.py merge_lora output): train on the
    mesh, save, reload for serving.  Quantized trees must be dequantized or
    merged first (HF layout has no {q, s} convention).

    Returns the written shard file names.
    """
    from safetensors.numpy import save_file

    from .quant import is_quantized

    if is_quantized(params):
        raise ValueError(
            "save_params writes HF layout, which has no int8 {q, s} "
            "convention — expand with quant.dequantize_params first "
            "(merge_lora output still holds untargeted int8 groups)"
        )
    os.makedirs(checkpoint_dir, exist_ok=True)

    def tensors():
        """(name, array) lazily — one stacked group fetched at a time, so
        host peak is one group + the shard being packed (mirrors the
        loader's streaming discipline)."""
        yield "model.embed_tokens.weight", np.asarray(params["embed"])
        yield "model.norm.weight", np.asarray(params["ln_final"])
        if "lm_head" in params:
            yield "lm_head.weight", np.ascontiguousarray(
                np.asarray(params["lm_head"]).T
            )
        for ours, (hf, transpose) in _HF_LAYER_NAMES.items():
            stacked = np.asarray(params["layers"][ours])
            for i in range(config.num_layers):
                tensor = stacked[i].T if transpose else stacked[i]
                yield f"model.layers.{i}.{hf}.weight", np.ascontiguousarray(tensor)
            del stacked
        for axis in "qkv":
            if f"b{axis}" not in params["layers"]:
                continue
            stacked = np.asarray(params["layers"][f"b{axis}"])
            for i in range(config.num_layers):
                yield (
                    f"model.layers.{i}.self_attn.{axis}_proj.bias",
                    np.ascontiguousarray(stacked[i]),
                )
            del stacked

    # pack + write shard-by-shard; rename to the final -of-NNNNN names once
    # the count is known
    weight_map: dict[str, str] = {}
    tmp_files: list[str] = []
    shard: dict[str, np.ndarray] = {}
    size = total_size = 0

    def flush():
        nonlocal shard, size
        if not shard:
            return
        fname = f"model-{len(tmp_files) + 1:05d}.tmp"
        save_file(shard, os.path.join(checkpoint_dir, fname))
        tmp_files.append(fname)
        for name in shard:
            weight_map[name] = fname
        shard, size = {}, 0

    for name, array in tensors():
        if size and size + array.nbytes > shard_bytes:
            flush()
        shard[name] = array
        size += array.nbytes
        total_size += array.nbytes
    flush()

    total = len(tmp_files)
    files: list[str] = []
    renames = {}
    for i, tmp in enumerate(tmp_files, start=1):
        final = f"model-{i:05d}-of-{total:05d}.safetensors"
        os.replace(
            os.path.join(checkpoint_dir, tmp), os.path.join(checkpoint_dir, final)
        )
        renames[tmp] = final
        files.append(final)
    weight_map = {name: renames[tmp] for name, tmp in weight_map.items()}
    with open(os.path.join(checkpoint_dir, "model.safetensors.index.json"), "w") as f:
        json.dump(
            {"metadata": {"total_size": total_size}, "weight_map": weight_map}, f
        )
    return files


def load_params(
    checkpoint_dir: str,
    config: ModelConfig,
    dtype: jnp.dtype = jnp.bfloat16,
    *,
    shardings: Optional[Mapping[str, Any]] = None,
    quantize: bool = False,
) -> Params:
    """Load a HF Llama checkpoint directory onto device.

    ``shardings`` optionally maps our param names (embed/lm_head/ln_final or
    stacked layer names wq/wk/...) to ``jax.sharding.Sharding``s so each
    tensor goes straight to its mesh placement (the TP path for Llama-3-8B
    on v5e-4, BASELINE config ladder); for quantized matrices the entry may
    be a ``{"q": ..., "s": ...}`` mapping (parallel/mesh.py param_shardings
    with quantized=True), or a single sharding applied to ``q`` with ``s``
    replicated (a matrix-rank spec cannot place the rank-2 scales).

    ``quantize=True`` quantizes each layer-matrix GROUP the moment it is
    placed (models/quant.py int8 scheme), so device peak memory is the int8
    tree plus ONE bf16 group — loading then calling ``quantize_params``
    would peak at float tree + int8 tree, an OOM for 8B-class checkpoints
    on a 16 GB chip.
    """
    from .quant import QUANTIZED_LAYER_MATRICES, quantize_matrix

    state = iter_safetensors(checkpoint_dir)
    quantize_jit = jax.jit(quantize_matrix) if quantize else None

    def place(value: jax.Array, sharding: Any) -> jax.Array:
        return jax.device_put(value, sharding) if sharding is not None else value

    def put(name: str, array: np.ndarray) -> Any:
        value = jnp.asarray(array, dtype)
        sharding = shardings.get(name) if shardings else None
        if quantize and name in QUANTIZED_LAYER_MATRICES:
            out = quantize_jit(value)
            # block so XLA frees the bf16 group before the next one arrives
            out = jax.block_until_ready(out)
            del value
            if isinstance(sharding, Mapping):
                return {k: place(v, sharding.get(k)) for k, v in out.items()}
            # single sharding: it has the matrix's rank, so it can only
            # place q; scales stay replicated (they're [n_layers, out])
            return {"q": place(out["q"], sharding), "s": out["s"]}
        return place(value, sharding)

    return convert_hf_state_dict(state, config, dtype, put=put)


class _AsyncLoad:
    """Handle for an in-flight streamed weight load (``load_params_async``).

    The load streams safetensors groups onto device from a daemon thread:
    HBM transfers overlap host-side work — in the serving provider that is
    the AOT-cache preload + any live compiles, which need only SHAPES, not
    weight values (serving/provider.py bring-up overlap).  ``result()``
    joins and re-raises any load failure on the caller."""

    def __init__(self, target, args, kwargs) -> None:
        self._result: Any = None
        self._error: Optional[BaseException] = None
        self._started = time.perf_counter()
        self.seconds: Optional[float] = None

        def _run() -> None:
            try:
                self._result = target(*args, **kwargs)
            except BaseException as exc:  # noqa: BLE001 - re-raised in result()
                self._error = exc
            finally:
                self.seconds = time.perf_counter() - self._started

        self._thread = threading.Thread(
            target=_run, name="weight-stream", daemon=True
        )
        self._thread.start()

    def done(self) -> bool:
        return not self._thread.is_alive()

    def result(self, timeout: Optional[float] = None) -> Params:
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError("weight stream still loading")
        if self._error is not None:
            raise self._error
        return self._result


def load_params_async(
    checkpoint_dir: str,
    config: ModelConfig,
    dtype: jnp.dtype = jnp.bfloat16,
    *,
    shardings: Optional[Mapping[str, Any]] = None,
    quantize: bool = False,
) -> _AsyncLoad:
    """Start ``load_params`` on a background thread and return a handle.

    Safe to overlap with tracing/lowering/AOT-cache deserialization: jax
    device_put and the quantize jit are thread-safe, and the consumer only
    touches params after ``result()``.  The GIL releases during the actual
    HBM transfers and safetensors reads, so the overlap is real, not
    cooperative."""
    return _AsyncLoad(
        load_params, (checkpoint_dir, config, dtype),
        {"shardings": shardings, "quantize": quantize},
    )
