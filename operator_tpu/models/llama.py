"""Llama-family decoder in pure functional JAX.

TPU-first design decisions (not a port of any torch implementation):

- **scan over layers**: per-layer parameters are stacked along a leading
  ``num_layers`` axis and the layer loop is ``jax.lax.scan`` — compile time
  is O(1) in depth and XLA sees one fused layer body;
- **bfloat16 compute, float32 accumulation** where it matters (RMSNorm mean,
  softmax, logits) — the MXU natively multiplies bf16 with f32 accumulate;
- **grouped-query attention without materialising repeated KV**: the query
  tensor is shaped [B, T, kv_heads, q_per_kv, head_dim] and contracted
  against [B, S, kv_heads, head_dim] in one einsum, so GQA costs no extra
  HBM bandwidth;
- **explicit KV cache** as a pytree of [layers, batch, max_seq, kv_heads,
  head_dim] arrays updated with ``dynamic_update_slice`` inside the same
  scan — prefill and decode are the same jitted function at different
  sequence lengths (the serving engine in ``operator_tpu.serving`` drives
  it; the paged variant lives in ``operator_tpu.ops.paged_attention``).

Weight layout convention: all projections are stored as ``[in_features,
out_features]`` so the forward pass is always ``x @ W`` (no transposes at
run time; the HF checkpoint loader transposes once at load).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..ops.flash_prefill import (
    flash_prefill_attention,
    flash_prefill_enabled,
    flash_prefill_supported,
)
from .configs import ModelConfig
from .quant import mm

Params = dict[str, Any]

#: projections that carry a bias vector when config.attention_bias (Qwen2)
_PROJ_BIAS = {"wq": "bq", "wk": "bk", "wv": "bv"}


# --------------------------------------------------------------------------
# parameter init
# --------------------------------------------------------------------------


def layer_matrix_shapes(config: ModelConfig) -> dict[str, tuple[int, int, int]]:
    """Stacked shapes of the seven per-layer matrices, in the canonical
    order the init key-split follows (shared with quant.init_params_quantized
    so the two init paths can never drift structurally)."""
    h, f = config.hidden_size, config.intermediate_size
    kvh, qh, d = config.num_kv_heads, config.num_heads, config.head_dim
    n = config.num_layers
    return {
        "wq": (n, h, qh * d),
        "wk": (n, h, kvh * d),
        "wv": (n, h, kvh * d),
        "wo": (n, qh * d, h),
        "w_gate": (n, h, f),
        "w_up": (n, h, f),
        "w_down": (n, f, h),
    }


def dense_init(
    key: jax.Array, shape: tuple[int, ...], fallback_fan_in: int, dtype: jnp.dtype
) -> jax.Array:
    """Normal init scaled by fan-in (the second-to-last axis)."""
    scale = (shape[-2] if len(shape) >= 2 else fallback_fan_in) ** -0.5
    return (jax.random.normal(key, shape, jnp.float32) * scale).astype(dtype)


def init_params(
    config: ModelConfig,
    key: jax.Array,
    dtype: jnp.dtype = jnp.bfloat16,
    *,
    layer_matrix_init: Optional[Any] = None,
) -> Params:
    """Random init with per-layer params stacked on axis 0 for lax.scan.

    ``layer_matrix_init(key, shape) -> leaf`` overrides how the seven layer
    matrices are built (default: ``dense_init``).  quant.py passes a
    per-matrix jitted init+quantize so the int8 tree never coexists with a
    full float tree — ONE assembly of the non-matrix leaves serves both.
    """
    k_embed, k_layers, k_head = jax.random.split(key, 3)
    h = config.hidden_size
    n = config.num_layers
    if layer_matrix_init is None:
        def layer_matrix_init(k, shape):
            return dense_init(k, shape, h, dtype)

    shapes = layer_matrix_shapes(config)
    keys = jax.random.split(k_layers, len(shapes))
    layers: dict[str, Any] = {
        name: layer_matrix_init(k, shape)
        for k, (name, shape) in zip(keys, shapes.items())
    }
    layers["ln_attn"] = jnp.ones((n, h), dtype)
    layers["ln_mlp"] = jnp.ones((n, h), dtype)
    if config.attention_bias:
        # Qwen2-style q/k/v projection biases (HF Qwen2Config attention_bias);
        # zero-init so random-weight parity tests see the unbiased model
        d, kvh, qh = config.head_dim, config.num_kv_heads, config.num_heads
        layers["bq"] = jnp.zeros((n, qh * d), dtype)
        layers["bk"] = jnp.zeros((n, kvh * d), dtype)
        layers["bv"] = jnp.zeros((n, kvh * d), dtype)
    params: Params = {
        "embed": dense_init(k_embed, (config.vocab_size, h), h, dtype),
        "layers": layers,
        "ln_final": jnp.ones((h,), dtype),
    }
    if not config.tie_embeddings:
        params["lm_head"] = dense_init(k_head, (h, config.vocab_size), h, dtype)
    return params


def param_count(params: Params) -> int:
    return sum(int(p.size) for p in jax.tree_util.tree_leaves(params))


# --------------------------------------------------------------------------
# building blocks
# --------------------------------------------------------------------------


def _lora_path(
    h_in: jax.Array,  # [B, T, in]
    factors: dict[str, jax.Array],
    alpha: float,
    lora_indices: Optional[jax.Array],  # [B] adapter ids, or None
) -> jax.Array:
    """The low-rank delta ``(x @ A @ B) * alpha/r``, never expanded to a
    full matrix.  With ``lora_indices``, the factors carry a per-layer
    ADAPTER axis (``[n_adapters, in, r]`` — parallel/lora.py
    ``stack_adapters``) and each batch row applies its own adapter: the
    multi-LoRA serving path, one compiled program for the whole set."""
    a = factors["a"].astype(h_in.dtype)
    b = factors["b"].astype(h_in.dtype)
    scale = alpha / a.shape[-1]
    if lora_indices is None:
        return ((h_in @ a) @ b) * scale
    a_sel = a[lora_indices]  # [B, in, r] — rank-r gather, kilobytes per row
    b_sel = b[lora_indices]  # [B, r, out]
    low = jnp.einsum("bti,bir->btr", h_in, a_sel)
    return jnp.einsum("btr,bro->bto", low, b_sel) * scale


def rms_norm(x: jax.Array, scale: jax.Array, eps: float) -> jax.Array:
    """Float32 accumulation regardless of activation dtype."""
    x32 = x.astype(jnp.float32)
    variance = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    normed = x32 * jax.lax.rsqrt(variance + eps)
    return (normed * scale.astype(jnp.float32)).astype(x.dtype)


def rope_frequencies(config: ModelConfig) -> jax.Array:
    """Inverse frequencies [head_dim // 2] (HF half-rotation convention),
    with Llama-3.1-style NTK-by-parts scaling when configured: wavelengths
    beyond the original training context are slowed by ``factor``, short
    wavelengths kept, the band between linearly interpolated (matches HF
    ``rope_type: llama3``)."""
    d = config.head_dim
    exponents = jnp.arange(0, d, 2, dtype=jnp.float32) / d
    inv_freq = 1.0 / (config.rope_theta**exponents)
    scaling = config.rope_scaling
    if scaling is None:
        return inv_freq
    wavelen = 2.0 * jnp.pi / inv_freq
    low_freq_wavelen = scaling.original_max_positions / scaling.low_freq_factor
    high_freq_wavelen = scaling.original_max_positions / scaling.high_freq_factor
    scaled = inv_freq / scaling.factor
    smooth = (scaling.original_max_positions / wavelen - scaling.low_freq_factor) / (
        scaling.high_freq_factor - scaling.low_freq_factor
    )
    smoothed = (1.0 - smooth) * scaled + smooth * inv_freq
    out = jnp.where(wavelen > low_freq_wavelen, scaled, inv_freq)
    mid = (wavelen <= low_freq_wavelen) & (wavelen >= high_freq_wavelen)
    return jnp.where(mid, smoothed, out)


def apply_rope(x: jax.Array, positions: jax.Array, inv_freq: jax.Array) -> jax.Array:
    """x: [B, T, ..., head_dim]; positions: [B, T] — HF ``rotate_half``."""
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [B, T, d/2]
    cos = jnp.cos(angles)
    sin = jnp.sin(angles)
    # broadcast over any head axes between T and head_dim
    extra_axes = x.ndim - 3
    for _ in range(extra_axes):
        cos = cos[:, :, None]
        sin = sin[:, :, None]
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(jnp.float32)
    rotated = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return rotated.astype(x.dtype)


def make_causal_mask(
    q_positions: jax.Array,
    kv_positions: jax.Array,
    kv_valid: jax.Array,
    *,
    sliding_window: Optional[int] = None,
) -> jax.Array:
    """[B, Tq, S] boolean mask: causal + validity + optional sliding window.

    ``q_positions``: [B, Tq] absolute positions of the query tokens;
    ``kv_positions``: [B, S] absolute positions of cache slots;
    ``kv_valid``: [B, S] whether the slot holds a real token.
    """
    causal = kv_positions[:, None, :] <= q_positions[:, :, None]
    mask = causal & kv_valid[:, None, :]
    if sliding_window is not None:
        recent = kv_positions[:, None, :] > (q_positions[:, :, None] - sliding_window)
        mask = mask & recent
    return mask


# --------------------------------------------------------------------------
# KV cache
# --------------------------------------------------------------------------


@dataclass
class KVCache:
    """Contiguous per-layer cache (the paged variant lives in ops/)."""

    k: jax.Array  # [layers, B, max_seq, kv_heads, head_dim]
    v: jax.Array  # [layers, B, max_seq, kv_heads, head_dim]

    @classmethod
    def create(
        cls,
        config: ModelConfig,
        batch_size: int,
        max_seq_len: Optional[int] = None,
        dtype: jnp.dtype = jnp.bfloat16,
    ) -> "KVCache":
        shape = (
            config.num_layers,
            batch_size,
            max_seq_len or config.max_seq_len,
            config.num_kv_heads,
            config.head_dim,
        )
        return cls(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


jax.tree_util.register_pytree_node(
    KVCache,
    lambda cache: ((cache.k, cache.v), None),
    lambda _, children: KVCache(k=children[0], v=children[1]),
)


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------


def _attention(
    q: jax.Array,  # [B, T, QH, D]
    k: jax.Array,  # [B, S, KH, D]
    v: jax.Array,  # [B, S, KH, D]
    mask: jax.Array,  # [B, T, S] bool
    config: ModelConfig,
) -> jax.Array:
    b, t, qh, d = q.shape
    kh = config.num_kv_heads
    g = config.q_per_kv
    q_grouped = q.reshape(b, t, kh, g, d)
    # [B, KH, G, T, S] with f32 accumulation on the MXU
    scores = jnp.einsum(
        "btkgd,bskd->bkgts", q_grouped, k, preferred_element_type=jnp.float32
    )
    scores = scores * (d**-0.5)
    scores = jnp.where(mask[:, None, None, :, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgts,bskd->btkgd", probs, v)
    return out.reshape(b, t, qh * d)


#: f32 score-tensor budget for one prefill attention: above this the query
#: axis is chunked (lax.scan) so the [B, KH, G, T, S] tensor never
#: materialises.  256 MB keeps an 8B prefill bucket (n=8, t=4096) well
#: inside a 16 GB v5e while staying coarse enough that XLA sees big matmuls.
_SCORE_BUDGET_BYTES = int(
    float(os.environ.get("OPERATOR_TPU_SCORE_BUDGET_MB", "256")) * 2**20
)

#: unroll factor for the layer lax.scan (1 = rolled).  Unrolling lets XLA
#: schedule/alias per-layer cache updates without the scan's stacked-ys
#: round trip — a decode-bandwidth experiment knob (scripts/tpu_experiments.sh);
#: compile time grows with the factor.
_LAYER_UNROLL = int(os.environ.get("OPERATOR_TPU_LAYER_UNROLL", "1"))


def _pick_q_chunk(b: int, t: int, s: int, qh: int, shards: int = 1) -> Optional[int]:
    """Largest divisor-of-t query chunk whose f32 scores fit the budget;
    None means no chunking (the dense tensor already fits).  ``shards``
    divides the effective batch: under a dp-sharded prefill each device
    holds b/shards of the score tensor, so the global shape overstates
    per-device memory by that factor."""
    rows = max(1, b // max(1, shards))
    row_bytes = rows * qh * s * 4  # score bytes per query position
    if row_bytes * t <= _SCORE_BUDGET_BYTES:
        return None
    target = max(1, _SCORE_BUDGET_BYTES // row_bytes)
    for chunk in range(min(t - 1, target), 0, -1):
        if t % chunk == 0:
            return chunk
    return 1


def _attention_chunked(
    q: jax.Array,  # [B, T, QH, D]
    k: jax.Array,  # [B, S, KH, D]
    v: jax.Array,
    q_positions: jax.Array,  # [B, T]
    kv_positions: jax.Array,  # [B, S]
    kv_valid: jax.Array,  # [B, S] bool
    config: ModelConfig,
    q_chunk: int,
) -> jax.Array:
    """Long-context prefill attention: scan over query chunks, building each
    chunk's causal/window mask on the fly — peak memory is ONE chunk's f32
    scores instead of the whole [T, S] plane (SURVEY.md §7 hard part b; the
    reference ships entire pod logs as one string, application.properties:10,
    so the rebuild's prefill must not be quadratic in HBM)."""
    b, t, qh, d = q.shape
    assert t % q_chunk == 0, (t, q_chunk)
    n_chunks = t // q_chunk
    qs = jnp.moveaxis(q.reshape(b, n_chunks, q_chunk, qh, d), 1, 0)
    qps = jnp.moveaxis(q_positions.reshape(b, n_chunks, q_chunk), 1, 0)

    def body(_, xs):
        q_c, qp_c = xs
        mask = make_causal_mask(
            qp_c, kv_positions, kv_valid, sliding_window=config.sliding_window
        )
        return None, _attention(q_c, k, v, mask, config)

    _, outs = jax.lax.scan(body, None, (qs, qps))  # [n_chunks, B, q_chunk, QH*D]
    return jnp.moveaxis(outs, 0, 1).reshape(b, t, qh * d)


def forward(
    params: Params,
    config: ModelConfig,
    token_ids: jax.Array,  # [B, T] int32
    positions: jax.Array,  # [B, T] int32 absolute positions
    cache: Optional[KVCache] = None,
    cache_offset: int | jax.Array = 0,
    attn_mask: Optional[jax.Array] = None,  # [B, T, S]; forces the dense path
    kv_valid: Optional[jax.Array] = None,  # [B, S] validity override
    q_chunk: Optional[int] = None,  # explicit prefill chunk (tests)
    score_shards: int = 1,  # devices the batch axis is sharded over
    prefill_lengths: Optional[jax.Array] = None,  # [B]; enables flash prefill
    lora: Optional[dict[str, dict[str, jax.Array]]] = None,  # parallel/lora.py
    lora_alpha: float = 16.0,
    lora_indices: Optional[jax.Array] = None,  # [B]; lora holds STACKED adapters
) -> tuple[jax.Array, Optional[KVCache]]:
    """One decoder pass.

    Without a cache: plain causal self-attention over the T tokens (training
    / parity testing).  With a cache: the T tokens are written at
    ``cache_offset`` and attend over the whole cache (prefill writes many,
    decode writes one — same code path).  ``cache_offset`` may be a scalar
    or a per-sequence ``[B]`` vector — the continuous-batching engine
    decodes slots at ragged positions (serving/engine.py).

    Long prefills chunk the query axis automatically (`_pick_q_chunk`) so
    the f32 score tensor never exceeds a fixed budget — an 8B-config
    t=4096 prefill fits a 16 GB chip.  ``kv_valid`` masks cache slots that
    hold no real token (right-padded batched prefill); passing a full
    ``attn_mask`` instead forces the dense path (legacy/test hook).

    Returns (logits [B, T, vocab] float32, updated cache or None).
    """
    inv_freq = rope_frequencies(config)
    x = jnp.take(params["embed"], token_ids, axis=0)  # [B, T, H]
    b, t, h = x.shape

    use_cache = cache is not None
    offsets = jnp.broadcast_to(jnp.asarray(cache_offset, jnp.int32), (b,))
    if use_cache:
        max_seq = cache.k.shape[2]
        kv_positions = jnp.broadcast_to(
            jnp.arange(max_seq, dtype=jnp.int32)[None], (b, max_seq)
        )
        if kv_valid is None:
            kv_valid = kv_positions < offsets[:, None] + t
    else:
        max_seq = t
        kv_positions = positions
        if kv_valid is None:
            kv_valid = jnp.ones((b, t), bool)

    # flash prefill (Pallas, gated): self-attention buckets where the kv
    # range is exactly the q range and per-row validity is `pos < length`
    # (kv_valid must be the caller's pos<lengths mask — required non-None so
    # the no-cache all-ones default can never silently diverge from the
    # kernel's length masking).  score_shards>1 means the bucket is sharded
    # over a mesh: pallas_call has no SPMD rule here, so flash stays off.
    use_flash = (
        prefill_lengths is not None
        and kv_valid is not None
        and attn_mask is None
        and score_shards == 1
        and flash_prefill_enabled()
        and flash_prefill_supported(t, max_seq, cache_offset)
    )
    if use_flash:
        q_chunk = None
    elif attn_mask is None:
        q_chunk = q_chunk or _pick_q_chunk(
            b, t, max_seq, config.num_heads, shards=score_shards
        )
        if q_chunk is None:
            attn_mask = make_causal_mask(
                positions, kv_positions, kv_valid,
                sliding_window=config.sliding_window,
            )
    else:
        q_chunk = None  # explicit mask: dense semantics the mask encodes

    layers = params["layers"]

    def layer_step(carry: jax.Array, scanned: dict[str, jax.Array]):
        x = carry
        weights, layer_cache = scanned["w"], scanned.get("cache")
        layer_lora = scanned.get("lora")

        def proj(h_in: jax.Array, name: str) -> jax.Array:
            """x @ W plus the low-rank LoRA path x @ A @ B — the factors
            are never expanded to a full delta matrix, so training memory
            stays rank-r (parallel/lora.py)."""
            y = mm(h_in, weights[name])
            bias = _PROJ_BIAS.get(name)
            if bias is not None and bias in weights:
                y = y + weights[bias].astype(y.dtype)
            if layer_lora is not None and name in layer_lora:
                y = y + _lora_path(
                    h_in, layer_lora[name], lora_alpha, lora_indices
                )
            return y

        # -- attention ---------------------------------------------------
        attn_in = rms_norm(x, weights["ln_attn"], config.rms_norm_eps)
        q = proj(attn_in, "wq").reshape(b, t, config.num_heads, config.head_dim)
        k = proj(attn_in, "wk").reshape(b, t, config.num_kv_heads, config.head_dim)
        v = proj(attn_in, "wv").reshape(b, t, config.num_kv_heads, config.head_dim)
        q = apply_rope(q, positions, inv_freq)
        k = apply_rope(k, positions, inv_freq)
        if layer_cache is not None:
            # per-sequence write offsets (ragged continuous batching)
            write = jax.vmap(
                lambda buf, new, off: jax.lax.dynamic_update_slice(
                    buf, new.astype(buf.dtype), (off, 0, 0)
                )
            )
            k_all = write(layer_cache["k"], k, offsets)
            v_all = write(layer_cache["v"], v, offsets)
            new_cache = {"k": k_all, "v": v_all}
        else:
            k_all, v_all = k, v
            new_cache = None
        k_att = k_all.astype(q.dtype)
        v_att = v_all.astype(q.dtype)
        if use_flash:
            attn = flash_prefill_attention(
                q, k_att, v_att, prefill_lengths,
                sliding_window=config.sliding_window,
            )
        elif q_chunk is not None:
            attn = _attention_chunked(
                q, k_att, v_att, positions, kv_positions, kv_valid, config, q_chunk
            )
        else:
            attn = _attention(q, k_att, v_att, attn_mask, config)
        x = x + proj(attn, "wo")
        # -- mlp ----------------------------------------------------------
        mlp_in = rms_norm(x, weights["ln_mlp"], config.rms_norm_eps)
        gate = jax.nn.silu(proj(mlp_in, "w_gate"))
        up = proj(mlp_in, "w_up")
        x = x + proj(gate * up, "w_down")
        return x, new_cache

    if use_cache:
        scanned_in = {"w": layers, "cache": {"k": cache.k, "v": cache.v}}
        if lora is not None:
            scanned_in["lora"] = lora
        x, cache_out = jax.lax.scan(
            lambda carry, s: layer_step(carry, s), x, scanned_in,
            unroll=_LAYER_UNROLL,
        )
        new_cache = KVCache(k=cache_out["k"], v=cache_out["v"])
    else:
        scanned_in = {"w": layers}
        if lora is not None:
            scanned_in["lora"] = lora
        x, _ = jax.lax.scan(
            lambda carry, s: (layer_step(carry, s)[0], None), x, scanned_in,
            unroll=_LAYER_UNROLL,
        )
        new_cache = None

    x = rms_norm(x, params["ln_final"], config.rms_norm_eps)
    head = params["embed"].T if config.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("bth,hv->btv", x, head, preferred_element_type=jnp.float32)
    return logits, new_cache


def decode_step(
    params: Params,
    config: ModelConfig,
    token_ids: jax.Array,  # [B, 1]
    positions: jax.Array,  # [B, 1]
    cache: KVCache,
    cache_offset: jax.Array,
    lora: Optional[dict[str, dict[str, jax.Array]]] = None,
    lora_alpha: float = 16.0,
    lora_indices: Optional[jax.Array] = None,
) -> tuple[jax.Array, KVCache]:
    """Single-token decode (jit once, call per step)."""
    logits, new_cache = forward(
        params, config, token_ids, positions, cache=cache, cache_offset=cache_offset,
        lora=lora, lora_alpha=lora_alpha, lora_indices=lora_indices,
    )
    return logits[:, -1, :], new_cache


def decode_step_paged(
    params: Params,
    config: ModelConfig,
    token_ids: jax.Array,  # [B, 1]
    paged: "PagedKVCache",
    lora: Optional[dict[str, dict[str, jax.Array]]] = None,  # stacked adapters
    lora_alpha: float = 16.0,
    lora_indices: Optional[jax.Array] = None,  # [B] adapter id per slot
) -> tuple[jax.Array, "PagedKVCache"]:
    """Single-token decode over a paged KV cache (ops/paged_attention.py).

    Each sequence appends at its own ``lengths[b]`` position (the page
    table maps it to a page/slot) and attends over exactly its own pages —
    the ragged-batch decode of SURVEY.md §7 hard part (c).  Sliding-window
    configs (Mistral) mask to the last ``sliding_window`` tokens, matching
    the contiguous path's make_causal_mask semantics.

    Returns (last-token logits [B, vocab] float32, cache with lengths+1).
    """
    from ..ops.paged_attention import PagedKVCache, paged_attention, write_tokens

    inv_freq = rope_frequencies(config)
    b = token_ids.shape[0]
    positions = paged.lengths[:, None]  # [B, 1] append position
    x = jnp.take(params["embed"], token_ids, axis=0)  # [B, 1, H]
    new_lengths = paged.lengths + 1

    def layer_step(carry: jax.Array, scanned: dict[str, jax.Array]):
        x = carry
        weights = scanned["w"]
        layer_lora = scanned.get("lora")
        attn_in = rms_norm(x, weights["ln_attn"], config.rms_norm_eps)

        def proj(h_in: jax.Array, name: str) -> jax.Array:
            y = mm(h_in, weights[name])
            bias = _PROJ_BIAS.get(name)
            if bias is not None and bias in weights:
                y = y + weights[bias].astype(y.dtype)
            if layer_lora is not None and name in layer_lora:
                y = y + _lora_path(
                    h_in, layer_lora[name], lora_alpha, lora_indices
                )
            return y

        q = proj(attn_in, "wq").reshape(b, 1, config.num_heads, config.head_dim)
        k = proj(attn_in, "wk").reshape(b, 1, config.num_kv_heads, config.head_dim)
        v = proj(attn_in, "wv").reshape(b, 1, config.num_kv_heads, config.head_dim)
        q = apply_rope(q, positions, inv_freq)
        k = apply_rope(k, positions, inv_freq)
        k_pages = write_tokens(scanned["k"], paged.page_table, k, paged.lengths)
        v_pages = write_tokens(scanned["v"], paged.page_table, v, paged.lengths)
        attn = paged_attention(
            q[:, 0].astype(k_pages.dtype), k_pages, v_pages,
            paged.page_table, new_lengths,
            sliding_window=config.sliding_window,
        )  # [B, QH, D]
        x = x + proj(attn.astype(x.dtype).reshape(b, 1, -1), "wo")
        mlp_in = rms_norm(x, weights["ln_mlp"], config.rms_norm_eps)
        gate = jax.nn.silu(proj(mlp_in, "w_gate"))
        up = proj(mlp_in, "w_up")
        x = x + proj(gate * up, "w_down")
        return x, {"k": k_pages, "v": v_pages}

    scanned_in = {"w": params["layers"], "k": paged.k_pages, "v": paged.v_pages}
    if lora is not None:
        scanned_in["lora"] = lora
    x, pages_out = jax.lax.scan(layer_step, x, scanned_in, unroll=_LAYER_UNROLL)

    x = rms_norm(x, params["ln_final"], config.rms_norm_eps)
    head = params["embed"].T if config.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("bth,hv->btv", x, head, preferred_element_type=jnp.float32)
    new_cache = PagedKVCache(
        k_pages=pages_out["k"], v_pages=pages_out["v"],
        page_table=paged.page_table, lengths=new_lengths,
    )
    return logits[:, -1, :], new_cache
