"""Model configurations for the Llama family.

One decoder architecture covers every model the system serves (BASELINE
configs 2/4/5): RMSNorm + RoPE + grouped-query attention + SiLU-gated MLP.
Mistral adds a sliding attention window; Llama-3 a larger vocab and RoPE
theta.  Sizes are from the public model cards / HF config.json files.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional


@dataclass(frozen=True)
class RopeScaling:
    """Llama-3.1-style ("llama3") NTK-by-parts RoPE scaling: low-frequency
    bands are slowed by ``factor``, high-frequency bands kept, and the bands
    between interpolated — how 3.1/3.2 stretch an 8k-trained RoPE to 128k."""

    factor: float = 8.0
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_positions: int = 8192


@dataclass(frozen=True)
class ModelConfig:
    name: str
    vocab_size: int
    hidden_size: int
    intermediate_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    rope_theta: float = 10_000.0
    rms_norm_eps: float = 1e-5
    max_seq_len: int = 2048
    sliding_window: Optional[int] = None  # Mistral-style local attention
    tie_embeddings: bool = False
    rope_scaling: Optional[RopeScaling] = None  # Llama-3.1+ long context
    attention_bias: bool = False  # Qwen2-style bias on the q/k/v projections

    @property
    def q_per_kv(self) -> int:
        return self.num_heads // self.num_kv_heads

    def __post_init__(self) -> None:
        assert self.num_heads % self.num_kv_heads == 0, "heads must divide evenly into kv groups"


TINYLLAMA_1_1B = ModelConfig(
    name="tinyllama-1.1b",
    vocab_size=32000,
    hidden_size=2048,
    intermediate_size=5632,
    num_layers=22,
    num_heads=32,
    num_kv_heads=4,
    head_dim=64,
    rope_theta=10_000.0,
    max_seq_len=2048,
)

LLAMA_3_8B = ModelConfig(
    name="llama-3-8b",
    vocab_size=128256,
    hidden_size=4096,
    intermediate_size=14336,
    num_layers=32,
    num_heads=32,
    num_kv_heads=8,
    head_dim=128,
    rope_theta=500_000.0,
    max_seq_len=8192,
)

LLAMA_3_1_8B = ModelConfig(
    name="llama-3.1-8b",
    vocab_size=128256,
    hidden_size=4096,
    intermediate_size=14336,
    num_layers=32,
    num_heads=32,
    num_kv_heads=8,
    head_dim=128,
    rope_theta=500_000.0,
    max_seq_len=16384,  # serving cap; the model supports 128k
    rope_scaling=RopeScaling(factor=8.0),
)

# small modern targets: a 1B that outclasses TinyLlama at the same latency
# budget, and a 3B midpoint — both tie embeddings and use llama3 scaling
LLAMA_3_2_1B = ModelConfig(
    name="llama-3.2-1b",
    vocab_size=128256,
    hidden_size=2048,
    intermediate_size=8192,
    num_layers=16,
    num_heads=32,
    num_kv_heads=8,
    head_dim=64,
    rope_theta=500_000.0,
    max_seq_len=16384,
    tie_embeddings=True,
    rope_scaling=RopeScaling(factor=32.0),
)

LLAMA_3_2_3B = ModelConfig(
    name="llama-3.2-3b",
    vocab_size=128256,
    hidden_size=3072,
    intermediate_size=8192,
    num_layers=28,
    num_heads=24,
    num_kv_heads=8,
    head_dim=128,
    rope_theta=500_000.0,
    max_seq_len=16384,
    tie_embeddings=True,
    rope_scaling=RopeScaling(factor=32.0),
)

MISTRAL_7B = ModelConfig(
    name="mistral-7b",
    vocab_size=32000,
    hidden_size=4096,
    intermediate_size=14336,
    num_layers=32,
    num_heads=32,
    num_kv_heads=8,
    head_dim=128,
    rope_theta=10_000.0,
    sliding_window=4096,
    max_seq_len=8192,
)

# Qwen2 family: same decoder skeleton plus bias vectors on the q/k/v
# projections (HF Qwen2Config attention_bias); 2.5 generation sizes
QWEN2_5_7B = ModelConfig(
    name="qwen2.5-7b",
    vocab_size=152064,
    hidden_size=3584,
    intermediate_size=18944,
    num_layers=28,
    num_heads=28,
    num_kv_heads=4,
    head_dim=128,
    rope_theta=1_000_000.0,
    rms_norm_eps=1e-6,
    max_seq_len=16384,  # serving cap; the model supports 32k
    attention_bias=True,
)

QWEN2_5_1_5B = ModelConfig(
    name="qwen2.5-1.5b",
    vocab_size=151936,
    hidden_size=1536,
    intermediate_size=8960,
    num_layers=28,
    num_heads=12,
    num_kv_heads=2,
    head_dim=128,
    rope_theta=1_000_000.0,
    rms_norm_eps=1e-6,
    max_seq_len=16384,
    tie_embeddings=True,
    attention_bias=True,
)

#: small config for tests and the compile-check entry point: real arrays,
#: real architecture, laptop-sized
TINY_TEST = ModelConfig(
    name="tiny-test",
    vocab_size=512,
    hidden_size=128,
    intermediate_size=352,
    num_layers=3,
    num_heads=8,
    num_kv_heads=2,
    head_dim=16,
    rope_theta=10_000.0,
    max_seq_len=256,
)

_REGISTRY = {
    cfg.name: cfg
    for cfg in (
        TINYLLAMA_1_1B,
        LLAMA_3_8B,
        LLAMA_3_1_8B,
        LLAMA_3_2_1B,
        LLAMA_3_2_3B,
        MISTRAL_7B,
        QWEN2_5_7B,
        QWEN2_5_1_5B,
        TINY_TEST,
    )
}


def get_config(name: str) -> ModelConfig:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(f"unknown model {name!r}; known: {sorted(_REGISTRY)}") from None


def register_config(config: ModelConfig) -> None:
    _REGISTRY[config.name] = config


def scaled(config: ModelConfig, *, num_layers: Optional[int] = None,
           max_seq_len: Optional[int] = None) -> ModelConfig:
    """A reduced variant (fewer layers / shorter context) for smoke tests."""
    kwargs = {}
    if num_layers is not None:
        kwargs["num_layers"] = num_layers
    if max_seq_len is not None:
        kwargs["max_seq_len"] = max_seq_len
    return replace(config, name=f"{config.name}-scaled", **kwargs)
