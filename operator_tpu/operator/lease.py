"""Lease-based leader election — HA for the control plane.

A ``coordination.k8s.io/v1 Lease`` object is the shared lock: the replica
whose identity is in ``spec.holderIdentity`` — and whose ``spec.renewTime``
is fresher than ``spec.leaseDurationSeconds`` ago — is the leader.  Only
the leader runs the watcher, reconcilers, pattern sync, and the analysis
pipeline; standbys keep their health server (and engine warmup) hot and
poll the lease so takeover is a re-list away (client-go's
``leaderelection`` discipline, sized down to the calls this control plane
actually needs).

Semantics:

- **acquire**: create the Lease if missing; otherwise take over only when
  the current holder's renewTime has EXPIRED (or the lease is unheld /
  already ours), guarded by resourceVersion so two standbys racing the
  same takeover produce exactly one winner (409 loses);
- **renew**: the leader re-stamps renewTime every ``renew_period_s``
  (jittered so a fleet of operators doesn't synchronize its apiserver
  load).  A renewal observing a DIFFERENT holder steps down immediately;
  renewals failing past ``renew_deadline_s`` (client-go's renewDeadline,
  default 2/3 of the lease duration) step down too — strictly before the
  lease can expire, so the step-down completes while a standby is still
  fenced out; two concurrent "leaders" is the one state this module
  exists to prevent;
- **graceful release**: on shutdown the leader blanks holderIdentity so
  the standby acquires on its next retry tick instead of waiting out the
  full lease duration.

Every apiserver call is bounded by ``kube_timeout_s`` at the call
(graftlint GL003) — a wedged apiserver costs one bounded tick, never the
renew loop.  Clock and jitter rng are injectable so chaos tests replay
deterministically (tests/test_leader.py).
"""

from __future__ import annotations

import asyncio
import datetime
import logging
import random
from typing import Callable, Optional

from ..schema.kube import Lease, LeaseSpec
from ..schema.meta import ObjectMeta
from ..schema.serde import to_dict as _serde_to_dict
from ..utils.timing import METRICS, MetricsRegistry
from .kubeapi import ApiError, ConflictError, KubeApi, NotFoundError

log = logging.getLogger(__name__)

LEASE_KIND = "Lease"


def _iso_micro(epoch: float) -> str:
    """RFC3339 MicroTime, the Lease wire format for acquire/renew times."""
    return (
        datetime.datetime.fromtimestamp(epoch, datetime.timezone.utc)
        .isoformat(timespec="microseconds")
        .replace("+00:00", "Z")
    )


def parse_micro(stamp: Optional[str]) -> Optional[float]:
    """Epoch seconds from an RFC3339(Micro) stamp; None on junk — an
    unparseable renewTime counts as expired (fail open to takeover, the
    alternative is a permanently wedged lease)."""
    if not stamp:
        return None
    try:
        return datetime.datetime.fromisoformat(
            stamp.replace("Z", "+00:00")
        ).timestamp()
    except ValueError:
        return None


class LeaseElector:
    """Acquire/renew/release loop over one Lease object."""

    def __init__(
        self,
        api: KubeApi,
        *,
        lease_name: str,
        namespace: str,
        identity: str,
        duration_s: float = 15.0,
        renew_period_s: float = 5.0,
        retry_period_s: float = 2.0,
        renew_deadline_s: Optional[float] = None,
        kube_timeout_s: float = 15.0,
        metrics: Optional[MetricsRegistry] = None,
        wall_clock: Optional[Callable[[], float]] = None,
        rng: Optional[random.Random] = None,
    ) -> None:
        import time

        self.api = api
        self.lease_name = lease_name
        self.namespace = namespace
        self.identity = identity
        self.duration_s = max(1.0, duration_s)
        self.renew_period_s = max(0.01, renew_period_s)
        self.retry_period_s = max(0.01, retry_period_s)
        # client-go's renewDeadline discipline: the leader must stop
        # renewing (and step down) strictly BEFORE the lease can expire
        # under it, so the step-down completes while the stale lease still
        # fences the standby out.  Without this, a renew RPC wedged in a
        # partitioned apiserver for kube_timeout_s could keep the control
        # loops running seconds after a standby legitimately took over.
        self.renew_deadline_s = min(
            renew_deadline_s if renew_deadline_s is not None
            else 2.0 * self.duration_s / 3.0,
            self.duration_s,
        )
        self.kube_timeout_s = kube_timeout_s
        self.metrics = metrics or METRICS
        self._wall = wall_clock or time.time
        self._rng = rng or random.Random()
        self._leading = asyncio.Event()
        self._not_leading = asyncio.Event()
        self._not_leading.set()
        self._last_renew: Optional[float] = None
        #: leadership transitions observed by THIS elector (tests)
        self.elections = 0

    # -- observers -------------------------------------------------------
    @property
    def is_leader(self) -> bool:
        return self._leading.is_set()

    async def wait_leading(self, stop: asyncio.Event) -> bool:
        """Block until this replica leads (True) or ``stop`` is set."""
        return await self._wait(self._leading, stop)

    async def wait_not_leading(self, stop: asyncio.Event) -> bool:
        """Block until leadership is LOST (True) or ``stop`` is set."""
        return await self._wait(self._not_leading, stop)

    @staticmethod
    async def _wait(event: asyncio.Event, stop: asyncio.Event) -> bool:
        waiters = [
            asyncio.create_task(event.wait()),
            asyncio.create_task(stop.wait()),
        ]
        try:
            await asyncio.wait(waiters, return_when=asyncio.FIRST_COMPLETED)
        finally:
            for task in waiters:
                task.cancel()
            await asyncio.gather(*waiters, return_exceptions=True)
        return event.is_set()

    # -- state flips ------------------------------------------------------
    def _set_leading(self, leading: bool) -> None:
        if leading and not self._leading.is_set():
            self.elections += 1
            self.metrics.incr("leader_elected")
            log.info("leader election: %s acquired lease %s/%s",
                     self.identity, self.namespace, self.lease_name)
            self._leading.set()
            self._not_leading.clear()
        elif not leading and self._leading.is_set():
            self.metrics.incr("leader_lost")
            log.warning("leader election: %s lost lease %s/%s",
                        self.identity, self.namespace, self.lease_name)
            self._leading.clear()
            self._not_leading.set()

    def _jittered(self, period: float) -> float:
        """±20% so a fleet of operators doesn't synchronize its apiserver
        load (and a post-failure retry isn't guaranteed to land late)."""
        return period * (1.0 + 0.2 * (2.0 * self._rng.random() - 1.0))

    # -- main loop --------------------------------------------------------
    async def run(self, stop: asyncio.Event) -> None:
        """Contend for the lease until ``stop``; on exit, release if held."""
        try:
            while not stop.is_set():
                if await self._try_acquire():
                    self._set_leading(True)
                    self._last_renew = self._wall()
                    await self._renew_until_lost(stop)
                    self._set_leading(False)
                if stop.is_set():
                    return
                await self._sleep(self._jittered(self.retry_period_s), stop)
        finally:
            self._set_leading(False)

    async def _renew_until_lost(self, stop: asyncio.Event) -> None:
        period = self.renew_period_s
        while not stop.is_set():
            await self._sleep(self._jittered(period), stop)
            if stop.is_set():
                return
            # bound the attempt by the REMAINING renew deadline, not just
            # kube_timeout_s: an RPC blocked past the deadline must not
            # delay the step-down below it
            budget = self.renew_deadline_s
            if self._last_renew is not None:
                budget = (self._last_renew + self.renew_deadline_s) - self._wall()
            renewed: Optional[bool] = None
            if budget > 0:
                try:
                    renewed = await asyncio.wait_for(self._renew(), timeout=budget)
                except asyncio.TimeoutError:
                    self.metrics.incr("leader_renew_errors")
            now = self._wall()
            if renewed:
                self._last_renew = now
                period = self.renew_period_s
                continue
            if renewed is False:
                # positively lost: another holder observed on the lease
                return
            # transient apiserver failure (None): keep trying while OUR
            # last successful renewal is within the renew deadline — past
            # that the lease may expire mid-attempt and a standby
            # legitimately acquire it, so step down first
            if self._last_renew is None or now - self._last_renew >= self.renew_deadline_s:
                log.warning(
                    "leader election: renewals failing for >%.0fs; stepping down",
                    self.renew_deadline_s,
                )
                return
            # retry at the FASTER retry cadence (client-go's retryPeriod):
            # at the renew cadence, a single blip would usually exhaust the
            # remaining deadline before the next attempt and depose a
            # leader whose lease was still perfectly valid
            period = self.retry_period_s

    @staticmethod
    async def _sleep(seconds: float, stop: asyncio.Event) -> None:
        try:
            await asyncio.wait_for(stop.wait(), timeout=seconds)
        except asyncio.TimeoutError:
            pass

    # -- lease CRUD --------------------------------------------------------
    def _lease_body(self, *, transitions: int, acquire_time: Optional[str]) -> dict:
        spec = LeaseSpec(
            holder_identity=self.identity,
            lease_duration_seconds=int(self.duration_s),
            renew_time=_iso_micro(self._wall()),
            lease_transitions=transitions,
            acquire_time=acquire_time,
        )
        return _serde_to_dict(spec)

    async def _try_acquire(self) -> bool:
        """One acquisition attempt; False on held-by-live-leader, races,
        and apiserver failures (the run loop retries)."""
        try:
            raw = await asyncio.wait_for(
                self.api.get(LEASE_KIND, self.lease_name, self.namespace),
                timeout=self.kube_timeout_s,
            )
        except NotFoundError:
            return await self._create_lease()
        except (ApiError, asyncio.TimeoutError):
            self.metrics.incr("leader_renew_errors")
            return False
        spec = raw.get("spec") or {}
        holder = spec.get("holderIdentity") or ""
        renew = parse_micro(spec.get("renewTime"))
        fresh = renew is not None and (self._wall() - renew) < self.duration_s
        if holder and holder != self.identity and fresh:
            return False  # a live leader holds it
        transitions = int(spec.get("leaseTransitions") or 0)
        if holder != self.identity:
            transitions += 1
        patch = {"spec": self._lease_body(
            transitions=transitions, acquire_time=_iso_micro(self._wall())
        )}
        try:
            await asyncio.wait_for(
                self.api.patch(
                    LEASE_KIND, self.lease_name, self.namespace, patch,
                    resource_version=(raw.get("metadata") or {}).get("resourceVersion"),
                ),
                timeout=self.kube_timeout_s,
            )
        except ConflictError:
            return False  # another standby won the takeover race
        except NotFoundError:
            return await self._create_lease()
        except (ApiError, asyncio.TimeoutError):
            self.metrics.incr("leader_renew_errors")
            return False
        return True

    async def _create_lease(self) -> bool:
        body = Lease(
            metadata=ObjectMeta(name=self.lease_name, namespace=self.namespace),
        ).to_dict()
        body["spec"] = self._lease_body(
            transitions=0, acquire_time=_iso_micro(self._wall())
        )
        try:
            await asyncio.wait_for(
                self.api.create(LEASE_KIND, body), timeout=self.kube_timeout_s
            )
        except ConflictError:
            return False  # another replica created it first
        except (ApiError, asyncio.TimeoutError):
            self.metrics.incr("leader_renew_errors")
            return False
        return True

    async def _renew(self) -> Optional[bool]:
        """One renewal tick: True = renewed, False = positively lost (a
        different holder is on the lease), None = transient failure."""
        try:
            raw = await asyncio.wait_for(
                self.api.get(LEASE_KIND, self.lease_name, self.namespace),
                timeout=self.kube_timeout_s,
            )
        except NotFoundError:
            # someone deleted the lease out from under us: re-create on the
            # acquire path rather than silently leading lease-less
            return False
        except (ApiError, asyncio.TimeoutError):
            self.metrics.incr("leader_renew_errors")
            return None
        spec = raw.get("spec") or {}
        holder = spec.get("holderIdentity") or ""
        if holder and holder != self.identity:
            return False
        try:
            await asyncio.wait_for(
                self.api.patch(
                    LEASE_KIND, self.lease_name, self.namespace,
                    {"spec": {
                        "holderIdentity": self.identity,
                        "renewTime": _iso_micro(self._wall()),
                    }},
                    resource_version=(raw.get("metadata") or {}).get("resourceVersion"),
                ),
                timeout=self.kube_timeout_s,
            )
        except ConflictError:
            return None  # racing write; next tick re-reads
        except NotFoundError:
            return False
        except (ApiError, asyncio.TimeoutError):
            self.metrics.incr("leader_renew_errors")
            return None
        return True

    async def release(self) -> None:
        """Graceful hand-off: blank holderIdentity so the standby's next
        retry tick acquires immediately instead of waiting out the lease
        duration.  Best-effort and bounded — shutdown must complete.  No
        local is-leader gate: the run loop has usually already stepped down
        by the time shutdown calls this, so the authority on whether there
        is anything to release is the lease's own holder field."""
        self._set_leading(False)
        try:
            raw = await asyncio.wait_for(
                self.api.get(LEASE_KIND, self.lease_name, self.namespace),
                timeout=self.kube_timeout_s,
            )
            if ((raw.get("spec") or {}).get("holderIdentity") or "") != self.identity:
                return  # already taken over; nothing to release
            await asyncio.wait_for(
                self.api.patch(
                    LEASE_KIND, self.lease_name, self.namespace,
                    {"spec": {"holderIdentity": "", "renewTime": None}},
                    resource_version=(raw.get("metadata") or {}).get("resourceVersion"),
                ),
                timeout=self.kube_timeout_s,
            )
            log.info("leader election: released lease %s/%s",
                     self.namespace, self.lease_name)
        except (ApiError, asyncio.TimeoutError):
            log.warning("lease release failed; standby will wait out the "
                        "lease duration", exc_info=True)
