"""Health checks.

Readiness parity with reference PatternLibraryReadinessCheck
(health/PatternLibraryReadinessCheck.java:22-86): ready when no
PatternLibrary CRs exist; otherwise require at least one pattern YAML in the
cache; after a 5-minute startup grace period report ready regardless (so a
broken Git remote can't keep the operator out of rotation forever).

Beyond parity, readiness also gates on serving-engine WARMTH when the
operator is warming one (weights loaded + default-bucket programs
compiled).  The reference gates readiness on its heavy dependency being
usable (the pattern cache, :22-86); this system's heavy dependency is the
in-process TPU engine — minutes of weight load + XLA compile at 8B scale.
Without the gate a pod reports Ready cold, and the first failures
analyzed in that window eat the compile latency inside their 2 s budget.
The same grace period applies so a permanently broken engine (which the
operator survives by degrading to pattern-only analyses) cannot keep the
pod out of rotation forever.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Optional

from ..patterns.loader import discover_library_files
from ..utils.config import OperatorConfig
from .kubeapi import ApiError, KubeApi

STARTUP_GRACE_S = 300.0  # reference :26 (5 minutes)

#: engine warmth states an ``engine_state`` callable may report
ENGINE_DISABLED = "disabled"   # no engine is being warmed (no gating)
ENGINE_LOADING = "loading"     # weights/compile in progress (gate)
ENGINE_READY = "ready"         # warmup generation completed
ENGINE_FAILED = "failed"       # build failed; operator degrades to pattern-only


@dataclass
class HealthStatus:
    ready: bool
    reason: str


class ReadinessCheck:
    def __init__(
        self,
        api: KubeApi,
        config: Optional[OperatorConfig] = None,
        *,
        started_at: Optional[float] = None,
        engine_state: Optional[Callable[[], str]] = None,
    ) -> None:
        self.api = api
        self.config = config or OperatorConfig()
        self.started_at = time.monotonic() if started_at is None else started_at
        #: callable reporting ENGINE_* warmth; None = no engine gating
        self.engine_state = engine_state

    def _in_grace(self) -> bool:
        return (time.monotonic() - self.started_at) > STARTUP_GRACE_S

    async def check(self) -> HealthStatus:
        patterns = await self._check_patterns()
        if not patterns.ready:
            return patterns
        state = self.engine_state() if self.engine_state is not None else ENGINE_DISABLED
        if state == ENGINE_LOADING:
            if self._in_grace():
                return HealthStatus(
                    True, f"{patterns.reason}; engine still warming but grace elapsed"
                )
            return HealthStatus(
                False, "serving engine warming (weight load / XLA compile)"
            )
        if state == ENGINE_FAILED:
            # deliberate: the operator stays in rotation serving
            # pattern-only analyses (app.py degrades quietly); a dead
            # optional engine must not unschedule the control plane
            return HealthStatus(True, f"{patterns.reason}; engine failed (degraded)")
        if state == ENGINE_READY:
            return HealthStatus(True, f"{patterns.reason}; engine warm")
        return patterns

    async def _check_patterns(self) -> HealthStatus:
        try:
            libraries = await self.api.list("PatternLibrary")
        except ApiError as exc:
            # can't even list CRs: not ready unless grace elapsed
            if self._in_grace():
                return HealthStatus(True, f"degraded (list failed: {exc}) but grace elapsed")
            return HealthStatus(False, f"cannot list PatternLibrary CRs: {exc}")
        if not libraries:
            return HealthStatus(True, "no PatternLibrary CRs configured")  # reference :38-41
        files = discover_library_files(self.config.pattern_cache_directory)
        if files:
            return HealthStatus(True, f"{len(files)} pattern file(s) cached")
        if self._in_grace():
            return HealthStatus(True, "no patterns cached but startup grace elapsed")  # :72-76
        return HealthStatus(False, "PatternLibrary CRs exist but no patterns cached yet")


class LivenessCheck:
    """Alive as long as the event loop answers."""

    async def check(self) -> HealthStatus:
        return HealthStatus(True, "alive")
