"""Health checks.

Readiness parity with reference PatternLibraryReadinessCheck
(health/PatternLibraryReadinessCheck.java:22-86): ready when no
PatternLibrary CRs exist; otherwise require at least one pattern YAML in the
cache; after a 5-minute startup grace period report ready regardless (so a
broken Git remote can't keep the operator out of rotation forever).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Optional

from ..patterns.loader import discover_library_files
from ..utils.config import OperatorConfig
from .kubeapi import ApiError, KubeApi

STARTUP_GRACE_S = 300.0  # reference :26 (5 minutes)


@dataclass
class HealthStatus:
    ready: bool
    reason: str


class ReadinessCheck:
    def __init__(
        self,
        api: KubeApi,
        config: Optional[OperatorConfig] = None,
        *,
        started_at: Optional[float] = None,
    ) -> None:
        self.api = api
        self.config = config or OperatorConfig()
        self.started_at = time.monotonic() if started_at is None else started_at

    def _in_grace(self) -> bool:
        return (time.monotonic() - self.started_at) > STARTUP_GRACE_S

    async def check(self) -> HealthStatus:
        try:
            libraries = await self.api.list("PatternLibrary")
        except ApiError as exc:
            # can't even list CRs: not ready unless grace elapsed
            if self._in_grace():
                return HealthStatus(True, f"degraded (list failed: {exc}) but grace elapsed")
            return HealthStatus(False, f"cannot list PatternLibrary CRs: {exc}")
        if not libraries:
            return HealthStatus(True, "no PatternLibrary CRs configured")  # reference :38-41
        files = discover_library_files(self.config.pattern_cache_directory)
        if files:
            return HealthStatus(True, f"{len(files)} pattern file(s) cached")
        if self._in_grace():
            return HealthStatus(True, "no patterns cached but startup grace elapsed")  # :72-76
        return HealthStatus(False, "PatternLibrary CRs exist but no patterns cached yet")


class LivenessCheck:
    """Alive as long as the event loop answers."""

    async def check(self) -> HealthStatus:
        return HealthStatus(True, "alive")
