"""CLI entry: ``python -m operator_tpu.operator --demo``."""

from .app import _main

if __name__ == "__main__":
    raise SystemExit(_main())
