"""Kubernetes Events — the system's user-facing result channel.

Parity with the reference's EventService (SURVEY.md §5 observability):

- three lifecycle events: ``PodFailureDetected`` (Warning),
  ``PodmortemAnalysisComplete`` (Normal), ``PodmortemAnalysisError``
  (Warning) (reference EventService.java:45-128);
- each emitted to three targets: the failed pod, its owning Deployment
  (found by chasing Pod -> ReplicaSet -> Deployment owner references,
  :224-256), and the Podmortem CR;
- 1024-byte message budget that preserves the "Root Cause" / "Fix"
  sections of AI output when truncating (:81-91,278-305);
- ``reportingController: podmortem.operator`` (:32).
"""

from __future__ import annotations

import asyncio
import logging
import re
import uuid
from typing import Optional

from ..schema.analysis import AnalysisResult
from ..schema.crds import Podmortem
from ..schema.kube import Event, ObjectReference, Pod
from ..schema.meta import K8sObject, now_iso
from ..utils.config import OperatorConfig
from .kubeapi import ApiError, KubeApi, NotFoundError

log = logging.getLogger(__name__)

REASON_FAILURE_DETECTED = "PodFailureDetected"
REASON_ANALYSIS_COMPLETE = "PodmortemAnalysisComplete"
REASON_ANALYSIS_ERROR = "PodmortemAnalysisError"


def _section(text: str, heading: str) -> Optional[str]:
    """Extract a ``heading: ...`` section from AI output (up to the next
    heading-looking line or blank line block)."""
    pattern = re.compile(
        rf"(?im)^[#*\s]*{heading}[^\n:]*:?\s*\n?(.*?)(?=\n[#*\s]*[A-Z][\w /]+:|\n\s*\n|\Z)",
        re.DOTALL,
    )
    match = pattern.search(text)
    if not match:
        return None
    body = match.group(1).strip()
    return body or None


def truncate_message(text: str, limit: int = 1024) -> str:
    """Budgeted truncation that keeps the parts users act on
    (reference EventService.java:278-305: preserves Root Cause / Fix)."""
    if len(text) <= limit:
        return text
    root_cause = _section(text, "Root Cause")
    fix = _section(text, "(?:Suggested )?Fix")
    if root_cause or fix:
        parts = []
        if root_cause:
            parts.append(f"Root Cause: {root_cause}")
        if fix:
            parts.append(f"Fix: {fix}")
        composed = "\n".join(parts)
        if len(composed) <= limit:
            return composed
        return composed[: limit - 3] + "..."
    return text[: limit - 3] + "..."


class EventService:
    def __init__(self, api: KubeApi, config: Optional[OperatorConfig] = None) -> None:
        self.api = api
        self.config = config or OperatorConfig()

    # -- public emitters ---------------------------------------------------
    async def emit_failure_detected(self, pod: Pod, podmortem: Podmortem) -> None:
        message = (
            f"Pod failure detected in {pod.qualified_name()}; analysis started "
            f"(podmortem: {podmortem.metadata.name})"
        )
        await self._emit_all(REASON_FAILURE_DETECTED, "Warning", message, pod, podmortem)

    async def emit_analysis_complete(
        self,
        pod: Pod,
        podmortem: Podmortem,
        result: AnalysisResult,
        explanation: Optional[str],
    ) -> None:
        severity = result.summary.highest_severity or "NONE"
        header = (
            f"Analysis complete for {pod.qualified_name()} "
            f"[severity: {severity}, significant events: {result.summary.significant_events}]"
        )
        message = f"{header}\n{explanation}" if explanation else header
        await self._emit_all(REASON_ANALYSIS_COMPLETE, "Normal", message, pod, podmortem)

    async def emit_analysis_error(self, pod: Pod, podmortem: Podmortem, error: str) -> None:
        message = f"Analysis failed for {pod.qualified_name()}: {error}"
        await self._emit_all(REASON_ANALYSIS_ERROR, "Warning", message, pod, podmortem)

    # -- mechanics ---------------------------------------------------------
    async def _emit_all(
        self, reason: str, type_: str, message: str, pod: Pod, podmortem: Podmortem
    ) -> None:
        """Emit to pod + owning Deployment + CR; an individual emission
        failing must not break the pipeline (reference emits async off the
        event loop and logs failures, EventService.java:158-203)."""
        targets: list[K8sObject] = [pod]
        deployment = await self.find_owning_deployment(pod)
        if deployment is not None:
            targets.append(deployment)
        targets.append(podmortem)
        for target in targets:
            try:
                await self._emit(reason, type_, message, target)
            except (ApiError, asyncio.TimeoutError) as exc:
                log.warning("failed to emit %s to %s: %s", reason,
                            target.qualified_name(), str(exc) or "timed out")

    async def _emit(self, reason: str, type_: str, message: str, target: K8sObject) -> None:
        event = Event()
        event.metadata.name = self._event_name(target.metadata.name or "obj")
        event.metadata.namespace = target.metadata.namespace
        event.reason = reason
        event.type_ = type_
        event.note = truncate_message(message, self.config.event_message_limit)
        event.action = "Analyze"
        event.reporting_controller = self.config.reporting_controller
        event.reporting_instance = f"{self.config.reporting_controller}-0"
        event.event_time = now_iso()
        event.regarding = ObjectReference(
            api_version=target.api_version,
            kind=target.kind,
            name=target.metadata.name,
            namespace=target.metadata.namespace,
            uid=target.metadata.uid,
        )
        # bounded by the control-loop kube budget (graftlint GL003):
        # events are a best-effort surface — a wedged apiserver costs one
        # bounded attempt, never the analysis pipeline behind it
        await asyncio.wait_for(
            self.api.create("Event", event.to_dict()),
            timeout=self.config.kube_call_timeout_s,
        )

    @staticmethod
    def _event_name(target_name: str) -> str:
        # unique per occurrence (reference generateEventName :264)
        return f"podmortem.{target_name[:40]}.{uuid.uuid4().hex[:10]}"

    async def find_owning_deployment(self, pod: Pod) -> Optional[K8sObject]:
        """Pod -> ReplicaSet -> Deployment owner chase
        (reference EventService.java:224-256)."""
        from ..schema.kube import Deployment  # local to avoid cycle noise

        rs_ref = next(
            (ref for ref in pod.metadata.owner_references if ref.kind == "ReplicaSet"), None
        )
        if rs_ref is None or not pod.metadata.namespace:
            return None
        try:
            rs_dict = await asyncio.wait_for(
                self.api.get("ReplicaSet", rs_ref.name, pod.metadata.namespace),
                timeout=self.config.kube_call_timeout_s,
            )
        except NotFoundError:
            return None
        except (ApiError, asyncio.TimeoutError) as exc:
            log.debug("owner chase failed at ReplicaSet: %s", exc)
            return None
        from ..schema.kube import ReplicaSet

        rs = ReplicaSet.parse(rs_dict)
        deploy_ref = next(
            (ref for ref in rs.metadata.owner_references if ref.kind == "Deployment"), None
        )
        if deploy_ref is None:
            return None
        try:
            deploy_dict = await asyncio.wait_for(
                self.api.get("Deployment", deploy_ref.name, pod.metadata.namespace),
                timeout=self.config.kube_call_timeout_s,
            )
        except NotFoundError:
            return None
        except (ApiError, asyncio.TimeoutError) as exc:
            log.debug("owner chase failed at Deployment: %s", exc)
            return None
        return Deployment.parse(deploy_dict)
