"""AI provider backends + resolution of AIProvider CRs into runtime config.

The reference delegates explanation generation to an external ai-interface
service addressed by ``providerId`` (``openai``, ``ollama`` — reference
aiprovider-crd.yaml:19-21, AIInterfaceRestClient.java:37-39).  Here providers
are in-process backends behind one async interface:

- ``tpu-native``  — the in-tree TPU serving engine (registered by
  ``operator_tpu.serving`` at startup; the whole point of the rebuild);
- ``template``    — deterministic pattern-based explanations, no model
  (fallback + tests);
- ``openai`` / any OpenAI-compatible HTTP endpoint — preserved for parity
  (reference README.md:50-66), implemented with urllib in a thread so the
  event loop stays unblocked (the reference's worker-pool discipline,
  SURVEY.md §5).

Config resolution mirrors AIInterfaceClient.convertToProviderConfig
(reference :71-105): CR spec + defaults + auth token base64-decoded from the
referenced Secret (:118-149).
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import logging
import time
import urllib.error
import urllib.request
from collections import OrderedDict
from typing import Callable, Optional, Protocol

from ..schema.analysis import AIProviderConfig, AIResponse, AnalysisRequest
from ..schema.crds import AIProvider
from ..schema.kube import Secret
from ..utils.deadline import Deadline
from .kubeapi import ApiError, KubeApi, NotFoundError

log = logging.getLogger(__name__)


class AIProviderBackend(Protocol):
    async def generate(self, request: AnalysisRequest) -> AIResponse: ...


class ProviderError(Exception):
    pass


# --------------------------------------------------------------------------
# per-provider circuit breaker
# --------------------------------------------------------------------------


class CircuitBreaker:
    """Consecutive-failure breaker for one AI backend.

    States: ``closed`` (calls flow) → after ``failure_threshold``
    consecutive failures ``open`` (calls skipped: a dead backend must stop
    burning the deadline budget — the pipeline falls through the existing
    degradation ladder and stores pattern-only results) → after
    ``reset_s`` ``half-open`` (exactly ONE probe flows) → probe success
    closes, probe failure re-opens for another window.

    The clock is injectable so chaos tests drive the state machine
    deterministically (tests/test_chaos.py).
    """

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half-open"

    def __init__(
        self,
        failure_threshold: int = 5,
        reset_s: float = 30.0,
        clock: Optional[Callable[[], float]] = None,
    ) -> None:
        self.failure_threshold = max(1, failure_threshold)
        self.reset_s = reset_s
        self._clock = clock or time.monotonic
        self.state = self.CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_at = 0.0

    def allow(self) -> bool:
        """May a call be attempted now?  Transitions open → half-open when
        the reset window elapsed (that caller IS the probe; concurrent
        callers in half-open are refused until the probe resolves).  A
        probe whose caller died without ever reporting (cancelled task,
        operator shutdown mid-call) must not wedge the breaker: after
        another full window in half-open a fresh probe is admitted."""
        now = self._clock()
        if self.state == self.OPEN:
            if now - self._opened_at >= self.reset_s:
                self.state = self.HALF_OPEN
                self._probe_at = now
                return True
            return False
        if self.state == self.HALF_OPEN:
            if now - self._probe_at >= self.reset_s:
                self._probe_at = now
                return True
            return False
        return True

    def record_success(self) -> None:
        self._consecutive_failures = 0
        self.state = self.CLOSED

    def record_failure(self) -> bool:
        """Returns True when THIS failure opened (or re-opened) the
        breaker — the caller's cue to count/emit the trip once."""
        if self.state == self.HALF_OPEN:
            self.state = self.OPEN
            self._opened_at = self._clock()
            return True
        self._consecutive_failures += 1
        if (
            self.state == self.CLOSED
            and self._consecutive_failures >= self.failure_threshold
        ):
            self.state = self.OPEN
            self._opened_at = self._clock()
            return True
        return False


class BreakerBoard:
    """One CircuitBreaker per providerId, created on first use."""

    def __init__(
        self,
        failure_threshold: int = 5,
        reset_s: float = 30.0,
        clock: Optional[Callable[[], float]] = None,
    ) -> None:
        self.failure_threshold = failure_threshold
        self.reset_s = reset_s
        self._clock = clock
        self._breakers: dict[str, CircuitBreaker] = {}

    def for_provider(self, provider_id: Optional[str]) -> CircuitBreaker:
        pid = provider_id or "template"
        breaker = self._breakers.get(pid)
        if breaker is None:
            breaker = CircuitBreaker(
                self.failure_threshold, self.reset_s, clock=self._clock
            )
            self._breakers[pid] = breaker
        return breaker

    def states(self) -> dict[str, str]:
        return {pid: b.state for pid, b in self._breakers.items()}


# --------------------------------------------------------------------------
# registry
# --------------------------------------------------------------------------


class ProviderRegistry:
    def __init__(self) -> None:
        self._backends: dict[str, AIProviderBackend] = {}
        self._factories: dict[str, Callable[[], AIProviderBackend]] = {}

    def register(self, provider_id: str, backend: AIProviderBackend) -> None:
        self._backends[provider_id] = backend

    def register_factory(self, provider_id: str, factory: Callable[[], AIProviderBackend]) -> None:
        """Lazy registration — the tpu-native backend loads model weights, so
        it materialises on first use, not at import."""
        self._factories[provider_id] = factory

    def resolve(self, provider_id: Optional[str]) -> AIProviderBackend:
        pid = provider_id or "template"
        backend = self._backends.get(pid)
        if backend is None and pid in self._factories:
            try:
                backend = self._factories[pid]()
            except Exception as exc:  # noqa: BLE001 - degrade to ProviderError
                # keep the factory registered: the failure may be transient
                # (e.g. TPU busy); the pipeline stores a pattern-only result
                raise ProviderError(f"provider {pid!r} failed to initialise: {exc}") from exc
            del self._factories[pid]
            self._backends[pid] = backend
        if backend is None:
            if pid in ("openai", "ollama", "openai-compatible"):
                backend = OpenAICompatProvider()
                self._backends[pid] = backend
            else:
                raise ProviderError(f"unknown providerId {pid!r}")
        return backend

    def known_ids(self) -> list[str]:
        return sorted(
            set(self._backends) | set(self._factories) | {"openai", "ollama", "template"}
        )


def default_registry() -> ProviderRegistry:
    registry = ProviderRegistry()
    registry.register("template", TemplateProvider())
    return registry


# --------------------------------------------------------------------------
# CR -> config resolution
# --------------------------------------------------------------------------


async def resolve_provider_config(
    api: KubeApi,
    provider: AIProvider,
    *,
    deadline: Optional[Deadline] = None,
) -> AIProviderConfig:
    """CR spec + defaults + auth token from the referenced Secret.  The
    Secret read spends from ``deadline`` (the analysis envelope residue);
    a timeout degrades exactly like a fetch error — config without a token."""
    spec = provider.spec
    token: Optional[str] = None
    auth = spec.authentication_ref
    if auth is not None and auth.secret_name:
        try:
            secret_dict = await asyncio.wait_for(
                api.get(
                    "Secret", auth.secret_name, provider.metadata.namespace or "default"
                ),
                timeout=deadline.remaining() if deadline is not None else None,
            )
            token = Secret.parse(secret_dict).decoded(auth.secret_key or "token")
            if token is None:
                log.warning(
                    "secret %s has no key %s", auth.secret_name, auth.secret_key or "token"
                )
        except NotFoundError:
            log.warning("auth secret %s not found for provider %s",
                        auth.secret_name, provider.metadata.name)
        except (ApiError, asyncio.TimeoutError) as exc:
            log.warning("failed reading auth secret for %s: %s",
                        provider.metadata.name, str(exc) or "timed out")
    return AIProviderConfig(
        provider_id=spec.provider_id,
        api_url=spec.api_url,
        model_id=spec.model_id,
        auth_token=token,
        timeout_seconds=spec.timeout_seconds,
        max_retries=spec.max_retries,
        caching_enabled=spec.caching_enabled,
        prompt_template=spec.prompt_template,
        max_tokens=spec.max_tokens,
        temperature=spec.temperature,
        additional_config=dict(spec.additional_config),
    )


# --------------------------------------------------------------------------
# response cache (reference cachingEnabled, AIInterfaceClient.java:80)
# --------------------------------------------------------------------------


class ResponseCache:
    """Small LRU keyed on the analysis evidence, so a crash-looping pod
    replaying one failure doesn't re-run generation every restart."""

    def __init__(self, maxsize: int = 256) -> None:
        self.maxsize = maxsize
        self._entries: OrderedDict[str, AIResponse] = OrderedDict()

    @staticmethod
    def key(request: AnalysisRequest) -> str:
        result = request.analysis_result
        config = request.provider_config
        basis = {
            "provider": config.provider_id if config else None,
            "model": config.model_id if config else None,
            "patterns": [
                (e.matched_pattern.id if e.matched_pattern else None,
                 e.context.matched_line if e.context else None)
                for e in (result.events if result else [])[:8]
            ],
            # near-miss recalls change the rendered prompt, so they are
            # part of the response identity too
            "prior": [p.fingerprint for p in request.prior_incidents],
        }
        return hashlib.sha256(json.dumps(basis, sort_keys=True).encode()).hexdigest()

    def get(self, key: str) -> Optional[AIResponse]:
        response = self._entries.get(key)
        if response is not None:
            self._entries.move_to_end(key)
        return response

    def put(self, key: str, response: AIResponse) -> None:
        self._entries[key] = response
        self._entries.move_to_end(key)
        while len(self._entries) > self.maxsize:
            self._entries.popitem(last=False)


# --------------------------------------------------------------------------
# backends
# --------------------------------------------------------------------------


class TemplateProvider:
    """Deterministic explanation straight from the pattern result — the
    zero-model fallback, formatted with the Root Cause / Fix sections the
    event truncation preserves (reference EventService.java:282-301)."""

    async def generate(self, request: AnalysisRequest) -> AIResponse:
        result = request.analysis_result
        config = request.provider_config or AIProviderConfig()
        if result is None or not result.events:
            return AIResponse(
                explanation="Root Cause: no known failure pattern matched the logs.\n"
                "Fix: inspect the pod logs manually.",
                provider_id="template",
                model_id=config.model_id,
            )
        top = result.top_events(3)
        primary = top[0]
        name = primary.matched_pattern.name if primary.matched_pattern else "unknown failure"
        lines = [f"Root Cause: {name}."]
        if primary.context and primary.context.matched_line:
            lines.append(f'Evidence: "{primary.context.matched_line.strip()[:200]}"')
        if len(top) > 1:
            others = ", ".join(
                e.matched_pattern.name for e in top[1:] if e.matched_pattern and e.matched_pattern.name
            )
            if others:
                lines.append(f"Related signals: {others}.")
        remediation = primary.matched_pattern.remediation if primary.matched_pattern else None
        lines.append(f"Fix: {remediation.strip()}" if remediation else
                     "Fix: inspect the surrounding log context.")
        return AIResponse(
            explanation="\n".join(lines),
            provider_id="template",
            model_id=config.model_id,
        )


class OpenAICompatProvider:
    """OpenAI-compatible chat-completions client (covers ``openai`` and
    ``ollama`` providerIds).  Blocking urllib runs in a worker thread; retries
    honour the CR's maxRetries (reference defaults :78-84)."""

    def __init__(self, opener: Optional[Callable] = None) -> None:
        # injectable for tests; defaults to urllib
        self._opener = opener or urllib.request.urlopen
        #: opt-in chaos seam (utils/faultinject.py): consulted before each
        #: outbound attempt under site "http.provider"
        self.fault_plan = None

    async def generate(self, request: AnalysisRequest) -> AIResponse:
        config = request.provider_config or AIProviderConfig()
        if not config.api_url:
            return AIResponse(error="provider has no apiUrl", provider_id=config.provider_id)
        from ..serving.prompts import build_prompt  # shared with tpu-native path

        prompt = build_prompt(request)
        body = {
            "model": config.model_id,
            "messages": [{"role": "user", "content": prompt}],
            "max_tokens": config.max_tokens,
            "temperature": config.temperature,
        }
        # accept any of: bare host, .../v1, or a full .../chat/completions URL
        # (the documented OpenAI base is https://api.openai.com/v1)
        url = config.api_url.rstrip("/")
        if url.endswith("/chat/completions"):
            pass
        elif url.endswith("/v1"):
            url = f"{url}/chat/completions"
        else:
            url = f"{url}/v1/chat/completions"
        headers = {"Content-Type": "application/json"}
        if config.auth_token:
            headers["Authorization"] = f"Bearer {config.auth_token}"
        # W3C trace context: the analysis trace crosses into the external
        # backend (and any proxy between) — its serving-side spans join
        # OUR trace id (operator_tpu/obs/, docs/OBSERVABILITY.md).
        # Captured here on the event loop; the blocking call runs in a
        # worker thread where the ambient span is not visible.
        from ..obs import current_traceparent

        traceparent = current_traceparent()
        if traceparent:
            headers["traceparent"] = traceparent

        def call(timeout_s: float) -> AIResponse:
            req = urllib.request.Request(
                url, data=json.dumps(body).encode(), headers=headers, method="POST"
            )
            with self._opener(req, timeout=timeout_s) as resp:
                payload = json.loads(resp.read().decode())
            text = payload["choices"][0]["message"]["content"]
            usage = payload.get("usage", {})
            return AIResponse(
                explanation=text,
                provider_id=config.provider_id,
                model_id=config.model_id,
                prompt_tokens=usage.get("prompt_tokens"),
                completion_tokens=usage.get("completion_tokens"),
                deadline_outcome=(
                    "completed" if request.deadline_s is not None else None
                ),
            )

        # deadline budget: the CR's per-attempt read timeout never reaches
        # past the residue, and the retry loop stops once it is spent —
        # retrying a dead backend must not eat the whole analysis envelope
        budget = (
            Deadline.start(request.deadline_s)
            if request.deadline_s is not None
            else None
        )
        last_error: Optional[str] = None
        for attempt in range(max(1, config.max_retries)):
            timeout_s = float(config.timeout_seconds)
            if budget is not None:
                residue = budget.remaining()
                if residue <= 0.0:
                    return AIResponse(
                        error=f"deadline exceeded after {attempt} attempt(s): "
                              f"{last_error or 'no attempt completed in budget'}",
                        provider_id=config.provider_id, model_id=config.model_id,
                        deadline_outcome="deadline-exceeded",
                    )
                timeout_s = min(timeout_s, residue)
            try:
                if self.fault_plan is not None:
                    self.fault_plan.apply("http.provider", attempt=attempt)
                return await asyncio.to_thread(call, timeout_s)
            except (urllib.error.URLError, OSError, KeyError, ValueError) as exc:
                last_error = str(exc)
                log.warning("provider %s attempt %d failed: %s",
                            config.provider_id, attempt + 1, exc)
                await asyncio.sleep(min(2**attempt * 0.2, 2.0))
        return AIResponse(error=f"provider failed after retries: {last_error}",
                          provider_id=config.provider_id, model_id=config.model_id)
