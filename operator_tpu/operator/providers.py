"""AI provider backends + resolution of AIProvider CRs into runtime config.

The reference delegates explanation generation to an external ai-interface
service addressed by ``providerId`` (``openai``, ``ollama`` — reference
aiprovider-crd.yaml:19-21, AIInterfaceRestClient.java:37-39).  Here providers
are in-process backends behind one async interface:

- ``tpu-native``  — the in-tree TPU serving engine (registered by
  ``operator_tpu.serving`` at startup; the whole point of the rebuild);
- ``template``    — deterministic pattern-based explanations, no model
  (fallback + tests);
- ``openai`` / any OpenAI-compatible HTTP endpoint — preserved for parity
  (reference README.md:50-66), implemented with urllib in a thread so the
  event loop stays unblocked (the reference's worker-pool discipline,
  SURVEY.md §5).

Config resolution mirrors AIInterfaceClient.convertToProviderConfig
(reference :71-105): CR spec + defaults + auth token base64-decoded from the
referenced Secret (:118-149).
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import logging
import urllib.parse
import urllib.request
from collections import OrderedDict
from typing import Callable, Optional, Protocol

from ..router import EngineRouter, Replica, RouterError, request_key
# the breaker machinery moved to the router package (per-provider AND
# per-replica boards share one implementation); re-exported here so every
# existing import path keeps working
from ..router.health import BreakerBoard, CircuitBreaker  # noqa: F401
from ..schema.analysis import AIProviderConfig, AIResponse, AnalysisRequest
from ..schema.crds import AIProvider
from ..schema.kube import Secret
from ..utils.deadline import Deadline
from .kubeapi import ApiError, KubeApi, NotFoundError

log = logging.getLogger(__name__)


class AIProviderBackend(Protocol):
    async def generate(self, request: AnalysisRequest) -> AIResponse: ...


class ProviderError(Exception):
    pass


# --------------------------------------------------------------------------
# registry
# --------------------------------------------------------------------------


class ProviderRegistry:
    def __init__(self) -> None:
        self._backends: dict[str, AIProviderBackend] = {}
        self._factories: dict[str, Callable[[], AIProviderBackend]] = {}

    def register(self, provider_id: str, backend: AIProviderBackend) -> None:
        self._backends[provider_id] = backend

    def register_factory(self, provider_id: str, factory: Callable[[], AIProviderBackend]) -> None:
        """Lazy registration — the tpu-native backend loads model weights, so
        it materialises on first use, not at import."""
        self._factories[provider_id] = factory

    def resolve(self, provider_id: Optional[str]) -> AIProviderBackend:
        pid = provider_id or "template"
        backend = self._backends.get(pid)
        if backend is None and pid in self._factories:
            try:
                backend = self._factories[pid]()
            except Exception as exc:  # noqa: BLE001 - degrade to ProviderError
                # keep the factory registered: the failure may be transient
                # (e.g. TPU busy); the pipeline stores a pattern-only result
                raise ProviderError(f"provider {pid!r} failed to initialise: {exc}") from exc
            del self._factories[pid]
            self._backends[pid] = backend
        if backend is None:
            if pid in ("openai", "ollama", "openai-compatible"):
                backend = OpenAICompatProvider()
                self._backends[pid] = backend
            else:
                raise ProviderError(f"unknown providerId {pid!r}")
        return backend

    def has(self, provider_id: str) -> bool:
        """Is a backend (or factory) already registered for this id? —
        wiring code must not clobber an injected test/real backend."""
        return provider_id in self._backends or provider_id in self._factories

    def known_ids(self) -> list[str]:
        return sorted(
            set(self._backends) | set(self._factories) | {"openai", "ollama", "template"}
        )


def default_registry() -> ProviderRegistry:
    registry = ProviderRegistry()
    registry.register("template", TemplateProvider())
    return registry


# --------------------------------------------------------------------------
# CR -> config resolution
# --------------------------------------------------------------------------


async def resolve_provider_config(
    api: KubeApi,
    provider: AIProvider,
    *,
    deadline: Optional[Deadline] = None,
) -> AIProviderConfig:
    """CR spec + defaults + auth token from the referenced Secret.  The
    Secret read spends from ``deadline`` (the analysis envelope residue);
    a timeout degrades exactly like a fetch error — config without a token."""
    spec = provider.spec
    token: Optional[str] = None
    auth = spec.authentication_ref
    if auth is not None and auth.secret_name:
        try:
            secret_dict = await asyncio.wait_for(
                api.get(
                    "Secret", auth.secret_name, provider.metadata.namespace or "default"
                ),
                timeout=deadline.remaining() if deadline is not None else None,
            )
            token = Secret.parse(secret_dict).decoded(auth.secret_key or "token")
            if token is None:
                log.warning(
                    "secret %s has no key %s", auth.secret_name, auth.secret_key or "token"
                )
        except NotFoundError:
            log.warning("auth secret %s not found for provider %s",
                        auth.secret_name, provider.metadata.name)
        except (ApiError, asyncio.TimeoutError) as exc:
            log.warning("failed reading auth secret for %s: %s",
                        provider.metadata.name, str(exc) or "timed out")
    return AIProviderConfig(
        provider_id=spec.provider_id,
        api_url=spec.api_url,
        model_id=spec.model_id,
        auth_token=token,
        timeout_seconds=spec.timeout_seconds,
        max_retries=spec.max_retries,
        caching_enabled=spec.caching_enabled,
        prompt_template=spec.prompt_template,
        max_tokens=spec.max_tokens,
        temperature=spec.temperature,
        additional_config=dict(spec.additional_config),
    )


# --------------------------------------------------------------------------
# response cache (reference cachingEnabled, AIInterfaceClient.java:80)
# --------------------------------------------------------------------------


class ResponseCache:
    """Small LRU keyed on the analysis evidence, so a crash-looping pod
    replaying one failure doesn't re-run generation every restart."""

    def __init__(self, maxsize: int = 256) -> None:
        self.maxsize = maxsize
        self._entries: OrderedDict[str, AIResponse] = OrderedDict()

    @staticmethod
    def key(request: AnalysisRequest) -> str:
        result = request.analysis_result
        config = request.provider_config
        basis = {
            "provider": config.provider_id if config else None,
            "model": config.model_id if config else None,
            "patterns": [
                (e.matched_pattern.id if e.matched_pattern else None,
                 e.context.matched_line if e.context else None)
                for e in (result.events if result else [])[:8]
            ],
            # near-miss recalls change the rendered prompt, so they are
            # part of the response identity too
            "prior": [p.fingerprint for p in request.prior_incidents],
        }
        return hashlib.sha256(json.dumps(basis, sort_keys=True).encode()).hexdigest()

    def get(self, key: str) -> Optional[AIResponse]:
        response = self._entries.get(key)
        if response is not None:
            self._entries.move_to_end(key)
        return response

    def put(self, key: str, response: AIResponse) -> None:
        self._entries[key] = response
        self._entries.move_to_end(key)
        while len(self._entries) > self.maxsize:
            self._entries.popitem(last=False)


# --------------------------------------------------------------------------
# backends
# --------------------------------------------------------------------------


class TemplateProvider:
    """Deterministic explanation straight from the pattern result — the
    zero-model fallback, formatted with the Root Cause / Fix sections the
    event truncation preserves (reference EventService.java:282-301)."""

    async def generate(self, request: AnalysisRequest) -> AIResponse:
        result = request.analysis_result
        config = request.provider_config or AIProviderConfig()
        if result is None or not result.events:
            return AIResponse(
                explanation="Root Cause: no known failure pattern matched the logs.\n"
                "Fix: inspect the pod logs manually.",
                provider_id="template",
                model_id=config.model_id,
            )
        top = result.top_events(3)
        primary = top[0]
        name = primary.matched_pattern.name if primary.matched_pattern else "unknown failure"
        lines = [f"Root Cause: {name}."]
        if primary.context and primary.context.matched_line:
            lines.append(f'Evidence: "{primary.context.matched_line.strip()[:200]}"')
        if len(top) > 1:
            others = ", ".join(
                e.matched_pattern.name for e in top[1:] if e.matched_pattern and e.matched_pattern.name
            )
            if others:
                lines.append(f"Related signals: {others}.")
        remediation = primary.matched_pattern.remediation if primary.matched_pattern else None
        lines.append(f"Fix: {remediation.strip()}" if remediation else
                     "Fix: inspect the surrounding log context.")
        return AIResponse(
            explanation="\n".join(lines),
            provider_id="template",
            model_id=config.model_id,
        )


def replica_set(api_url: str) -> list[Replica]:
    """Parse a CR's ``apiUrl`` into the replica set it names.

    ``apiUrl`` accepts a single endpoint (the pre-router form) or a
    comma/whitespace-separated list of them — N serving replicas behind
    one AIProvider.  Every entry must be scheme-qualified (``http://`` /
    ``https://`` with a host): once routing multiplies endpoints, a bare
    ``host:8000`` would fail deep inside urllib with a message naming
    neither the CR nor the offending entry — reject it HERE with a clear
    :class:`ProviderError` instead.  Each replica's id is its normalized
    URL (stable across restarts, readable in spans and metrics)."""
    replicas: list[Replica] = []
    seen: set[str] = set()
    for raw in api_url.replace(",", " ").split():
        url = raw.rstrip("/")
        parts = urllib.parse.urlsplit(url)
        if parts.scheme not in ("http", "https") or not parts.netloc:
            raise ProviderError(
                f"invalid apiUrl entry {raw!r}: must be an absolute "
                "http(s)://host[:port][/path] URL (scheme-qualified; "
                "comma-separate multiple replicas)"
            )
        if url not in seen:
            seen.add(url)
            replicas.append(Replica(id=url, url=url))
    if not replicas:
        raise ProviderError("apiUrl names no endpoints")
    return replicas


def _completions_url(base: str) -> str:
    """Accept any of: bare host, .../v1, or a full .../chat/completions
    URL (the documented OpenAI base is https://api.openai.com/v1)."""
    url = base.rstrip("/")
    if url.endswith("/chat/completions"):
        return url
    if url.endswith("/v1"):
        return f"{url}/chat/completions"
    return f"{url}/v1/chat/completions"


class OpenAICompatProvider:
    """OpenAI-compatible chat-completions client (covers ``openai`` and
    ``ollama`` providerIds).  Blocking urllib runs in a worker thread;
    retries honour the CR's maxRetries (reference defaults :78-84).

    The CR's ``apiUrl`` may name N replicas (comma-separated, or the
    per-pod DNS names of the headless serving Service): dispatch then
    runs through an :class:`~operator_tpu.router.EngineRouter` per
    distinct replica set — consistent-hash affinity on the incident
    fingerprint / prompt prefix, per-replica breakers, load-fed
    shedding, and requeue-ONCE failover with the residual deadline
    (docs/ROBUSTNESS.md "Multi-replica data plane").  Router state (and
    so breaker/health history) persists across calls per replica set.
    """

    def __init__(
        self,
        opener: Optional[Callable] = None,
        *,
        metrics=None,
        router_vnodes: int = 64,
        shed_pressure: int = 8,
        replica_failure_threshold: int = 3,
        replica_reset_s: float = 10.0,
        clock: Optional[Callable[[], float]] = None,
    ) -> None:
        # injectable for tests; defaults to urllib
        self._opener = opener or urllib.request.urlopen
        #: opt-in chaos seam (utils/faultinject.py): consulted before each
        #: outbound attempt under site "http.provider" (ctx: attempt,
        #: replica) — replica kills/partitions inject here
        self.fault_plan = None
        #: value-aware overload ladder (router/value.py): the pipeline
        #: stamps its policy here; router_for hands it to every router so
        #: the pre-dispatch verdict (shed / degrade / serve) and the
        #: supervisor requeue discipline share one value model
        self.overload_policy = None
        self._metrics = metrics
        self._router_vnodes = router_vnodes
        self._shed_pressure = shed_pressure
        self._replica_failure_threshold = replica_failure_threshold
        self._replica_reset_s = replica_reset_s
        self._clock = clock
        #: one router per distinct replica set, created on first use —
        #: breaker state must survive across requests or a dead replica
        #: would be re-probed by every analysis
        self._routers: dict[tuple[str, ...], EngineRouter] = {}

    #: sentinel replica-set key for the DISCOVERY-driven router: its
    #: membership is mutated live by router/discovery.py instead of being
    #: derived from a CR's apiUrl
    DYNAMIC_KEY: tuple[str, ...] = ("<discovery>",)

    def dynamic_router(self) -> EngineRouter:
        """The endpoint-watch fleet's router (created empty on first
        use).  Living in ``_routers`` means ``fleet_view()`` and the
        health-poll sweep cover discovered replicas for free; when it has
        members, :meth:`generate` prefers it over the static apiUrl set —
        the serving fleet scales without a single CR edit or restart."""
        router = self._routers.get(self.DYNAMIC_KEY)
        if router is None:
            router = EngineRouter(
                [],
                vnodes=self._router_vnodes,
                shed_pressure=self._shed_pressure,
                failure_threshold=self._replica_failure_threshold,
                reset_s=self._replica_reset_s,
                clock=self._clock,
                metrics=self._metrics,
            )
            self._routers[self.DYNAMIC_KEY] = router
        router.fault_plan = self.fault_plan
        router.policy = self.overload_policy
        return router

    async def prewarm_replica(
        self, replica: Replica, *, timeout_s: float = 5.0
    ) -> bool:
        """The discovery loop's join gate: one bounded ``GET /healthz``
        probe against a replica that just appeared in the Endpoints.  A
        200 with ``status == "ok"`` admits it — and the probe body's load
        report primes the health board (queue depth, KV inventory) BEFORE
        the first routed request, so the new member joins warm, not
        blind.  Anything else (still compiling its warmup grid, foreign
        body, unreachable) defers the join to the next Endpoints event."""

        split = urllib.parse.urlsplit(replica.url)
        health_url = f"{split.scheme}://{split.netloc}/healthz"

        def probe() -> dict:
            if self.fault_plan is not None:
                self.fault_plan.apply("http.healthz", replica=replica.id)
            req = urllib.request.Request(health_url, method="GET")
            with self._opener(req, timeout=timeout_s) as resp:
                payload = json.loads(resp.read().decode())
            if not isinstance(payload, dict) or not isinstance(
                payload.get("status"), str
            ):
                raise ValueError(f"foreign /healthz body: {payload!r}")
            return payload

        payload = await asyncio.to_thread(probe)  # raising defers the join
        if payload["status"] != "ok":
            return False
        router = self.dynamic_router()
        router.mark_probe(replica.id, True)
        load = payload.get("load")
        if isinstance(load, dict):
            from ..router.health import ReplicaLoad

            router.report_load(replica.id, ReplicaLoad.parse(load))
        return True

    def router_for(self, replicas: list[Replica]) -> EngineRouter:
        key = tuple(sorted(r.id for r in replicas))
        router = self._routers.get(key)
        if router is None:
            router = EngineRouter(
                replicas,
                vnodes=self._router_vnodes,
                shed_pressure=self._shed_pressure,
                failure_threshold=self._replica_failure_threshold,
                reset_s=self._replica_reset_s,
                clock=self._clock,
                metrics=self._metrics,
            )
            self._routers[key] = router
        router.fault_plan = self.fault_plan
        router.policy = self.overload_policy
        return router

    def fleet_view(self) -> dict:
        """Fleet perf roll-up across EVERY routed replica set — the body
        the operator's token-gated ``GET /fleet`` serves.  Rows come from
        each router's HealthBoard (fed by the health-poll sweep below);
        a replica appearing in several sets keeps one row (same id, same
        /healthz body — last board wins)."""
        from ..router.health import fleet_rollup

        replicas: dict = {}
        for router in list(self._routers.values()):
            replicas.update(router.health.fleet_view()["replicas"])
        fleet = fleet_rollup(replicas)
        # the overload ladder's storm signal, fleet-wide: the best offer
        # any routed replica can make — what the autoscaler bursts on
        fleet["pressure"] = self.fleet_pressure()
        return {"replicas": replicas, "fleet": fleet}

    def fleet_pressure(self) -> "Optional[float]":
        """Least-loaded healthy replica's queue pressure across every
        routed set (None = no healthy replica anywhere)."""
        pressures = [
            p
            for p in (
                router.fleet_pressure()
                for router in list(self._routers.values())
            )
            if p is not None
        ]
        return min(pressures) if pressures else None

    async def poll_replica_health(self, *, timeout_s: float = 5.0) -> int:
        """Active ``GET /healthz`` sweep over every routed replica set,
        feeding each router's HealthBoard (probe verdict + load report).

        Without this, load reports arrive only when request traffic
        happens to feed ``report_load`` — between analyses the shed
        decision flies blind and only the passive breaker gates a sick
        replica (ROADMAP multi-engine item (b)).  The operator runs it
        on a background cadence (``router_health_poll_s``); each probe
        is a blocking urllib GET in a worker thread bounded by
        ``timeout_s`` at the call.  A failed probe marks the replica
        not-ready (the router's health gate skips it) — never raises.
        Returns the number of replicas successfully polled."""
        from ..router.health import ReplicaLoad

        async def poll_one(router: EngineRouter, replica: Replica) -> bool:
            split = urllib.parse.urlsplit(replica.url)
            health_url = f"{split.scheme}://{split.netloc}/healthz"

            def probe(url=health_url):
                if self.fault_plan is not None:
                    # chaos seam: partition/timeout scenarios inject here
                    self.fault_plan.apply("http.healthz", replica=replica.id)
                req = urllib.request.Request(url, method="GET")
                with self._opener(req, timeout=timeout_s) as resp:
                    payload = json.loads(resp.read().decode())
                if not isinstance(payload, dict) or not isinstance(
                    payload.get("status"), str
                ):
                    # valid JSON but not our shape (an LB answering "ok"
                    # or {"healthy": true} in front of a dead engine):
                    # same verdict as an unreachable replica — a foreign
                    # body must neither readmit the replica nor escape
                    # the per-probe handling below (one odd replica
                    # aborting the WHOLE sweep would blind the health
                    # feed for every healthy sibling too)
                    raise ValueError(f"foreign /healthz body: {payload!r}")
                return payload

            try:
                payload = await asyncio.to_thread(probe)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - a dead replica IS the signal
                router.mark_probe(replica.id, False)
                if self._metrics is not None:
                    self._metrics.incr("router_health_poll_failed")
                return False
            # only the one status OUR serving /healthz emits counts as
            # ready; "degraded" (supervisor gave up) and anything foreign
            # leave the replica gated
            router.mark_probe(replica.id, payload["status"] == "ok")
            load = payload.get("load")
            if isinstance(load, dict):
                router.report_load(replica.id, ReplicaLoad.parse(load))
            if self._metrics is not None:
                self._metrics.incr("router_health_poll")
            return True

        # fan the probes out: serially, N black-holed replicas would
        # hold the sweep N x timeout_s — stale health data exactly when
        # replicas are failing, the condition the poll exists for.  The
        # sweep's wall time is ONE probe timeout regardless of fleet size
        results = await asyncio.gather(*(
            poll_one(router, replica)
            for router in list(self._routers.values())
            for replica in router.replicas()
        ))
        return sum(results)

    async def generate(self, request: AnalysisRequest) -> AIResponse:
        config = request.provider_config or AIProviderConfig()
        # discovery-driven fleet first: when the endpoint watch has
        # populated the dynamic router, IT is the replica set — the CR's
        # apiUrl (typically the headless Service DNS) is the bootstrap
        # fallback for installs without discovery (an EMPTY dynamic
        # router falls through rather than failing every request while
        # the fleet is scaled to zero mid-wake)
        router = self._routers.get(self.DYNAMIC_KEY)
        if router is not None and len(router) > 0:
            router.fault_plan = self.fault_plan
            router.policy = self.overload_policy
        else:
            router = None
        if router is None:
            if not config.api_url:
                return AIResponse(error="provider has no apiUrl", provider_id=config.provider_id)
            try:
                replicas = replica_set(config.api_url)
            except ProviderError as exc:
                # a malformed apiUrl is a CONFIG error, not backend weather:
                # surface it verbatim (it names the offending entry) instead
                # of letting urllib produce "unknown url type" noise
                return AIResponse(error=str(exc), provider_id=config.provider_id,
                                  model_id=config.model_id)
            router = self.router_for(replicas)
        from ..serving.prompts import build_prompt  # shared with tpu-native path

        prompt = build_prompt(request)
        # value-aware overload ladder (router/value.py): consult the
        # policy BEFORE building the dispatch — shed returns here with no
        # network traffic at all; degrade truncates analysis depth AND
        # drops the cross-replica requeue allowance to 1 attempt (a
        # depth-truncated answer is not worth a second replica's time —
        # the supervisor-requeue leg of shed-lowest-value-first)
        max_tokens = max(1, config.max_tokens)
        attempts = max(1, config.max_retries)
        degraded = False
        if router.policy is not None:
            verdict = router.overload_verdict(
                value=router.policy.model.value(
                    slo_class=request.slo_class,
                    residual_s=request.deadline_s,
                    recall_p=request.recall_p,
                ),
                request_id=request_key(prompt),
                site="provider",
            )
            if verdict is not None and verdict.action == "shed":
                from ..obs import annotate_root
                from ..obs.sloledger import SLO_OUTCOME_ATTR

                annotate_root(SLO_OUTCOME_ATTR, "shed", overwrite=False)
                return AIResponse(
                    error=(
                        "request shed by overload ladder: lowest value "
                        "under storm (router/value.py)"
                    ),
                    provider_id=config.provider_id,
                    model_id=config.model_id,
                    deadline_outcome="shed",
                )
            if verdict is not None and verdict.action == "degrade":
                max_tokens = max(
                    16, int(max_tokens * verdict.degrade_tokens_frac)
                )
                attempts = 1
                degraded = True
        body = {
            "model": config.model_id,
            "messages": [{"role": "user", "content": prompt}],
            "max_tokens": max_tokens,
            "temperature": config.temperature,
        }
        payload_bytes = json.dumps(body).encode()
        headers = {"Content-Type": "application/json"}
        if config.auth_token:
            headers["Authorization"] = f"Bearer {config.auth_token}"
        # W3C trace context: the analysis trace crosses into the external
        # backend (and any proxy between) — its serving-side spans join
        # OUR trace id (operator_tpu/obs/, docs/OBSERVABILITY.md).
        # Captured here on the event loop; the blocking call runs in a
        # worker thread where the ambient span is not visible.
        from ..obs import current_traceparent

        traceparent = current_traceparent()
        if traceparent:
            headers["traceparent"] = traceparent
        # idempotency key: a deterministic digest of the rendered prompt,
        # NOT a uuid — at-least-once dispatch (the cross-replica requeue)
        # stays deduplicatable downstream, and a seeded chaos replay
        # produces the identical key
        request_id = request_key(prompt)
        headers["x-podmortem-request-id"] = request_id

        def call(url: str, timeout_s: Optional[float]) -> AIResponse:
            req = urllib.request.Request(
                url, data=payload_bytes, headers=headers, method="POST"
            )
            with self._opener(req, timeout=timeout_s) as resp:
                payload = json.loads(resp.read().decode())
            text = payload["choices"][0]["message"]["content"]
            usage = payload.get("usage", {})
            return AIResponse(
                explanation=text,
                provider_id=config.provider_id,
                model_id=config.model_id,
                prompt_tokens=usage.get("prompt_tokens"),
                completion_tokens=usage.get("completion_tokens"),
                deadline_outcome=(
                    "completed" if request.deadline_s is not None else None
                ),
            )

        async def send(replica: Replica, attempt: int, budget_s: Optional[float]) -> AIResponse:
            # the CR's per-attempt read timeout never reaches past the
            # residual deadline the router hands this attempt
            timeout_s = float(config.timeout_seconds)
            if budget_s is not None:
                timeout_s = min(timeout_s, budget_s)
            if self.fault_plan is not None:
                # apply_async: delay/jitter actions shape provider latency
                # without blocking the loop
                await self.fault_plan.apply_async(
                    "http.provider", attempt=attempt, replica=replica.id
                )
            return await asyncio.to_thread(
                call, _completions_url(replica.url), timeout_s
            )

        # deadline budget: ABSOLUTE across the whole dispatch — retries
        # and cross-replica requeues all spend from one envelope, so
        # retrying a dead backend can never eat more than the residue
        budget = (
            Deadline.start(request.deadline_s)
            if request.deadline_s is not None
            else None
        )
        # affinity: recurrences follow the incident fingerprint (recall
        # caches are per replica), first sightings follow the shared
        # prompt prefix (the prefix-cache reuse unit)
        affinity = EngineRouter.affinity_key(
            prefix=prompt, fingerprint=request.fingerprint
        )
        try:
            outcome = await router.dispatch(
                send,
                key=affinity,
                request_id=request_id,
                deadline=budget,
                attempts=attempts,
                tokens=max_tokens,
            )
        except RouterError as exc:
            deadline_spent = budget is not None and budget.remaining() <= 0.0
            last = exc.last_error
            detail = f": {last}" if last is not None else ""
            return AIResponse(
                error=(
                    f"deadline exceeded during provider dispatch{detail}"
                    if deadline_spent
                    else f"provider failed after retries ({exc}){detail}"
                ),
                provider_id=config.provider_id,
                model_id=config.model_id,
                deadline_outcome="deadline-exceeded" if deadline_spent else None,
                replica_id=exc.tried[-1] if exc.tried else None,
            )
        response: AIResponse = outcome.response
        # the routed replica surfaces in the response metadata — the
        # flight recorder's span attrs and status entries both read it
        response.replica_id = outcome.replica_id
        response.requeues = outcome.requeues
        if degraded and response.explanation and not response.error:
            # the ladder truncated this analysis's depth: a DISTINCT
            # terminal outcome, not conflated with deadline truncation
            response.deadline_outcome = "degraded"
        return response
