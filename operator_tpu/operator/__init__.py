"""The asyncio control plane (SURVEY.md §7 stage 5): watch loop, reconcilers,
event emission, durable storage, git pattern sync, health — the operator half
of the reference, rebuilt around one shared analysis pipeline."""

from .app import Operator
from .events import EventService, truncate_message
from .health import LivenessCheck, ReadinessCheck
from .kubeapi import (
    ApiError,
    ConflictError,
    FakeKubeApi,
    ForbiddenError,
    KubeApi,
    NotFoundError,
    WatchClosed,
    WatchEvent,
)
from .patternsync import GitSyncService, PatternLibraryReconciler, SyncOutcome
from .pipeline import AnalysisPipeline
from .providers import (
    BreakerBoard,
    CircuitBreaker,
    OpenAICompatProvider,
    ProviderError,
    ProviderRegistry,
    ResponseCache,
    TemplateProvider,
    default_registry,
    resolve_provider_config,
)
from .reconciler import AIProviderReconciler, PodmortemReconciler
from .storage import AnalysisStorageService
from .watcher import PodFailureWatcher, PodmortemCache, get_failure_time, has_pod_failed

__all__ = [name for name in dir() if not name.startswith("_")]
