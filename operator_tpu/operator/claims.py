"""Durable analysis claims — the crash-safe successor to the in-memory dedupe.

The pipeline used to claim a ``(pod, failureTime)`` in a process-local map
(``FailureDedupe``): an operator crash or node preemption silently dropped
every in-flight analysis, and a second replica would happily double-analyze
everything the first one already owned.  This module replaces that map with
an append-only JSONL **claim ledger** (same torn-line-tolerant discipline as
``memory/store.py``):

- ``claim`` records carry everything a *successor process* needs to re-run
  the analysis: pod coordinates, failure time, the matched Podmortem refs,
  the claim's total deadline budget, and its wall-clock birth;
- ``stage`` records note coarse progress (which CR is being analyzed) so a
  post-mortem of the ledger shows where a crash landed;
- ``done`` / ``release`` are the terminal transitions (``release`` =
  retryable: the other detection path may claim the failure again).

On startup — or on lease takeover (``operator/lease.py``) — the pipeline
replays the ledger and re-enqueues every NON-terminal claim with its
**remaining** deadline budget (total minus wall-clock elapsed since the
claim was born; wall-clock because monotonic clocks do not survive the
process).  Status patches are idempotent (``operator/storage.py``), so
at-least-once execution of a resumed claim still yields exactly-once
``status.recentFailures`` entries.

``path=None`` keeps the ledger purely in-memory — exactly the old
``FailureDedupe`` semantics — for tests and laptops.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..utils.journal import Journal

log = logging.getLogger(__name__)

_IN_FLIGHT = "in-flight"
_DONE = "done"


@dataclass
class ClaimRecord:
    """One claimed failure: identity + everything a successor needs to
    resume it after a crash."""

    key: str
    pod_name: str = ""
    pod_namespace: str = ""
    failure_time: str = ""
    #: matched Podmortem CRs as "namespace/name" refs — the fan-out a
    #: resumed claim re-runs (a ref deleted since the claim is skipped)
    podmortems: list[str] = field(default_factory=list)
    #: the claim's full deadline envelope; the successor runs with
    #: ``total - (wall_now - claimed_at)`` — the REMAINING budget
    deadline_total_s: float = 0.0
    #: wall-clock birth (epoch seconds): monotonic clocks die with the
    #: process, so cross-process budget arithmetic must be wall-clock
    claimed_at: float = 0.0
    #: coarse progress marker ("analyze:<ns>/<name>") for forensics
    stage: str = ""
    state: str = _IN_FLIGHT

    def to_dict(self) -> dict:
        return {
            "key": self.key,
            "pod_name": self.pod_name,
            "pod_namespace": self.pod_namespace,
            "failure_time": self.failure_time,
            "podmortems": list(self.podmortems),
            "deadline_total_s": self.deadline_total_s,
            "claimed_at": self.claimed_at,
            "stage": self.stage,
        }

    @classmethod
    def parse(cls, data: dict) -> "ClaimRecord":
        return cls(
            key=str(data["key"]),
            pod_name=str(data.get("pod_name") or ""),
            pod_namespace=str(data.get("pod_namespace") or ""),
            failure_time=str(data.get("failure_time") or ""),
            podmortems=[str(p) for p in (data.get("podmortems") or [])],
            deadline_total_s=float(data.get("deadline_total_s") or 0.0),
            claimed_at=float(data.get("claimed_at") or 0.0),
            stage=str(data.get("stage") or ""),
        )


class ClaimLedger:
    """Thread-safe bounded claim map with an optional crash-safe journal.

    The map is an LRU bounded at ``max_entries`` exactly like the old
    dedupe (terminal entries age out; the durable annotation marker in
    etcd remains the long-term dedupe).  Journal compaction rewrites one
    line per live entry via temp-file + ``os.replace`` so a crash
    mid-compaction leaves the old journal intact.
    """

    def __init__(
        self,
        path: Optional[str] = None,
        *,
        max_entries: int = 10_000,
        compact_factor: int = 8,
        wall_clock: Optional[Callable[[], float]] = None,
    ) -> None:
        self.path = path
        self.max_entries = max(1, max_entries)
        self.compact_factor = max(2, compact_factor)
        self._wall = wall_clock or time.time
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, ClaimRecord]" = OrderedDict()
        # shared crash-safe JSONL discipline (utils/journal.py) on a
        # dedicated writer thread (the obs/record.py pattern): stage/done/
        # release transitions and compaction enqueue and return, so the
        # ROUTINE ledger traffic — including every compaction — runs off
        # the event loop and a slow RWX volume no longer stalls the lease
        # renew loop on each transition.  try_claim alone WAITS for its
        # flush (durable-before-analysis, by contract); that one wait can
        # still queue behind an in-flight compaction on severely wedged
        # storage — the residual, rare exposure, down from every-append.
        self._journal = Journal(path, label="claim ledger", async_writes=True)
        #: non-terminal claims found at load: a previous process died while
        #: they were in flight.  Drained (once) by :meth:`take_pending`.
        self._pending: list[ClaimRecord] = []
        if path:
            with self._lock:
                self._load_journal_locked()
                self._journal.open()

    @staticmethod
    def key(pod, failure_time: str) -> str:
        """Same identity as the old ``FailureDedupe.key``."""
        return f"{pod.metadata.namespace}/{pod.metadata.name}@{failure_time}"

    # -- journal (the shared utils/journal.py discipline) ---------------
    def _load_journal_locked(self) -> None:
        self._journal.load(self._replay_locked)
        self._pending = [
            record for record in self._entries.values() if record.state == _IN_FLIGHT
        ]
        if self._pending:
            log.warning(
                "claim ledger %s: %d non-terminal claim(s) from a previous "
                "process await resume", self.path, len(self._pending),
            )

    def _replay_locked(self, record: dict) -> None:
        op = record.get("op")
        if op == "claim":
            claim = ClaimRecord.parse(record["claim"])
            self._entries[claim.key] = claim
            self._entries.move_to_end(claim.key)
        elif op == "stage":
            claim = self._entries.get(record["key"])
            if claim is not None:
                claim.stage = str(record.get("stage") or "")
        elif op == "done":
            claim = self._entries.get(record["key"])
            if claim is not None:
                claim.state = _DONE
        elif op == "release":
            self._entries.pop(record.get("key", ""), None)
        else:
            raise KeyError(f"unknown ledger op {op!r}")

    def _append_locked(self, record: dict, *, wait: bool = False) -> None:
        # the wait=True caller is try_claim's durable-before-analysis
        # write: the claim record MUST hit disk before the analysis
        # starts, or a crash in the gap loses the failure entirely —
        # a deliberate, bounded stall (one fsync) the ledger's contract
        # documents (utils/journal.py module doc)
        self._journal.append(record, wait=wait)  # graftlint: disable=GL006 reason=durable-before-analysis claim write; one bounded fsync by contract
        if self._journal.lines > self.compact_factor * max(len(self._entries), 16):
            self._compact_locked()

    def _compact_locked(self) -> None:
        """One ``claim`` (+ ``done`` for terminal entries, preserving the
        stage marker on the claim record) per live claim — serialized
        under the lock NOW, replaced atomically on the writer thread."""
        records: list[dict] = []
        for claim in self._entries.values():
            records.append({"op": "claim", "claim": claim.to_dict()})
            if claim.state == _DONE:
                records.append({"op": "done", "key": claim.key})
        self._journal.compact(records)

    def close(self) -> None:
        with self._lock:
            self._journal.close()

    def reload(self) -> None:
        """Re-read the journal from disk and reopen the append handle.

        The HA takeover path: a warm standby's ledger was loaded at ITS
        boot, but the claims that matter at takeover are the ones the dead
        leader wrote to the shared journal SINCE — and the leader's
        compaction may have ``os.replace``d the file, which would orphan
        this process's boot-time append handle (appends to the old inode
        are lost).  ``resume_pending`` calls this before draining pending
        claims.  Only safe while this process has no un-journaled
        in-flight claims of its own — exactly the takeover/startup window,
        where the control loops are not running yet."""
        if not self.path:
            return
        with self._lock:
            self._journal.close()
            self._entries.clear()
            self._pending = []
            self._load_journal_locked()
            self._journal.open()

    def abandon(self) -> None:
        """Chaos seam: drop the journal handle WITHOUT terminal records —
        the on-disk state a SIGKILL leaves behind.  Further transitions
        mutate only this process's memory; a successor ledger opened on
        the same path sees the claims exactly as the kill left them."""
        with self._lock:
            self._journal.abandon()

    # -- claim lifecycle ------------------------------------------------
    def try_claim(
        self,
        key: str,
        *,
        pod_name: str = "",
        pod_namespace: str = "",
        failure_time: str = "",
        podmortems: Optional[list[str]] = None,
        deadline_total_s: float = 0.0,
    ) -> bool:
        """Claim the failure for processing; False if already in flight or
        done.  The claim record is durable BEFORE the analysis starts, so
        a crash at any later point leaves a resumable record."""
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                return False
            claim = ClaimRecord(
                key=key,
                pod_name=pod_name,
                pod_namespace=pod_namespace,
                failure_time=failure_time,
                podmortems=list(podmortems or []),
                deadline_total_s=float(deadline_total_s),
                claimed_at=self._wall(),
            )
            self._entries[key] = claim
            while len(self._entries) > self.max_entries:
                evicted_key, _ = self._entries.popitem(last=False)
                # the eviction must reach the journal too: a "claim" line
                # with no terminal op would resurrect as pending at the
                # next load and re-run an arbitrarily stale analysis
                self._append_locked({"op": "release", "key": evicted_key})
            # the ONE write that waits for its flush: the claim record
            # must be durable BEFORE the analysis starts, or a crash in
            # the gap loses the failure entirely
            self._append_locked({"op": "claim", "claim": claim.to_dict()}, wait=True)
            return True

    def note_stage(self, key: str, stage: str) -> None:
        """Coarse progress marker; forensics only (which CR was mid-flight
        when the process died)."""
        with self._lock:
            claim = self._entries.get(key)
            if claim is None:
                return
            claim.stage = stage
            self._append_locked({"op": "stage", "key": key, "stage": stage})

    def mark_done(self, key: str) -> None:
        with self._lock:
            claim = self._entries.get(key)
            if claim is not None:
                claim.state = _DONE
            self._append_locked({"op": "done", "key": key})

    def release(self, key: str) -> None:
        """Forget a failed attempt so either path may retry it."""
        with self._lock:
            self._entries.pop(key, None)
            self._append_locked({"op": "release", "key": key})

    # -- crash-resume ---------------------------------------------------
    def take_pending(self) -> list[ClaimRecord]:
        """Drain the non-terminal claims a previous process left behind
        (oldest first).  Single-shot: the caller owns resuming them; each
        resumed claim ends in ``mark_done``/``release`` as usual."""
        with self._lock:
            pending, self._pending = self._pending, []
            return sorted(pending, key=lambda c: c.claimed_at)

    def remaining_budget_s(self, claim: ClaimRecord) -> float:
        """The claim's residual deadline envelope at resume time."""
        elapsed = max(0.0, self._wall() - claim.claimed_at)
        return max(0.0, claim.deadline_total_s - elapsed)

    # -- introspection --------------------------------------------------
    def get(self, key: str) -> Optional[ClaimRecord]:
        with self._lock:
            return self._entries.get(key)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)
