"""Production Kubernetes API client — stdlib only (http.client + ssl).

Implements the :class:`~operator_tpu.operator.kubeapi.KubeApi` surface
against a real apiserver, the role the reference delegates to the fabric8
client (reference PodFailureWatcher.java:92, AnalysisStorageService.java:339).
No third-party HTTP dependency: unary calls run ``http.client`` on the
asyncio worker-thread pool (the event loop never blocks — the reference's
Mutiny worker-pool discipline, SURVEY.md §5), and watches stream JSON-lines
from a long-lived response, also read off-loop.

Auth/config resolution order (``from_env``):

1. in-cluster: ``KUBERNETES_SERVICE_HOST`` + the serviceaccount token/CA at
   ``/var/run/secrets/kubernetes.io/serviceaccount/`` (what the shipped
   deployment uses — deploy/operator-deployment.yaml);
2. kubeconfig: ``$KUBECONFIG`` or ``~/.kube/config`` — token, basic user
   client-cert, or insecure-skip-tls-verify entries (exec plugins are out
   of scope and raise a clear error).

Status-code mapping matches the fake apiserver so the retry discipline
(409 → re-fetch + retry with backoff, 403 → RBAC warning) behaves
identically in tests and production.
"""

from __future__ import annotations

import asyncio
import base64
import http.client
import json
import logging
import os
import ssl
import tempfile
import urllib.parse
from dataclasses import dataclass
from typing import Any, AsyncIterator, Optional

from ..schema.meta import LabelSelector
from .kubeapi import (
    ApiError,
    ConflictError,
    ForbiddenError,
    KubeApi,
    NotFoundError,
    WatchClosed,
    WatchExpired,
    WatchEvent,
)

log = logging.getLogger(__name__)

SERVICEACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

#: kind -> (api prefix, plural, namespaced)
_KINDS: dict[str, tuple[str, str, bool]] = {
    "Pod": ("/api/v1", "pods", True),
    "Secret": ("/api/v1", "secrets", True),
    "ConfigMap": ("/api/v1", "configmaps", True),
    "Endpoints": ("/api/v1", "endpoints", True),
    "Service": ("/api/v1", "services", True),
    "Event": ("/apis/events.k8s.io/v1", "events", True),
    "ReplicaSet": ("/apis/apps/v1", "replicasets", True),
    "Deployment": ("/apis/apps/v1", "deployments", True),
    "Lease": ("/apis/coordination.k8s.io/v1", "leases", True),
    "Podmortem": ("/apis/podmortem.tpu.dev/v1alpha1", "podmortems", True),
    "AIProvider": ("/apis/podmortem.tpu.dev/v1alpha1", "aiproviders", True),
    "PatternLibrary": ("/apis/podmortem.tpu.dev/v1alpha1", "patternlibraries", True),
}


def _selector_string(selector: Optional[LabelSelector]) -> Optional[str]:
    """LabelSelector -> apiserver ``labelSelector`` query value."""
    if selector is None or selector.is_empty():
        return None
    parts = [f"{k}={v}" for k, v in sorted(selector.match_labels.items())]
    for req in selector.match_expressions:
        op = (req.operator or "").lower()
        values = ",".join(req.values or [])
        if op == "in":
            parts.append(f"{req.key} in ({values})")
        elif op == "notin":
            parts.append(f"{req.key} notin ({values})")
        elif op == "exists":
            parts.append(f"{req.key}")
        elif op == "doesnotexist":
            parts.append(f"!{req.key}")
    return ",".join(parts)


def _raise_for_status(status: int, body: bytes, context: str) -> None:
    if status < 400:
        return
    try:
        message = json.loads(body).get("message", body.decode(errors="replace"))
    except (ValueError, AttributeError):
        message = body.decode(errors="replace")[:300]
    detail = f"{context}: {message}"
    if status == 404:
        raise NotFoundError(detail)
    if status == 409:
        raise ConflictError(detail)
    if status == 403:
        raise ForbiddenError(detail)
    raise ApiError(detail, status=status)


@dataclass
class ClusterConfig:
    host: str
    port: int
    token: Optional[str] = None
    ca_file: Optional[str] = None
    client_cert_file: Optional[str] = None
    client_key_file: Optional[str] = None
    verify_tls: bool = True
    scheme: str = "https"
    namespace: str = "default"

    def ssl_context(self) -> Optional[ssl.SSLContext]:
        if self.scheme != "https":
            return None
        if self.verify_tls:
            context = ssl.create_default_context(cafile=self.ca_file)
        else:
            context = ssl._create_unverified_context()  # noqa: S323 - explicit opt-in
        if self.client_cert_file:
            context.load_cert_chain(self.client_cert_file, self.client_key_file)
        return context


def load_incluster_config(sa_dir: str = SERVICEACCOUNT_DIR) -> ClusterConfig:
    host = os.environ.get("KUBERNETES_SERVICE_HOST")
    port = int(os.environ.get("KUBERNETES_SERVICE_PORT", "443"))
    if not host:
        raise ApiError("KUBERNETES_SERVICE_HOST not set: not running in-cluster")
    with open(os.path.join(sa_dir, "token")) as f:
        token = f.read().strip()
    namespace = "default"
    ns_path = os.path.join(sa_dir, "namespace")
    if os.path.exists(ns_path):
        with open(ns_path) as f:
            namespace = f.read().strip() or "default"
    ca = os.path.join(sa_dir, "ca.crt")
    return ClusterConfig(
        host=host, port=port, token=token,
        ca_file=ca if os.path.exists(ca) else None,
        namespace=namespace,
    )


def load_kubeconfig(path: Optional[str] = None) -> ClusterConfig:
    """Minimal kubeconfig support: current-context -> cluster + user with
    token / client-cert / basic fields.  Exec credential plugins raise."""
    import yaml

    path = path or os.environ.get("KUBECONFIG") or os.path.expanduser("~/.kube/config")
    with open(path) as f:
        doc = yaml.safe_load(f)
    contexts = {c["name"]: c["context"] for c in doc.get("contexts", [])}
    current = doc.get("current-context")
    if current not in contexts:
        raise ApiError(f"kubeconfig {path}: current-context {current!r} not found")
    ctx = contexts[current]
    clusters = {c["name"]: c["cluster"] for c in doc.get("clusters", [])}
    users = {u["name"]: u["user"] for u in doc.get("users", [])}
    cluster = clusters[ctx["cluster"]]
    user = users.get(ctx.get("user", ""), {})
    if "exec" in user:
        raise ApiError("kubeconfig exec credential plugins are not supported")

    url = urllib.parse.urlparse(cluster["server"])
    config = ClusterConfig(
        host=url.hostname or "localhost",
        port=url.port or (443 if url.scheme == "https" else 80),
        scheme=url.scheme or "https",
        namespace=ctx.get("namespace", "default"),
        verify_tls=not cluster.get("insecure-skip-tls-verify", False),
    )

    def materialize(data_key: str, file_key: str, source: dict) -> Optional[str]:
        if source.get(file_key):
            return source[file_key]
        if source.get(data_key):
            blob = base64.b64decode(source[data_key])
            handle = tempfile.NamedTemporaryFile(delete=False, suffix=".pem")
            handle.write(blob)
            handle.close()
            return handle.name
        return None

    config.ca_file = materialize("certificate-authority-data", "certificate-authority", cluster)
    config.client_cert_file = materialize("client-certificate-data", "client-certificate", user)
    config.client_key_file = materialize("client-key-data", "client-key", user)
    config.token = user.get("token")
    return config


class HttpKubeApi(KubeApi):
    """KubeApi over HTTP(S) to a real apiserver."""

    #: slack past the server-side watch timeout before declaring the
    #: socket half-open (server close should always arrive first)
    _WATCH_SOCKET_MARGIN_S = 30.0

    def __init__(
        self,
        config: ClusterConfig,
        *,
        request_timeout_s: float = 30.0,
        watch_timeout_s: float = 300.0,
    ) -> None:
        self.config = config
        self.request_timeout_s = request_timeout_s
        self.watch_timeout_s = watch_timeout_s
        self._ssl = config.ssl_context()

    # -- construction ---------------------------------------------------
    @classmethod
    def from_env(cls) -> "HttpKubeApi":
        if os.environ.get("KUBERNETES_SERVICE_HOST"):
            return cls(load_incluster_config())
        return cls(load_kubeconfig())

    @property
    def namespace(self) -> str:
        return self.config.namespace

    # -- plumbing -------------------------------------------------------
    def _path(self, kind: str, namespace: Optional[str], name: Optional[str] = None,
              subresource: Optional[str] = None) -> str:
        try:
            prefix, plural, namespaced = _KINDS[kind]
        except KeyError:
            raise ApiError(f"unknown kind {kind!r}") from None
        path = prefix
        if namespaced and namespace:
            path += f"/namespaces/{urllib.parse.quote(namespace)}"
        path += f"/{plural}"
        if name:
            path += f"/{urllib.parse.quote(name)}"
        if subresource:
            path += f"/{subresource}"
        return path

    _UNSET: Any = object()

    def _connect(self, timeout: Any = _UNSET) -> http.client.HTTPConnection:
        # explicit None means "no timeout" (blocking socket) — what a watch
        # stream needs; only an omitted argument falls back to the default
        if timeout is HttpKubeApi._UNSET:
            timeout = self.request_timeout_s
        if self.config.scheme == "https":
            return http.client.HTTPSConnection(
                self.config.host, self.config.port, timeout=timeout, context=self._ssl
            )
        return http.client.HTTPConnection(self.config.host, self.config.port, timeout=timeout)

    def _headers(self, content_type: Optional[str] = None) -> dict[str, str]:
        headers = {"Accept": "application/json", "User-Agent": "operator-tpu"}
        if self.config.token:
            headers["Authorization"] = f"Bearer {self.config.token}"
        if content_type:
            headers["Content-Type"] = content_type
        return headers

    def _request_sync(
        self, method: str, path: str, body: Optional[dict] = None,
        *, content_type: str = "application/json",
    ) -> tuple[int, bytes]:
        conn = self._connect()
        try:
            conn.request(
                method, path,
                body=json.dumps(body).encode() if body is not None else None,
                headers=self._headers(content_type if body is not None else None),
            )
            response = conn.getresponse()
            return response.status, response.read()
        finally:
            conn.close()

    async def _request(
        self, method: str, path: str, body: Optional[dict] = None,
        *, content_type: str = "application/json",
    ) -> dict:
        status, payload = await asyncio.to_thread(
            self._request_sync, method, path, body, content_type=content_type
        )
        _raise_for_status(status, payload, f"{method} {path}")
        return json.loads(payload) if payload else {}

    # -- KubeApi surface ------------------------------------------------
    async def get(self, kind: str, name: str, namespace: str) -> dict:
        return await self._request("GET", self._path(kind, namespace, name))

    async def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[LabelSelector] = None,
    ) -> list[dict]:
        items, _ = await self.list_rv(kind, namespace, label_selector)
        return items

    async def list_rv(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[LabelSelector] = None,
    ) -> tuple[list[dict], Optional[str]]:
        path = self._path(kind, namespace)
        selector = _selector_string(label_selector)
        if selector:
            path += "?" + urllib.parse.urlencode({"labelSelector": selector})
        body = await self._request("GET", path)
        items = body.get("items", [])
        for item in items:  # items omit kind/apiVersion; restore for callers
            item.setdefault("kind", kind)
        version = (body.get("metadata") or {}).get("resourceVersion")
        return items, version

    async def create(self, kind: str, obj: dict) -> dict:
        namespace = obj.get("metadata", {}).get("namespace") or self.config.namespace
        return await self._request("POST", self._path(kind, namespace), obj)

    async def _patch(
        self, kind: str, name: str, namespace: str, patch: dict,
        *, resource_version: Optional[str], subresource: Optional[str],
    ) -> dict:
        if resource_version is not None:
            patch = dict(patch)
            meta = dict(patch.get("metadata", {}))
            meta["resourceVersion"] = resource_version  # 409 on mismatch
            patch["metadata"] = meta
        return await self._request(
            "PATCH",
            self._path(kind, namespace, name, subresource),
            patch,
            content_type="application/merge-patch+json",
        )

    async def patch(
        self, kind: str, name: str, namespace: str, patch: dict,
        *, resource_version: Optional[str] = None,
    ) -> dict:
        return await self._patch(
            kind, name, namespace, patch,
            resource_version=resource_version, subresource=None,
        )

    async def patch_status(
        self, kind: str, name: str, namespace: str, status: dict,
        *, resource_version: Optional[str] = None,
    ) -> dict:
        return await self._patch(
            kind, name, namespace, {"status": status},
            resource_version=resource_version, subresource="status",
        )

    async def delete(self, kind: str, name: str, namespace: str) -> None:
        await self._request("DELETE", self._path(kind, namespace, name))

    async def get_scale(self, kind: str, name: str, namespace: str) -> dict:
        return await self._request(
            "GET", self._path(kind, namespace, name, "scale")
        )

    async def patch_scale(
        self,
        kind: str,
        name: str,
        namespace: str,
        replicas: int,
        *,
        resource_version: Optional[str] = None,
    ) -> dict:
        patch: dict = {"spec": {"replicas": int(replicas)}}
        if resource_version is not None:
            patch["metadata"] = {"resourceVersion": resource_version}
        return await self._request(
            "PATCH",
            self._path(kind, namespace, name, "scale"),
            patch,
            content_type="application/merge-patch+json",
        )

    async def get_log(
        self,
        name: str,
        namespace: str,
        *,
        container: Optional[str] = None,
        previous: bool = False,
        tail_bytes: Optional[int] = None,
    ) -> str:
        query: dict[str, str] = {}
        if container:
            query["container"] = container
        if previous:
            query["previous"] = "true"
        if tail_bytes:
            query["limitBytes"] = str(tail_bytes)
        path = self._path("Pod", namespace, name, "log")
        if query:
            path += "?" + urllib.parse.urlencode(query)
        status, payload = await asyncio.to_thread(self._request_sync, "GET", path)
        _raise_for_status(status, payload, f"GET {path}")
        return payload.decode(errors="replace")

    # -- watch ----------------------------------------------------------
    async def watch(
        self,
        kind: str,
        namespace: Optional[str] = None,
        resource_version: Optional[str] = None,
    ) -> AsyncIterator[WatchEvent]:
        """Stream ADDED/MODIFIED/DELETED/BOOKMARK events as JSON-lines.

        With ``resource_version`` the stream resumes from that point
        (list+watch: pass the list's collection resourceVersion and no
        event between the list and the watch is lost — the informer
        discipline of the fabric8 client the reference runs on,
        PodFailureWatcher.java:92).  Bookmarks are requested so callers
        can refresh their cursor from quiet streams.  A compacted cursor
        raises :class:`WatchExpired` (HTTP 410 / ERROR-410 event): relist
        before watching again.  Other server closes raise
        :class:`WatchClosed` so the caller's restart-after-5s loop engages
        (reference PodFailureWatcher.java:562-583).
        """
        # the apiserver ends the watch after timeoutSeconds (clean close ->
        # reconnect); the socket timeout is the backstop for HALF-OPEN
        # connections (node reboot, LB idle drop without FIN) which would
        # otherwise block readline in its worker thread forever and
        # silently stop failure detection — the fabric8 client the
        # reference relies on keeps watches live the same two ways
        query = {
            "watch": "true",
            "allowWatchBookmarks": "true",
            "timeoutSeconds": str(int(self.watch_timeout_s)),
        }
        if resource_version is not None:
            query["resourceVersion"] = resource_version
        path = self._path(kind, namespace) + "?" + urllib.parse.urlencode(query)
        conn = self._connect(timeout=self.watch_timeout_s + self._WATCH_SOCKET_MARGIN_S)

        def open_stream() -> Any:
            conn.request("GET", path, headers=self._headers())
            return conn.getresponse()

        try:
            try:
                response = await asyncio.to_thread(open_stream)
            except (TimeoutError, OSError) as exc:
                raise WatchClosed(f"watch open for {kind} failed: {exc}") from exc
            if response.status == 410:
                raise WatchExpired(
                    f"watch resume for {kind} at resourceVersion "
                    f"{resource_version!r} expired (410 Gone)"
                )
            if response.status >= 400:
                payload = await asyncio.to_thread(response.read)
                _raise_for_status(response.status, payload, f"WATCH {path}")
            while True:
                try:
                    line = await asyncio.to_thread(response.readline)
                except (TimeoutError, OSError) as exc:  # dead-peer socket timeout
                    raise WatchClosed(f"watch stream for {kind} timed out: {exc}") from exc
                if not line:
                    raise WatchClosed(f"watch stream for {kind} closed by server")
                line = line.strip()
                if not line:
                    continue
                try:
                    event = json.loads(line)
                except ValueError:
                    log.warning("unparseable watch line for %s: %.120r", kind, line)
                    continue
                event_type = event.get("type", "")
                if event_type == "ERROR":
                    obj = event.get("object") or {}
                    if obj.get("code") == 410:
                        # etcd compacted past the resume cursor: the
                        # caller must relist, not merely reconnect
                        raise WatchExpired(
                            f"watch resume for {kind} expired: "
                            f"{obj.get('message', '410 Gone')}"
                        )
                    raise WatchClosed(f"watch error for {kind}: {obj}")
                obj = event.get("object", {})
                obj.setdefault("kind", kind)
                # BOOKMARK events flow through: the caller refreshes its
                # resume cursor from object.metadata.resourceVersion
                yield WatchEvent(type=event_type, object=obj)
        finally:
            await asyncio.to_thread(conn.close)
