"""Durable result storage — pod annotations + Podmortem CR status history.

Behavioural parity with the reference's AnalysisStorageService:

- annotation keys ``podmortem.io/{analysis,severity,analyzed-at,monitor}``
  (reference AnalysisStorageService.java:42-46);
- full AI text stored when present, else the pattern summary line
  (:142-156);
- Podmortem ``status.recentFailures`` is a newest-first ring capped at 10
  (:48,286-333);
- optimistic-concurrency discipline: re-fetch latest, patch with its
  resourceVersion, on 409 retry up to 5 times with 100ms*2^n backoff
  (:74-76,179-187); 403 logs an RBAC warning and gives up (:188-193).

Unlike the reference — where the reconciler injects this service but never
calls it (PodmortemReconciler.java:50, SURVEY.md §3.3) — both detection
paths here share one pipeline, so poll-path results are stored too.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from ..schema.analysis import AIResponse, AnalysisResult
from ..schema.crds import FailureRecurrence, PodFailureStatus, Podmortem
from ..schema.kube import Pod
from ..schema.meta import now_iso
from ..schema.serde import to_dict
from ..utils.config import OperatorConfig
from .kubeapi import ApiError, ConflictError, ForbiddenError, KubeApi, NotFoundError

log = logging.getLogger(__name__)

ANNOTATION_ANALYSIS = "podmortem.io/analysis"
ANNOTATION_SEVERITY = "podmortem.io/severity"
ANNOTATION_ANALYZED_AT = "podmortem.io/analyzed-at"
#: which failure (finishedAt) the stored analysis covers — the DURABLE
#: dedupe marker: even when the claim ledger (operator/claims.py) is
#: in-memory or freshly rotated, this annotation in etcd stops a restarted
#: watcher/reconciler from re-analyzing an already-annotated failure (the
#: reference accepts re-analysis after restart by design,
#: AnalysisStorageService.java:42-46; we do one better)
ANNOTATION_ANALYZED_FAILURE = "podmortem.io/analyzed-failure"
ANNOTATION_MONITOR = "podmortem.io/monitor"

#: the apiserver rejects objects whose TOTAL annotation BYTES exceed
#: 256 KiB (TotalAnnotationSizeLimitB); whatever the configured char cap
#: says, never let one analysis text get near it — a rejected patch loses
#: the whole store, a truncated text loses only its tail.  Enforced in
#: bytes because that is what the apiserver counts (CJK / box-drawing
#: evidence encodes at 3-4 bytes per char).
HARD_ANNOTATION_CEILING_BYTES = 240 * 1024

#: explicit truncation marker — a reader (or a tool diffing two stored
#: analyses) must be able to tell "short analysis" from "cap applied"
TRUNCATION_MARKER = "…[truncated]"


def truncate_marked(text: str, cap: int, *, max_bytes: Optional[int] = None) -> str:
    """Truncate ``text`` to at most ``cap`` chars — and, when ``max_bytes``
    is given, at most that many UTF-8 bytes — replacing the tail with an
    explicit marker when anything was cut.  Deterministic (equal inputs
    give byte-equal outputs — incident-memory reuse depends on it)."""
    out = text
    if 0 < cap < len(out):
        if cap <= len(TRUNCATION_MARKER):
            return TRUNCATION_MARKER[:cap]
        out = out[: cap - len(TRUNCATION_MARKER)] + TRUNCATION_MARKER
    if max_bytes is not None and len(out.encode("utf-8")) > max_bytes:
        budget = max(0, max_bytes - len(TRUNCATION_MARKER.encode("utf-8")))
        head = out.encode("utf-8")[:budget].decode("utf-8", errors="ignore")
        out = head + TRUNCATION_MARKER
    return out


class AnalysisStorageService:
    def __init__(self, api: KubeApi, config: Optional[OperatorConfig] = None) -> None:
        self.api = api
        self.config = config or OperatorConfig()

    # ------------------------------------------------------------------
    async def store_analysis_results(
        self,
        result: AnalysisResult,
        ai_response: Optional[AIResponse],
        pod: Pod,
        podmortem: Podmortem,
        *,
        failure_time: Optional[str] = None,
        recurrence: Optional[FailureRecurrence] = None,
        trace_id: Optional[str] = None,
    ) -> None:
        """Store to both places; failures in one must not block the other
        (reference stores annotations first, then status :60-68).
        ``trace_id`` links the status entry to its flight-recorder trace
        (GET /traces/{id}, docs/OBSERVABILITY.md)."""
        explanation = self._explanation_text(result, ai_response)
        # the durable marker is only earned by a FINAL result: AI succeeded,
        # or AI was never requested (pattern-only is the intended outcome).
        # A degraded store (AI errored / provider refused) must stay
        # re-analyzable — e.g. the checkpoint gets mounted and the operator
        # restarts; stamping the marker then would suppress the retry forever
        final = ai_response is None or bool(ai_response.explanation)
        await self.store_to_pod_annotations(
            pod, result, explanation, failure_time=failure_time if final else None
        )
        await self.store_to_podmortem_status(
            podmortem, pod, result, ai_response, explanation,
            failure_time=failure_time, recurrence=recurrence,
            trace_id=trace_id,
        )

    @staticmethod
    def _explanation_text(result: AnalysisResult, ai_response: Optional[AIResponse]) -> str:
        if ai_response is not None and ai_response.explanation:
            return ai_response.explanation
        return result.pattern_summary_line()

    # ------------------------------------------------------------------
    async def store_to_pod_annotations(
        self,
        pod: Pod,
        result: AnalysisResult,
        explanation: str,
        *,
        failure_time: Optional[str] = None,
    ) -> bool:
        annotations = {
            ANNOTATION_ANALYSIS: truncate_marked(
                explanation, self.config.max_annotation_chars,
                max_bytes=HARD_ANNOTATION_CEILING_BYTES,
            ),
            ANNOTATION_SEVERITY: (result.summary.highest_severity or "NONE"),
            ANNOTATION_ANALYZED_AT: now_iso(),
        }
        if failure_time:
            annotations[ANNOTATION_ANALYZED_FAILURE] = failure_time

        async def attempt() -> bool:
            # each apiserver call bounded by the control-loop budget
            # (kube_call_timeout_s, graftlint GL003): a wedged connection
            # costs one bounded attempt, not the pipeline forever
            latest = await asyncio.wait_for(
                self.api.get("Pod", pod.metadata.name, pod.metadata.namespace),
                timeout=self.config.kube_call_timeout_s,
            )
            rv = latest.get("metadata", {}).get("resourceVersion")
            await asyncio.wait_for(
                self.api.patch(
                    "Pod",
                    pod.metadata.name,
                    pod.metadata.namespace,
                    {"metadata": {"annotations": annotations}},
                    resource_version=rv,
                ),
                timeout=self.config.kube_call_timeout_s,
            )
            return True

        return await self._with_conflict_retry(
            attempt, what=f"pod annotations {pod.qualified_name()}"
        )

    # ------------------------------------------------------------------
    async def store_to_podmortem_status(
        self,
        podmortem: Podmortem,
        pod: Pod,
        result: AnalysisResult,
        ai_response: Optional[AIResponse],
        explanation: str,
        *,
        failure_time: Optional[str] = None,
        recurrence: Optional[FailureRecurrence] = None,
        trace_id: Optional[str] = None,
    ) -> bool:
        if ai_response is not None and ai_response.explanation:
            analysis_status = "Analyzed"
        elif ai_response is not None and ai_response.error:
            analysis_status = "Failed"
        else:
            analysis_status = "PatternOnly"
        deadline_outcome = ai_response.deadline_outcome if ai_response else None
        if deadline_outcome == "deadline-exceeded":
            # the budget — not the backend — killed the AI leg; operators
            # alert on this string (and podmortem_deadline_exceeded_total)
            analysis_status = "deadline-exceeded"
        elif deadline_outcome == "degraded":
            # the overload ladder truncated analysis depth but the leg
            # still produced text — a DISTINCT terminal status, not a
            # deadline miss (podmortem_deadline_degraded_total)
            analysis_status = "degraded"
        entry = PodFailureStatus(
            pod_name=pod.metadata.name,
            pod_namespace=pod.metadata.namespace,
            failure_time=failure_time or now_iso(),
            analysis_status=analysis_status,
            explanation=truncate_marked(
                explanation, self.config.max_status_explanation_chars
            ),
            severity=result.summary.highest_severity,
            deadline_outcome=deadline_outcome,
            recurrence=recurrence,
            trace_id=trace_id,
        )

        async def attempt() -> bool:
            latest = await asyncio.wait_for(
                self.api.get(
                    "Podmortem", podmortem.metadata.name, podmortem.metadata.namespace
                ),
                timeout=self.config.kube_call_timeout_s,
            )
            rv = latest.get("metadata", {}).get("resourceVersion")
            status = latest.get("status") or {}
            existing = list(status.get("recentFailures") or [])
            # IDEMPOTENT store: at-least-once execution (crash-resume,
            # operator/claims.py — a claim that died after storing replays)
            # must yield exactly-once status entries.  Identity is
            # (pod, failureTime) — the same triple that keys the claim.
            payload = to_dict(entry)
            duplicate_index: Optional[int] = None
            for i, prior in enumerate(existing):
                if (
                    prior.get("podName") == entry.pod_name
                    and prior.get("podNamespace") == entry.pod_namespace
                    and prior.get("failureTime") == entry.failure_time
                ):
                    duplicate_index = i
                    break
            if duplicate_index is not None:
                prior = existing[duplicate_index]
                if prior.get("traceId") and prior.get("traceId") == entry.trace_id:
                    # the SAME analysis already landed (a retried patch whose
                    # first attempt actually succeeded): nothing to write
                    return True
                # a resumed analysis supersedes the partial entry in place —
                # replace, never append, so the ring holds one entry per
                # failure no matter how many times the claim replays
                existing[duplicate_index] = payload
                failures = existing
            else:
                failures = [payload] + existing
            failures = failures[: self.config.max_recent_failures]  # ring of 10
            status.update(
                {
                    "recentFailures": failures,
                    "lastUpdateTime": now_iso(),
                }
            )
            await asyncio.wait_for(
                self.api.patch_status(
                    "Podmortem",
                    podmortem.metadata.name,
                    podmortem.metadata.namespace,
                    status,
                    resource_version=rv,
                ),
                timeout=self.config.kube_call_timeout_s,
            )
            return True

        return await self._with_conflict_retry(
            attempt, what=f"podmortem status {podmortem.qualified_name()}"
        )

    # ------------------------------------------------------------------
    async def _with_conflict_retry(self, attempt, what: str) -> bool:
        """Re-fetch + patch, retrying 409s with exponential backoff
        (reference :74-76,179-193)."""
        retries = self.config.conflict_max_retries
        for i in range(retries):
            try:
                return await attempt()
            except ConflictError:
                if i == retries - 1:
                    log.error("giving up storing %s after %d conflicts", what, retries)
                    return False
                delay = self.config.conflict_backoff_base_s * (2**i)
                log.debug("409 storing %s; retry %d in %.0fms", what, i + 1, delay * 1e3)
                await asyncio.sleep(delay)
            except ForbiddenError as exc:
                log.warning(
                    "RBAC forbids storing %s (%s); grant patch on the target resource", what, exc
                )
                return False
            except NotFoundError:
                log.info("target of %s is gone; skipping storage", what)
                return False
            except asyncio.TimeoutError:
                # the per-call kube budget (kube_call_timeout_s) expired:
                # storing is best-effort — give up on this attempt rather
                # than let a wedged apiserver stall the pipeline
                log.error("timed out storing %s (kube_call_timeout_s)", what)
                return False
            except ApiError as exc:
                log.error("failed storing %s: %s", what, exc)
                return False
        return False
