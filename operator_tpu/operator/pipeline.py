"""The failure-analysis pipeline: collect -> parse -> explain -> store -> emit.

One implementation shared by the real-time watcher and the poll-path
reconciler — the consolidation SURVEY.md §3.3 calls out (the reference
duplicates ~200 LoC between PodFailureWatcher and PodmortemReconciler, and
the reconcile path never stores results; here both paths store).

Graceful degradation mirrors the reference (SURVEY.md §5 failure-detection
entry): parse failure => error event + status; AI failure => pattern-only
result still stored; provider missing => stored without AI.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Callable, Optional

from ..memory import (
    RECALL_HIT,
    RECALL_NEAR,
    IncidentMemory,
    RecallDecision,
    build_incident_memory,
)
from ..obs import SLOLedger, Span, Tracer, annotate_root, parse_slo_classes, stage_durations
from ..obs.sloledger import SLO_OUTCOME_ATTR
from ..patterns.engine import PatternEngine
from ..schema.analysis import (
    AIResponse,
    AnalysisRequest,
    AnalysisResult,
    PodFailureData,
    PriorIncident,
)
from ..schema.crds import AIProvider, FailureRecurrence, Podmortem, parse_refresh_interval
from ..schema.kube import Event as KubeEvent
from ..schema.kube import Pod
from ..schema.meta import now_iso
from ..utils.config import OperatorConfig
from ..utils.deadline import Deadline
from ..utils.timing import METRICS, MetricsRegistry
from .claims import ClaimLedger, ClaimRecord
from .events import EventService
from .kubeapi import ApiError, KubeApi, NotFoundError
from .providers import (
    BreakerBoard,
    ProviderError,
    ProviderRegistry,
    ResponseCache,
    default_registry,
    resolve_provider_config,
)
from .storage import AnalysisStorageService

log = logging.getLogger(__name__)




class AnalysisPipeline:
    def __init__(
        self,
        api: KubeApi,
        engine: PatternEngine,
        *,
        config: Optional[OperatorConfig] = None,
        events: Optional[EventService] = None,
        storage: Optional[AnalysisStorageService] = None,
        providers: Optional[ProviderRegistry] = None,
        metrics: Optional[MetricsRegistry] = None,
        clock: Optional[Callable[[], float]] = None,
        memory: Optional[IncidentMemory] = None,
        tracer: Optional[Tracer] = None,
        claims: Optional[ClaimLedger] = None,
        slo_ledger: Optional[SLOLedger] = None,
        overload_policy: Optional[Any] = None,
    ) -> None:
        self.api = api
        self.engine = engine
        self.config = config or OperatorConfig()
        self.events = events or EventService(api, self.config)
        self.storage = storage or AnalysisStorageService(api, self.config)
        self.providers = providers or default_registry()
        self.metrics = metrics or METRICS
        self.cache = ResponseCache()
        # the claim map shared by the watcher and the poll-path reconciler —
        # one analysis per distinct (pod, failureTime), like the reference's
        # ``processedFailures`` map (PodFailureWatcher.java:50,180-193) but
        # (a) shared by both detection paths, (b) bounded, (c) retry-aware,
        # and (d) DURABLE when config.claims_path is set: claims journal to
        # a crash-safe ledger, and a restarted (or newly elected,
        # operator/lease.py) operator resumes non-terminal analyses with
        # their remaining deadline budget (resume_pending).  Injectable so
        # chaos tests drive the wall clock (tests/test_leader.py).
        self.claims = claims if claims is not None else ClaimLedger(
            self.config.claims_path,
            max_entries=self.config.claims_max_entries,
        )
        # incident memory (docs/MEMORY.md): recall across failures so a
        # recurring class pays the TPU decode once, not once per pod.
        # Injectable; the default honours config.memory_enabled.
        self.memory = memory if memory is not None else build_incident_memory(self.config)
        # per-analysis tracing + flight recorder (operator_tpu/obs/,
        # docs/OBSERVABILITY.md): every analysis produces a span tree;
        # deadline-exceeded / breaker-open / engine-error analyses dump a
        # black box.  Injectable; the default is the process-wide tracer.
        if tracer is not None:
            self.tracer = tracer
        else:
            from ..obs import TRACER

            self.tracer = TRACER
        # deadline budgets + per-provider circuit breakers share one
        # injectable clock so chaos tests replay deterministically
        self._clock = clock or time.monotonic
        # SLO ledger (obs/sloledger.py, docs/OBSERVABILITY.md "SLO
        # ledger"): every analysis is admitted under a class + latency
        # target at trace birth and settled in process_pod_failure's
        # finally — completed / deadline-exceeded / shed / failed, exactly
        # once per analysis.  Shares the pipeline clock so chaos replays
        # produce identical ledgers.
        self.slo_ledger = slo_ledger if slo_ledger is not None else SLOLedger(
            parse_slo_classes(self.config.slo_classes),
            path=self.config.slo_ledger_path or None,
            metrics=self.metrics,
            clock=self._clock,
        )
        self.breakers = BreakerBoard(
            self.config.breaker_failure_threshold,
            self.config.breaker_reset_s,
            clock=self._clock,
        )
        # value-aware overload ladder (router/value.py, docs/ROBUSTNESS.md
        # "Degradation ladder"): ONE model shared by every shed site —
        # the router's pre-dispatch verdict, the scheduler's queue
        # eviction, and admission's degrade clamp — fed live per-class
        # attainment from the SLO ledger so the class already below its
        # target is never shed.  Injectable for tests; the default builds
        # from config knobs.
        if overload_policy is not None:
            self.overload_policy = overload_policy
        else:
            from ..router.value import OverloadPolicy, ValueModel

            self.overload_policy = OverloadPolicy(
                ValueModel(
                    parse_slo_classes(self.config.slo_classes),
                    attainment=self.slo_ledger.attainment_by_class,
                    attainment_target=self.config.slo_attainment_target,
                ),
                shed_pressure=self.config.shed_pressure,
                degrade_pressure=(
                    self.config.degrade_pressure
                    if self.config.degrade_pressure > 0 else None
                ),
                degrade_tokens_frac=self.config.degrade_max_tokens_frac,
                shed_value_floor=self.config.shed_value_floor,
                metrics=self.metrics,
            )
        # hand the ladder to every provider that routes dispatches
        # (OpenAICompatProvider.router_for stamps it onto its router)
        for provider in getattr(self.providers, "_providers", {}).values():
            if hasattr(provider, "overload_policy"):
                provider.overload_policy = self.overload_policy

    def _deadline_total_for(self, podmortem: Podmortem) -> float:
        """One CR's full envelope in seconds: spec.analysisDeadline when
        set, else the operator default (the reference's 180 s LLM budget)."""
        total_s = self.config.analysis_deadline_s
        if podmortem.spec.analysis_deadline:
            total_s = float(parse_refresh_interval(
                podmortem.spec.analysis_deadline,
                default_seconds=int(self.config.analysis_deadline_s),
            ))
        return total_s

    def _deadline_for(self, podmortem: Podmortem) -> Deadline:
        """One CR's analysis envelope, born NOW.  PER CR — a fan-out
        group's first analysis legitimately spending its whole envelope
        must not starve the remaining CRs down to zero-budget no-result
        runs."""
        return Deadline.start(self._deadline_total_for(podmortem), clock=self._clock)

    # ------------------------------------------------------------------
    async def process_failure_group(
        self,
        pod: Pod,
        podmortems: list[Podmortem],
        *,
        failure_time: str,
    ) -> list[Optional[AnalysisResult]]:
        """Claim one (pod, failureTime) and fan out one pipeline per matching
        CR (reference fans out per CR, PodFailureWatcher.java:196-199).
        Returns [] if the failure was already claimed.  A fully failed group
        releases the claim so the other detection path can retry it."""
        key = ClaimLedger.key(pod, failure_time)
        # the claim record carries everything a SUCCESSOR process needs to
        # resume this analysis if we die mid-flight: pod coordinates, the
        # matched CR refs, and the largest per-CR envelope (resume clamps
        # each CR to what is left of it)
        if not self.claims.try_claim(
            key,
            pod_name=pod.metadata.name or "",
            pod_namespace=pod.metadata.namespace or "",
            failure_time=failure_time,
            podmortems=[pm.qualified_name() for pm in podmortems],
            deadline_total_s=max(
                (self._deadline_total_for(pm) for pm in podmortems), default=0.0
            ),
        ):
            return []
        # durable dedupe: the claim ledger may be fresh (or in-memory), but
        # the analyzed-failure annotation is in etcd — a restarted operator
        # (or the pre-watch sweep) must not re-analyze an annotated failure
        from .storage import ANNOTATION_ANALYZED_FAILURE

        if pod.metadata.annotations.get(ANNOTATION_ANALYZED_FAILURE) == failure_time:
            self.claims.mark_done(key)
            self.metrics.incr("dedupe_durable_hits")
            return []
        # each CR's deadline budget is BORN when its analysis starts under
        # this claim: collection, parse, AI — one envelope per CR (the
        # fan-out is sequential, so a shared group envelope would hand
        # later CRs whatever the first one left, possibly nothing)
        try:
            results = []
            for podmortem in podmortems:
                self.claims.note_stage(key, f"analyze:{podmortem.qualified_name()}")
                results.append(
                    await self.process_pod_failure(
                        pod, podmortem, failure_time=failure_time,
                        deadline=self._deadline_for(podmortem),
                    )
                )
        except BaseException:
            self.claims.release(key)
            raise
        if any(result is not None for result in results):
            self.claims.mark_done(key)
        else:
            self.claims.release(key)
        return results

    # ------------------------------------------------------------------
    async def resume_pending(self) -> int:
        """Crash-resume: re-run every non-terminal claim a previous process
        — or the previous LEADER, on lease takeover — left in the ledger.
        Each analysis restarts with the claim's REMAINING wall-clock budget
        (a claim 50 s into a 180 s envelope resumes with ~130 s).  Status
        patches are idempotent (operator/storage.py), so a claim that died
        after storing still converges to exactly one recentFailures entry.
        Returns the number of claims actually resumed."""
        # a warm standby's ledger was read at ITS boot: re-read the shared
        # journal NOW so takeover sees the dead leader's claims (and a
        # fresh append handle, in case the leader compacted the file)
        self.claims.reload()

        async def _one(claim: ClaimRecord) -> int:
            try:
                return await self._resume_claim(claim)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - one bad claim must not block the rest
                log.exception("claim resume failed for %s; releasing", claim.key)
                self.claims.release(claim.key)
                return 0

        # concurrent: the watcher does not start until resume returns, so
        # several pending claims resumed serially would leave the cluster
        # unwatched for the SUM of their budgets; gather bounds the blind
        # window to the slowest single claim
        return sum(
            await asyncio.gather(*(_one(c) for c in self.claims.take_pending()))
        )

    async def _resume_claim(self, claim: ClaimRecord) -> int:
        from .storage import ANNOTATION_ANALYZED_FAILURE

        try:
            raw = await asyncio.wait_for(
                self.api.get("Pod", claim.pod_name, claim.pod_namespace),
                timeout=self.config.kube_call_timeout_s,
            )
        except NotFoundError:
            self.claims.mark_done(claim.key)  # the pod is gone; nothing to analyze
            return 0
        except (ApiError, asyncio.TimeoutError):
            # transient: release so the sweep/reconciler can re-claim later
            self.claims.release(claim.key)
            return 0
        pod = Pod.parse(raw)
        if pod.metadata.annotations.get(ANNOTATION_ANALYZED_FAILURE) == claim.failure_time:
            # the previous process finished storing before it died
            self.claims.mark_done(claim.key)
            self.metrics.incr("dedupe_durable_hits")
            return 0
        podmortems: list[Podmortem] = []
        for ref in claim.podmortems:
            namespace, _, name = ref.partition("/")
            try:
                pm_raw = await asyncio.wait_for(
                    self.api.get("Podmortem", name, namespace),
                    timeout=self.config.kube_call_timeout_s,
                )
            except NotFoundError:
                continue  # CR deleted since the claim: skip it
            except (ApiError, asyncio.TimeoutError):
                # transient apiserver trouble (likely: the takeover window
                # IS an apiserver-degraded window) must not read as "CR
                # deleted" — that path marks the claim done and drops the
                # analysis forever.  Release so a later resume/sweep retries.
                self.claims.release(claim.key)
                return 0
            podmortems.append(Podmortem.parse(pm_raw))
        if not podmortems:
            self.claims.mark_done(claim.key)
            return 0
        self.metrics.incr("claims_resumed")
        log.info(
            "resuming claim %s (stage %r, %.1fs of %.1fs budget left)",
            claim.key, claim.stage,
            self.claims.remaining_budget_s(claim), claim.deadline_total_s,
        )
        # which CR was mid-flight when the process died (the stage marker):
        # refs at or before it consumed the claim's envelope and resume with
        # the wall-clock REMAINDER; refs after it never started, so they get
        # their own fresh envelope — exactly what the live path would have
        # handed them (its own design note: a shared group envelope would
        # hand later CRs whatever the first one left, possibly nothing)
        staged_ref = claim.stage.partition(":")[2]
        refs = claim.podmortems
        staged_idx = refs.index(staged_ref) if staged_ref in refs else len(refs)
        try:
            results = []
            for podmortem in podmortems:
                ref = podmortem.qualified_name()
                self.claims.note_stage(claim.key, f"resume:{ref}")
                if ref in refs and refs.index(ref) > staged_idx:
                    budget_s = self._deadline_total_for(podmortem)
                else:
                    # the resumed envelope is the smaller of the CR's own
                    # budget and what wall-clock says is left of the claim
                    budget_s = min(
                        self._deadline_total_for(podmortem),
                        self.claims.remaining_budget_s(claim),
                    )
                results.append(
                    await self.process_pod_failure(
                        pod, podmortem, failure_time=claim.failure_time,
                        deadline=Deadline.start(budget_s, clock=self._clock),
                    )
                )
        except BaseException:
            self.claims.release(claim.key)
            raise
        if any(result is not None for result in results):
            self.claims.mark_done(claim.key)
        else:
            self.claims.release(claim.key)
        return 1

    # ------------------------------------------------------------------
    async def process_pod_failure(
        self,
        pod: Pod,
        podmortem: Podmortem,
        *,
        failure_time: Optional[str] = None,
        deadline: Optional[Deadline] = None,
    ) -> Optional[AnalysisResult]:
        """The hot path (reference call stack §3.2).  Returns the analysis
        result, or None when collection failed outright.  Every stage spends
        the one ``deadline`` envelope (born at claim; a fresh default is
        created for direct callers).

        The whole run is one trace (operator_tpu/obs/): a span per stage,
        the trace id stamped into ``status.recentFailures[]``, and — when
        the analysis ends ``deadline-exceeded``, a breaker opens, or the
        engine reports a device error — a black-box dump of the full span
        tree plus the deadline ledger and any active fault-plan seed."""
        if deadline is None:
            deadline = self._deadline_for(podmortem)
        root: Optional[Span] = None
        result: Optional[AnalysisResult] = None
        try:
            with self.tracer.trace(
                "analysis",
                attributes={
                    "pod": pod.qualified_name(),
                    "podmortem": podmortem.qualified_name(),
                    "failure_time": failure_time or "",
                    "deadline_total_s": round(deadline.total_s, 3),
                },
            ) as root:
                # SLO admission at trace birth, keyed by the trace id so
                # ledger records join span trees on one id; the class
                # rides the pod's podmortem.io/slo-class annotation
                self.slo_ledger.admit(
                    root.trace_id,
                    cls=(pod.metadata.annotations or {}).get(
                        "podmortem.io/slo-class"
                    ),
                )
                result = await self._analyze(
                    pod, podmortem, failure_time=failure_time, deadline=deadline,
                    trace_root=root,
                )
            return result
        finally:
            # in a FINALLY so a flagged trace dumps even when the analysis
            # raises or is cancelled mid-flight (operator shutdown after a
            # breaker opened) — hard failures are exactly when the
            # forensic record matters.  The trace is fully assembled
            # (recorded by the tracer on context exit) before this reads it.
            if root is not None:
                reason = root.attributes.get("blackbox")
                if reason:
                    self._dump_black_box(root, reason, deadline)
                # settle the SLO record exactly once per analysis, in the
                # finally so cancelled/raised runs are accounted too.
                # Outcome precedence: an explicit backend override (the
                # storm harness stamps "shed" when the router refused the
                # dispatch) > the black-box deadline verdict > whether a
                # result was stored at all.
                outcome = root.attributes.get(SLO_OUTCOME_ATTR)
                if outcome is None:
                    if reason == "deadline-exceeded":
                        outcome = "deadline-exceeded"
                    elif result is not None:
                        outcome = "completed"
                    else:
                        outcome = "failed"
                self.slo_ledger.finish(
                    root.trace_id,
                    outcome=outcome,
                    tokens=int(root.attributes.get("ai_tokens") or 0),
                    replica=root.attributes.get("replica") or None,
                    stages=stage_durations(root),
                )

    def _dump_black_box(self, root: Span, reason: str, deadline: Deadline) -> None:
        """Dump the completed trace with its failure context: the deadline
        ledger and, when a chaos fault plan is active on the api seam, its
        seed + fired-fault fingerprint so the dump names the exact replay."""
        recorder = getattr(self.tracer, "recorder", None)
        if recorder is None:
            return
        extra: dict = {
            "deadline": {
                "total_s": round(deadline.total_s, 3),
                "elapsed_s": round(deadline.elapsed(), 3),
                "remaining_s": round(deadline.remaining(), 3),
            },
        }
        plan = getattr(self.api, "fault_plan", None)
        if plan is not None:
            extra["fault_plan"] = {
                "seed": plan.seed,
                "fired": len(plan.trace()),
                "fingerprint": plan.fingerprint(),
            }
        recorder.black_box(root.trace_id, reason, extra)

    async def _analyze(
        self,
        pod: Pod,
        podmortem: Podmortem,
        *,
        failure_time: Optional[str],
        deadline: Deadline,
        trace_root: Span,
    ) -> Optional[AnalysisResult]:
        started = time.perf_counter()
        self.metrics.incr("failures_detected")
        with self.tracer.span("emit.detected"):
            await self.events.emit_failure_detected(pod, podmortem)

        # -- collect (gets a SLICE of the budget) --------------------------
        collect_s = deadline.slice(
            self.config.collect_budget_fraction, floor_s=1.0
        )
        try:
            with self.tracer.span("collect", budget_s=round(collect_s, 3)):
                with self.metrics.timed("collect"):
                    failure = await asyncio.wait_for(
                        self.collect_failure_data(
                            pod,
                            deadline=Deadline.start(collect_s, clock=self._clock),
                        ),
                        timeout=collect_s,
                    )
        except asyncio.TimeoutError:
            log.error("log collection for %s exceeded its %.1fs budget slice",
                      pod.qualified_name(), collect_s)
            if deadline.expired:  # the ENVELOPE died during collection
                annotate_root("blackbox", "deadline-exceeded", overwrite=False)
            await self.events.emit_analysis_error(
                pod, podmortem,
                f"log collection exceeded its {collect_s:.1f}s budget slice",
            )
            self.metrics.incr("collect_timeouts")
            return None
        except ApiError as exc:
            log.error("failed collecting failure data for %s: %s", pod.qualified_name(), exc)
            await self.events.emit_analysis_error(pod, podmortem, f"log collection failed: {exc}")
            self.metrics.incr("collect_errors")
            return None

        # -- parse (CPU/TPU pattern match; capped by the remainder) --------
        parse_s = min(self.config.parse_timeout_s, max(0.1, deadline.remaining()))
        try:
            with self.tracer.span("parse", budget_s=round(parse_s, 3)):
                with self.metrics.timed("parse"):
                    result = await asyncio.wait_for(
                        asyncio.to_thread(self.engine.analyze, failure),
                        timeout=parse_s,
                    )
        except asyncio.TimeoutError:
            # attribute the timeout honestly: a deadline-bound cap means
            # the BUDGET killed the parse, not the pattern engine
            budget_bound = parse_s < self.config.parse_timeout_s
            message = (
                f"pattern analysis exceeded the remaining deadline budget "
                f"({parse_s:.1f}s)"
                if budget_bound
                else f"pattern analysis timed out after {parse_s:.0f}s"
            )
            log.error("%s (%s)", message, pod.qualified_name())
            if budget_bound:
                annotate_root("blackbox", "deadline-exceeded", overwrite=False)
            await self.events.emit_analysis_error(pod, podmortem, message)
            self.metrics.incr("deadline_exceeded" if budget_bound else "parse_errors")
            return None
        except Exception as exc:  # noqa: BLE001 - degrade, never crash the watch
            log.exception("pattern analysis failed for %s", pod.qualified_name())
            await self.events.emit_analysis_error(pod, podmortem, f"pattern analysis failed: {exc}")
            self.metrics.incr("parse_errors")
            return None

        # -- recall (incident memory, docs/MEMORY.md) ----------------------
        # exact fingerprint hit: reuse the stored analysis and SKIP the AI
        # leg — the dominant cost for a fleet-wide recurring failure; near
        # hit: carry the top-k prior incidents into the prompt; miss: full
        # analysis, remembered below
        ai_configured = (
            podmortem.spec.ai_analysis_enabled
            and podmortem.spec.ai_provider_ref is not None
        )
        # reuse identity is the provider ref PLUS a hash of the spec
        # fields that shape its output: a hit must hand this CR an
        # analysis its own CURRENT provider would have generated — never
        # another CR's text, and never a stale one from before the
        # AIProvider was edited (new model/template regenerates).  The CR's
        # cachingEnabled opt-out is honoured exactly like ResponseCache.
        provider_ref_key: Optional[str] = None
        provider: Optional[AIProvider] = None
        caching_ok = False
        recall: Optional[RecallDecision] = None
        recurrence: Optional[FailureRecurrence] = None
        ai_response: Optional[AIResponse] = None
        reused = False
        with self.tracer.span("recall") as recall_span:
            if ai_configured:
                # its own child span: the identity fetch is an apiserver
                # GET, and its latency must never read as incident-memory
                # time in the trace
                with self.tracer.span("provider.identity"):
                    provider, provider_ref_key = await self._resolve_provider_identity(
                        podmortem, deadline=deadline
                    )
                caching_ok = provider is not None and provider.spec.caching_enabled
            if self.memory is not None:
                with self.metrics.timed("recall"):
                    # embedding may be a neural encoder; keep the loop free
                    recall = await asyncio.to_thread(
                        self.memory.recall, result, pod,
                        allow_reuse=ai_configured and caching_ok,
                        provider_ref=provider_ref_key,
                        trace_id=trace_root.trace_id,
                    )
                recall_span.set(kind=recall.kind)
                if recall.prior_trace_id:
                    # a recurrence links back to its prior analysis's trace
                    recall_span.set(prior_trace_id=recall.prior_trace_id)
                if recall.kind == RECALL_HIT:
                    incident = recall.incident
                    reused = True
                    self.metrics.incr("recall_hit")
                    # the hit RETURNS the unused deadline budget: everything
                    # the AI leg would have spent is handed back (recorded so
                    # the decode-seconds saved are visible on /metrics)
                    self.metrics.record(
                        "recall_budget_returned", deadline.remaining() * 1e3
                    )
                    ai_response = AIResponse(
                        explanation=recall.analysis.explanation,
                        provider_id=recall.analysis.provider_id,
                        model_id=recall.analysis.model_id,
                        cached=True,
                    )
                    recurrence = FailureRecurrence(
                        fingerprint=incident.fingerprint,
                        seen_count=incident.seen_count,
                        first_seen=incident.first_seen,
                        reused_analysis=True,
                    )
                elif recall.kind == RECALL_NEAR:
                    self.metrics.incr("recall_near")
                else:
                    self.metrics.incr("recall_miss")

        # -- explain (the AI leg gets whatever budget is left) -------------
        with self.tracer.span(
            "explain", reused=reused, configured=ai_configured
        ) as explain_span:
            if reused:
                pass  # cached analysis; no generation
            elif ai_configured:
                if deadline.expired:
                    # the budget died before the AI leg even started: degrade
                    # to pattern-only NOW instead of dispatching a doomed call
                    message = (
                        f"analysis deadline ({deadline.total_s:.0f}s) exhausted "
                        "before AI generation; storing pattern-only result"
                    )
                    log.warning("%s (%s)", message, pod.qualified_name())
                    await self.events.emit_analysis_error(pod, podmortem, message)
                    ai_response = AIResponse(
                        error=message, deadline_outcome="deadline-exceeded"
                    )
                else:
                    priors = [
                        PriorIncident(
                            fingerprint=inc.fingerprint,
                            score=round(score, 4),
                            seen_count=inc.seen_count,
                            severity=inc.severity,
                            last_seen=inc.last_seen,
                            explanation=inc.explanation,
                        )
                        for inc, score in (recall.neighbors if recall else [])
                    ]
                    ai_response = await self._generate_explanation(
                        pod, podmortem, result, failure, deadline=deadline,
                        prior_incidents=priors, provider=provider,
                        # the failure-class fingerprint is the router's
                        # affinity key: recurrences of one incident land
                        # on the replica whose recall cache is hot
                        fingerprint=(
                            recall.fingerprint.digest if recall is not None
                            else None
                        ),
                        # overload-value signals (router/value.py): the
                        # SLO class weights the shed decision and the
                        # recall-hit probability discounts the expected
                        # cost — recalled work is shed last
                        slo_class=(pod.metadata.annotations or {}).get(
                            "podmortem.io/slo-class"
                        ),
                        recall_p=(
                            IncidentMemory.hit_probability(recall)
                            if recall is not None else 0.0
                        ),
                    )
                self._record_deadline_outcome(ai_response)
                if ai_response is not None:
                    if ai_response.deadline_outcome:
                        explain_span.set(outcome=ai_response.deadline_outcome)
                    if ai_response.deadline_outcome == "deadline-exceeded":
                        # the terminal deadline outcome — the black-box trigger
                        annotate_root(
                            "blackbox", "deadline-exceeded", overwrite=False
                        )
                    if ai_response.deadline_outcome in ("degraded", "shed"):
                        # the overload ladder's verdict settles the SLO
                        # record under its own outcome (the ledger's
                        # finally reads this override)
                        annotate_root(
                            SLO_OUTCOME_ATTR, ai_response.deadline_outcome,
                            overwrite=False,
                        )
                    if ai_response.error:
                        explain_span.status = "error"
                        explain_span.error = ai_response.error[:300]
                    # the SLO ledger's goodput + per-replica attribution
                    # read these off the root at settlement
                    if ai_response.completion_tokens:
                        trace_root.set(ai_tokens=ai_response.completion_tokens)
                    if ai_response.replica_id:
                        trace_root.set(replica=ai_response.replica_id)
            elif podmortem.spec.ai_analysis_enabled:
                log.info("podmortem %s has no aiProviderRef; storing pattern-only result",
                         podmortem.qualified_name())

        # -- remember (a hit already bumped its recurrence counters) -------
        if self.memory is not None and recall is not None:
            with self.tracer.span("remember"):
                if not reused:
                    incident = await asyncio.to_thread(
                        self.memory.insert, recall.fingerprint, result, pod, ai_response,
                        related=[inc.fingerprint for inc, _ in recall.neighbors],
                        # recall() already counted this sighting iff it found
                        # the digest; otherwise a racing concurrent first
                        # sighting is counted by the upsert itself
                        seen_recorded=recall.incident is not None,
                        # cachingEnabled=false also means "don't remember my
                        # generations": recurrence is tracked, text is not
                        provider_ref=provider_ref_key if caching_ok else None,
                        cacheable=caching_ok,
                        trace_id=trace_root.trace_id,
                    )
                    if incident is not None:  # weak fingerprints are never stored
                        recurrence = FailureRecurrence(
                            fingerprint=incident.fingerprint,
                            seen_count=incident.seen_count,
                            first_seen=incident.first_seen,
                            reused_analysis=False,
                        )
                # snapshot into the OPERATOR's namespace (where restore reads
                # it, app.py) — never the CR's, or multi-namespace fleets
                # scatter partial snapshots that restore can't find.  Hits
                # flush too: recurrence counters must survive a restart.
                await self.memory.maybe_flush_to_configmap(
                    self.api, getattr(self.api, "namespace", None) or "default"
                )

        # -- store + emit --------------------------------------------------
        with self.tracer.span("store"):
            with self.metrics.timed("store"):
                await self.storage.store_analysis_results(
                    result, ai_response, pod, podmortem,
                    failure_time=failure_time, recurrence=recurrence,
                    trace_id=trace_root.trace_id,
                )
        explanation = (
            ai_response.explanation
            if ai_response is not None and ai_response.explanation
            else result.pattern_summary_line()
        )
        with self.tracer.span("emit.complete"):
            await self.events.emit_analysis_complete(pod, podmortem, result, explanation)
        total_ms = (time.perf_counter() - started) * 1e3
        self.metrics.record("pipeline_total", total_ms)
        self.metrics.incr("analyses_completed")
        if result.timings is not None:
            result.timings.total_ms = round(total_ms, 3)
        return result

    # ------------------------------------------------------------------
    async def collect_failure_data(
        self, pod: Pod, *, deadline: Optional[Deadline] = None
    ) -> PodFailureData:
        """Pod log + namespace events for the pod
        (reference collectPodFailureData, PodFailureWatcher.java:310-345).
        Prefers the previous container's log when the pod restarted (the
        crash evidence lives there, not in the fresh container).  Each
        apiserver call spends from ``deadline`` (the collect slice of the
        analysis envelope); without one the calls are unbounded — callers
        on the analysis path always pass the budget."""
        restarted = any(
            cs.restart_count > 0 for cs in (pod.status.container_statuses if pod.status else [])
        )

        def residue() -> Optional[float]:
            return deadline.remaining() if deadline is not None else None

        logs = ""
        try:
            logs = await asyncio.wait_for(
                self.api.get_log(
                    pod.metadata.name,
                    pod.metadata.namespace,
                    previous=restarted,
                    tail_bytes=self.config.log_tail_bytes,
                ),
                timeout=residue(),
            )
        except NotFoundError:
            raise
        except ApiError as exc:
            log.warning("log fetch failed for %s (%s); continuing with events only",
                        pod.qualified_name(), exc)
        events: list[KubeEvent] = []
        try:
            raw_events = await asyncio.wait_for(
                self.api.list("Event", namespace=pod.metadata.namespace),
                timeout=residue(),
            )
            for raw in raw_events:
                event = KubeEvent.parse(raw)
                if event.regarding is None or event.regarding.name != pod.metadata.name:
                    continue
                # never feed our own analysis events back into analysis — the
                # explanation quotes log evidence, which would re-match the
                # patterns and echo-amplify on every restart
                if event.reporting_controller == self.config.reporting_controller:
                    continue
                events.append(event)
        except (ApiError, asyncio.TimeoutError) as exc:
            # events are best-effort evidence: a timeout here degrades to
            # logs-only instead of burning the rest of the collect slice
            log.debug("event list failed for %s: %s", pod.qualified_name(), exc)
        return PodFailureData(pod=pod, logs=logs, events=events, collection_time=now_iso())

    # ------------------------------------------------------------------
    async def _resolve_provider_identity(
        self, podmortem: Podmortem, *, deadline: Optional[Deadline] = None
    ) -> "tuple[Optional[AIProvider], Optional[str]]":
        """Fetch the CR's AIProvider and derive the reuse-identity key:
        ``namespace/name@spec-hash`` over the spec fields that shape the
        generated text (the same identity basis as ResponseCache.key).
        Fetch failures — including the ``deadline`` residue expiring —
        return (None, bare ref key): recall proceeds reuse-disabled and the
        AI leg's own fetch reports the error."""
        import hashlib
        import json

        ref = podmortem.spec.ai_provider_ref
        namespace = ref.namespace or podmortem.metadata.namespace or "default"
        ref_key = f"{namespace}/{ref.name}"
        try:
            provider_dict = await asyncio.wait_for(
                self.api.get("AIProvider", ref.name, namespace),
                timeout=deadline.remaining() if deadline is not None else None,
            )
        except (ApiError, asyncio.TimeoutError):
            return None, ref_key
        provider = AIProvider.parse(provider_dict)
        spec = provider.spec
        basis = json.dumps(
            {
                "provider": spec.provider_id,
                "url": spec.api_url,
                "model": spec.model_id,
                "template": spec.prompt_template,
                "max_tokens": spec.max_tokens,
                "temperature": spec.temperature,
                # additionalConfig selects LoRA adapters and guided-decoding
                # constraints — output-shaping, so part of the identity
                "extra": dict(sorted(spec.additional_config.items())),
            },
            sort_keys=True,
        )
        digest = hashlib.sha256(basis.encode()).hexdigest()[:12]
        return provider, f"{ref_key}@{digest}"

    # ------------------------------------------------------------------
    def _note_breaker_trip(self, breaker_key: str) -> None:
        """One place counts a breaker trip AND flags the ambient trace for
        a black-box dump — an open breaker is exactly the moment the
        per-request timeline matters (docs/OBSERVABILITY.md)."""
        self.metrics.incr("circuit_opened")
        annotate_root("blackbox", "breaker-open", overwrite=False)
        annotate_root("breaker", breaker_key)

    # ------------------------------------------------------------------
    def _record_deadline_outcome(self, ai_response: Optional[AIResponse]) -> None:
        """One place turns the AI leg's budget outcome into counters (the
        Prometheus surface: podmortem_deadline_*_total).  Backends that
        produced text without reporting an outcome count as completed."""
        if ai_response is None:
            return
        if ai_response.deadline_outcome is None and ai_response.explanation:
            ai_response.deadline_outcome = "completed"
        outcome = ai_response.deadline_outcome
        if outcome == "completed":
            self.metrics.incr("deadline_completed")
        elif outcome == "truncated":
            self.metrics.incr("deadline_truncated")
        elif outcome == "degraded":
            self.metrics.incr("deadline_degraded")
        elif outcome == "deadline-exceeded":
            self.metrics.incr("deadline_exceeded")

    # ------------------------------------------------------------------
    async def _generate_explanation(
        self,
        pod: Pod,
        podmortem: Podmortem,
        result: AnalysisResult,
        failure: PodFailureData,
        *,
        deadline: Optional[Deadline] = None,
        prior_incidents: Optional[list[PriorIncident]] = None,
        provider: Optional[AIProvider] = None,
        fingerprint: Optional[str] = None,
        slo_class: Optional[str] = None,
        recall_p: float = 0.0,
    ) -> AIResponse:
        ref = podmortem.spec.ai_provider_ref
        namespace = ref.namespace or podmortem.metadata.namespace or "default"
        with self.tracer.span("provider.resolve", ref=f"{namespace}/{ref.name}"):
            if provider is None:  # not pre-fetched by the recall identity step
                try:
                    provider_dict = await asyncio.wait_for(
                        self.api.get("AIProvider", ref.name, namespace),
                        timeout=(
                            deadline.remaining() if deadline is not None else None
                        ),
                    )
                except NotFoundError:
                    message = f"AIProvider {namespace}/{ref.name} not found"
                    log.warning("%s (podmortem %s)", message, podmortem.qualified_name())
                    await self.events.emit_analysis_error(pod, podmortem, message)
                    self.metrics.incr("provider_missing")
                    return AIResponse(error=message)
                except (ApiError, asyncio.TimeoutError) as exc:
                    message = (
                        f"AIProvider fetch failed: "
                        f"{str(exc) or 'deadline budget exhausted'}"
                    )
                    await self.events.emit_analysis_error(pod, podmortem, message)
                    return AIResponse(error=message)
                provider = AIProvider.parse(provider_dict)
            provider_config = await resolve_provider_config(
                self.api, provider, deadline=deadline
            )
        remaining = deadline.remaining() if deadline is not None else None
        request = AnalysisRequest(
            analysis_result=result, provider_config=provider_config,
            failure_data=failure, deadline_s=remaining,
            prior_incidents=list(prior_incidents or []),
            fingerprint=fingerprint,
            slo_class=slo_class, recall_p=recall_p,
        )

        cache_key = None
        if provider_config.caching_enabled:
            cache_key = ResponseCache.key(request)
            cached = self.cache.get(cache_key)
            if cached is not None:
                self.metrics.incr("ai_cache_hits")
                cached_copy = AIResponse(**{**cached.__dict__, "cached": True})
                return cached_copy

        # circuit breaker: a dead backend must stop burning the deadline
        # budget — skip the call outright while its breaker is open and
        # fall through the existing degradation ladder (pattern-only store).
        # Keyed by providerId AND apiUrl: two CRs sharing a providerId but
        # pointing at different HTTP endpoints are different backends, and
        # one dead endpoint must not blackhole the healthy one.
        breaker_key = provider_config.provider_id or "template"
        if provider_config.api_url:
            breaker_key = f"{breaker_key}@{provider_config.api_url}"
        breaker = self.breakers.for_provider(breaker_key)
        if not breaker.allow():
            message = f"circuit open for provider {breaker_key}: AI call skipped"
            log.warning("%s (%s)", message, pod.qualified_name())
            await self.events.emit_analysis_error(pod, podmortem, message)
            self.metrics.incr("circuit_open_skips")
            return AIResponse(error=message, provider_id=provider_config.provider_id)

        try:
            backend = self.providers.resolve(provider_config.provider_id)
        except ProviderError as exc:
            await self.events.emit_analysis_error(pod, podmortem, str(exc))
            self.metrics.incr("provider_errors")
            if breaker.record_failure():
                self._note_breaker_trip(breaker_key)
            return AIResponse(error=str(exc))

        # the AI leg gets the REMAINDER of the envelope, never more than
        # the flat reference budget (ai_timeout_s, application.properties)
        timeout_s = self.config.ai_timeout_s
        if remaining is not None:
            timeout_s = min(timeout_s, remaining)
        try:
            with self.tracer.span(
                "ai_generate",
                provider=provider_config.provider_id or "template",
                budget_s=round(timeout_s, 3),
            ) as gen_span:
                with self.metrics.timed("ai_generate"):
                    response = await asyncio.wait_for(
                        backend.generate(request), timeout=timeout_s
                    )
                # routing forensics (operator_tpu/router/): which replica
                # served this leg, and whether a cross-replica requeue
                # saved it — mirrored into the stage metrics so the
                # counter surface shows failovers without span digging
                if response.replica_id:
                    gen_span.set(replica=response.replica_id)
                if response.requeues:
                    gen_span.set(requeues=response.requeues)
                    self.metrics.incr("analysis_requeued")
        except asyncio.TimeoutError:
            budget_bound = remaining is not None and remaining < self.config.ai_timeout_s
            message = (
                f"AI generation exceeded the remaining deadline budget "
                f"({timeout_s:.1f}s)"
                if budget_bound
                else f"AI generation timed out after {timeout_s:.0f}s"
            )
            await self.events.emit_analysis_error(pod, podmortem, message)
            self.metrics.incr("ai_timeouts")
            # budget-bound timeouts are OUR deadline pressure, not backend
            # health: counting them would trip the breaker on a healthy
            # backend whenever upstream stages run long
            if not budget_bound and breaker.record_failure():
                self._note_breaker_trip(breaker_key)
            return AIResponse(
                error=message, provider_id=provider_config.provider_id,
                deadline_outcome="deadline-exceeded" if budget_bound else None,
            )
        except Exception as exc:  # noqa: BLE001 - degrade to pattern-only
            log.exception("AI generation failed for %s", pod.qualified_name())
            await self.events.emit_analysis_error(pod, podmortem, f"AI generation failed: {exc}")
            self.metrics.incr("ai_errors")
            if breaker.record_failure():
                self._note_breaker_trip(breaker_key)
            return AIResponse(error=str(exc), provider_id=provider_config.provider_id)

        if response.error:
            await self.events.emit_analysis_error(pod, podmortem, response.error)
            self.metrics.incr("ai_errors")
            # backend-attributed failures only: a deadline-exceeded outcome
            # means the BUDGET killed the leg, not the provider
            if response.deadline_outcome != "deadline-exceeded" and \
                    breaker.record_failure():
                self._note_breaker_trip(breaker_key)
        else:
            breaker.record_success()
            if cache_key is not None:
                self.cache.put(cache_key, response)
        return response
