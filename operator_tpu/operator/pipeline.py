"""The failure-analysis pipeline: collect -> parse -> explain -> store -> emit.

One implementation shared by the real-time watcher and the poll-path
reconciler — the consolidation SURVEY.md §3.3 calls out (the reference
duplicates ~200 LoC between PodFailureWatcher and PodmortemReconciler, and
the reconcile path never stores results; here both paths store).

Graceful degradation mirrors the reference (SURVEY.md §5 failure-detection
entry): parse failure => error event + status; AI failure => pattern-only
result still stored; provider missing => stored without AI.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional

from ..patterns.engine import PatternEngine
from ..schema.analysis import AIResponse, AnalysisRequest, AnalysisResult, PodFailureData
from ..schema.crds import AIProvider, Podmortem
from ..schema.kube import Event as KubeEvent
from ..schema.kube import Pod
from ..schema.meta import now_iso
from ..utils.config import OperatorConfig
from ..utils.timing import METRICS, MetricsRegistry
from .events import EventService
from .kubeapi import ApiError, KubeApi, NotFoundError
from .providers import (
    ProviderError,
    ProviderRegistry,
    ResponseCache,
    default_registry,
    resolve_provider_config,
)
from .storage import AnalysisStorageService

log = logging.getLogger(__name__)


class FailureDedupe:
    """Shared dedupe of (pod, failureTime) across the watcher and the
    poll-path reconciler — one analysis per distinct failure, like the
    reference's ``processedFailures`` map (PodFailureWatcher.java:50,180-193)
    but (a) shared by both detection paths, (b) bounded, and (c) aware of
    in-flight vs done so a *failed* analysis can be retried."""

    _IN_FLIGHT = "in-flight"
    _DONE = "done"

    def __init__(self, max_entries: int = 10_000) -> None:
        from collections import OrderedDict

        self._states: "OrderedDict[str, str]" = OrderedDict()
        self._max = max_entries

    @staticmethod
    def key(pod: Pod, failure_time: str) -> str:
        return f"{pod.metadata.namespace}/{pod.metadata.name}@{failure_time}"

    def try_claim(self, key: str) -> bool:
        """Claim the failure for processing; False if already in flight or done."""
        if key in self._states:
            self._states.move_to_end(key)
            return False
        self._states[key] = self._IN_FLIGHT
        while len(self._states) > self._max:
            self._states.popitem(last=False)
        return True

    def mark_done(self, key: str) -> None:
        self._states[key] = self._DONE

    def release(self, key: str) -> None:
        """Forget a failed attempt so either path may retry it."""
        self._states.pop(key, None)


class AnalysisPipeline:
    def __init__(
        self,
        api: KubeApi,
        engine: PatternEngine,
        *,
        config: Optional[OperatorConfig] = None,
        events: Optional[EventService] = None,
        storage: Optional[AnalysisStorageService] = None,
        providers: Optional[ProviderRegistry] = None,
        metrics: Optional[MetricsRegistry] = None,
    ) -> None:
        self.api = api
        self.engine = engine
        self.config = config or OperatorConfig()
        self.events = events or EventService(api, self.config)
        self.storage = storage or AnalysisStorageService(api, self.config)
        self.providers = providers or default_registry()
        self.metrics = metrics or METRICS
        self.cache = ResponseCache()
        self.dedupe = FailureDedupe()

    # ------------------------------------------------------------------
    async def process_failure_group(
        self,
        pod: Pod,
        podmortems: list[Podmortem],
        *,
        failure_time: str,
    ) -> list[Optional[AnalysisResult]]:
        """Claim one (pod, failureTime) and fan out one pipeline per matching
        CR (reference fans out per CR, PodFailureWatcher.java:196-199).
        Returns [] if the failure was already claimed.  A fully failed group
        releases the claim so the other detection path can retry it."""
        key = FailureDedupe.key(pod, failure_time)
        if not self.dedupe.try_claim(key):
            return []
        # durable dedupe: the in-memory map dies with the process, but the
        # analyzed-failure annotation is in etcd — a restarted operator (or
        # the pre-watch sweep) must not re-analyze an annotated failure
        from .storage import ANNOTATION_ANALYZED_FAILURE

        if pod.metadata.annotations.get(ANNOTATION_ANALYZED_FAILURE) == failure_time:
            self.dedupe.mark_done(key)
            self.metrics.incr("dedupe_durable_hits")
            return []
        try:
            results = []
            for podmortem in podmortems:
                results.append(
                    await self.process_pod_failure(pod, podmortem, failure_time=failure_time)
                )
        except BaseException:
            self.dedupe.release(key)
            raise
        if any(result is not None for result in results):
            self.dedupe.mark_done(key)
        else:
            self.dedupe.release(key)
        return results

    # ------------------------------------------------------------------
    async def process_pod_failure(
        self,
        pod: Pod,
        podmortem: Podmortem,
        *,
        failure_time: Optional[str] = None,
    ) -> Optional[AnalysisResult]:
        """The hot path (reference call stack §3.2).  Returns the analysis
        result, or None when collection failed outright."""
        started = time.perf_counter()
        self.metrics.incr("failures_detected")
        await self.events.emit_failure_detected(pod, podmortem)

        # -- collect -----------------------------------------------------
        try:
            with self.metrics.timed("collect"):
                failure = await self.collect_failure_data(pod)
        except ApiError as exc:
            log.error("failed collecting failure data for %s: %s", pod.qualified_name(), exc)
            await self.events.emit_analysis_error(pod, podmortem, f"log collection failed: {exc}")
            self.metrics.incr("collect_errors")
            return None

        # -- parse (CPU/TPU pattern match) --------------------------------
        try:
            with self.metrics.timed("parse"):
                result = await asyncio.wait_for(
                    asyncio.to_thread(self.engine.analyze, failure),
                    timeout=self.config.parse_timeout_s,
                )
        except Exception as exc:  # noqa: BLE001 - degrade, never crash the watch
            log.exception("pattern analysis failed for %s", pod.qualified_name())
            await self.events.emit_analysis_error(pod, podmortem, f"pattern analysis failed: {exc}")
            self.metrics.incr("parse_errors")
            return None

        # -- explain ------------------------------------------------------
        ai_response: Optional[AIResponse] = None
        if podmortem.spec.ai_analysis_enabled and podmortem.spec.ai_provider_ref is not None:
            ai_response = await self._generate_explanation(pod, podmortem, result, failure)
        elif podmortem.spec.ai_analysis_enabled:
            log.info("podmortem %s has no aiProviderRef; storing pattern-only result",
                     podmortem.qualified_name())

        # -- store + emit --------------------------------------------------
        with self.metrics.timed("store"):
            await self.storage.store_analysis_results(
                result, ai_response, pod, podmortem, failure_time=failure_time
            )
        explanation = (
            ai_response.explanation
            if ai_response is not None and ai_response.explanation
            else result.pattern_summary_line()
        )
        await self.events.emit_analysis_complete(pod, podmortem, result, explanation)
        total_ms = (time.perf_counter() - started) * 1e3
        self.metrics.record("pipeline_total", total_ms)
        self.metrics.incr("analyses_completed")
        if result.timings is not None:
            result.timings.total_ms = round(total_ms, 3)
        return result

    # ------------------------------------------------------------------
    async def collect_failure_data(self, pod: Pod) -> PodFailureData:
        """Pod log + namespace events for the pod
        (reference collectPodFailureData, PodFailureWatcher.java:310-345).
        Prefers the previous container's log when the pod restarted (the
        crash evidence lives there, not in the fresh container)."""
        restarted = any(
            cs.restart_count > 0 for cs in (pod.status.container_statuses if pod.status else [])
        )
        logs = ""
        try:
            logs = await self.api.get_log(
                pod.metadata.name,
                pod.metadata.namespace,
                previous=restarted,
                tail_bytes=self.config.log_tail_bytes,
            )
        except NotFoundError:
            raise
        except ApiError as exc:
            log.warning("log fetch failed for %s (%s); continuing with events only",
                        pod.qualified_name(), exc)
        events: list[KubeEvent] = []
        try:
            raw_events = await self.api.list("Event", namespace=pod.metadata.namespace)
            for raw in raw_events:
                event = KubeEvent.parse(raw)
                if event.regarding is None or event.regarding.name != pod.metadata.name:
                    continue
                # never feed our own analysis events back into analysis — the
                # explanation quotes log evidence, which would re-match the
                # patterns and echo-amplify on every restart
                if event.reporting_controller == self.config.reporting_controller:
                    continue
                events.append(event)
        except ApiError as exc:
            log.debug("event list failed for %s: %s", pod.qualified_name(), exc)
        return PodFailureData(pod=pod, logs=logs, events=events, collection_time=now_iso())

    # ------------------------------------------------------------------
    async def _generate_explanation(
        self,
        pod: Pod,
        podmortem: Podmortem,
        result: AnalysisResult,
        failure: PodFailureData,
    ) -> AIResponse:
        ref = podmortem.spec.ai_provider_ref
        namespace = ref.namespace or podmortem.metadata.namespace or "default"
        try:
            provider_dict = await self.api.get("AIProvider", ref.name, namespace)
        except NotFoundError:
            message = f"AIProvider {namespace}/{ref.name} not found"
            log.warning("%s (podmortem %s)", message, podmortem.qualified_name())
            await self.events.emit_analysis_error(pod, podmortem, message)
            self.metrics.incr("provider_missing")
            return AIResponse(error=message)
        except ApiError as exc:
            await self.events.emit_analysis_error(pod, podmortem, f"AIProvider fetch failed: {exc}")
            return AIResponse(error=str(exc))

        provider = AIProvider.parse(provider_dict)
        provider_config = await resolve_provider_config(self.api, provider)
        request = AnalysisRequest(
            analysis_result=result, provider_config=provider_config, failure_data=failure
        )

        cache_key = None
        if provider_config.caching_enabled:
            cache_key = ResponseCache.key(request)
            cached = self.cache.get(cache_key)
            if cached is not None:
                self.metrics.incr("ai_cache_hits")
                cached_copy = AIResponse(**{**cached.__dict__, "cached": True})
                return cached_copy

        try:
            backend = self.providers.resolve(provider_config.provider_id)
        except ProviderError as exc:
            await self.events.emit_analysis_error(pod, podmortem, str(exc))
            self.metrics.incr("provider_errors")
            return AIResponse(error=str(exc))

        try:
            with self.metrics.timed("ai_generate"):
                response = await asyncio.wait_for(
                    backend.generate(request), timeout=self.config.ai_timeout_s
                )
        except asyncio.TimeoutError:
            message = f"AI generation timed out after {self.config.ai_timeout_s:.0f}s"
            await self.events.emit_analysis_error(pod, podmortem, message)
            self.metrics.incr("ai_timeouts")
            return AIResponse(error=message, provider_id=provider_config.provider_id)
        except Exception as exc:  # noqa: BLE001 - degrade to pattern-only
            log.exception("AI generation failed for %s", pod.qualified_name())
            await self.events.emit_analysis_error(pod, podmortem, f"AI generation failed: {exc}")
            self.metrics.incr("ai_errors")
            return AIResponse(error=str(exc), provider_id=provider_config.provider_id)

        if response.error:
            await self.events.emit_analysis_error(pod, podmortem, response.error)
            self.metrics.incr("ai_errors")
        elif cache_key is not None:
            self.cache.put(cache_key, response)
        return response
