"""Kubernetes API abstraction + in-memory fake apiserver.

The control plane talks to this interface only; production uses the HTTP
client (``operator_tpu.operator.httpapi``), tests use :class:`FakeKubeApi` —
the fabric8-mock-server role the reference's intended-but-never-landed test
strategy called for (SURVEY.md §4).

The fake reproduces the apiserver behaviours the operator's correctness
depends on:

- **optimistic concurrency**: a patch carrying a stale ``resourceVersion``
  fails with 409, exactly what AnalysisStorageService's retry discipline is
  built against (reference AnalysisStorageService.java:179-187);
- **watch streams** per kind/namespace with ADDED/MODIFIED/DELETED events and
  server-side close (so watcher auto-restart logic is testable —
  reference PodFailureWatcher.java:127-135);
- **label-selector list filtering** (reference PodmortemReconciler.java:105-111);
- **error injection hooks** for 409 storms, 403s, and transient faults —
  filterable by kind, so chaos tests can partition the leader away from its
  ``coordination.k8s.io/Lease`` (operator/lease.py) while the rest of its
  API traffic flows (Lease CRUD itself rides the generic kind-keyed store:
  create/get/patch with resourceVersion guards behave exactly like the real
  apiserver's optimistic concurrency, which is what leader takeover races
  are decided by).
"""

from __future__ import annotations

import asyncio
import copy
import logging
import uuid
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Callable, Optional

from ..schema.meta import LabelSelector, now_iso

log = logging.getLogger(__name__)


class ApiError(Exception):
    status = 500

    def __init__(self, message: str, status: Optional[int] = None) -> None:
        super().__init__(message)
        if status is not None:
            self.status = status


class NotFoundError(ApiError):
    status = 404


class ConflictError(ApiError):
    status = 409


class ForbiddenError(ApiError):
    status = 403


class WatchClosed(Exception):
    """The server closed the watch stream; callers should re-establish
    (the reference restarts its watch 5s after an error close —
    PodFailureWatcher.java:562-583)."""


class WatchExpired(WatchClosed):
    """The resume resourceVersion is too old (HTTP 410 Gone / ERROR event
    with code 410): the apiserver has compacted past it.  Callers must
    relist (re-sweep) and watch from the fresh list's resourceVersion —
    resuming from the stale cursor would silently drop events."""


@dataclass
class WatchEvent:
    #: ADDED | MODIFIED | DELETED | BOOKMARK — bookmarks carry only
    #: metadata.resourceVersion (cursor refresh); consumers MUST skip them
    #: before parsing (a bookmark parsed as a CR is a phantom object whose
    #: empty selector matches everything)
    type: str
    object: dict[str, Any]


# --------------------------------------------------------------------------
# interface
# --------------------------------------------------------------------------


class KubeApi:
    """Async Kubernetes API surface used by the control plane.  All objects
    are plain camelCase dicts (parse into schema types at the edges)."""

    async def get(self, kind: str, name: str, namespace: str) -> dict:
        raise NotImplementedError

    async def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[LabelSelector] = None,
    ) -> list[dict]:
        raise NotImplementedError

    async def list_rv(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[LabelSelector] = None,
    ) -> tuple[list[dict], Optional[str]]:
        """List plus the collection's resourceVersion — the resume cursor
        a subsequent watch() starts from so nothing between the list and
        the watch is missed.  None when the backend can't provide one
        (callers then watch from "now" and rely on sweeps)."""
        return await self.list(kind, namespace, label_selector), None

    async def create(self, kind: str, obj: dict) -> dict:
        raise NotImplementedError

    async def patch(
        self,
        kind: str,
        name: str,
        namespace: str,
        patch: dict,
        *,
        resource_version: Optional[str] = None,
    ) -> dict:
        raise NotImplementedError

    async def patch_status(
        self,
        kind: str,
        name: str,
        namespace: str,
        status: dict,
        *,
        resource_version: Optional[str] = None,
    ) -> dict:
        raise NotImplementedError

    async def delete(self, kind: str, name: str, namespace: str) -> None:
        raise NotImplementedError

    async def get_scale(self, kind: str, name: str, namespace: str) -> dict:
        """Read the ``scale`` subresource (autoscaling/v1 Scale dict) of a
        scalable object — ``spec.replicas`` is the desired count, an RBAC
        grant on ``deployments/scale`` alone suffices (the autoscaler
        never needs the full Deployment)."""
        raise NotImplementedError

    async def patch_scale(
        self,
        kind: str,
        name: str,
        namespace: str,
        replicas: int,
        *,
        resource_version: Optional[str] = None,
    ) -> dict:
        """Set ``spec.replicas`` through the ``scale`` subresource.  With
        ``resource_version`` the write is guarded by the same optimistic
        concurrency as :meth:`patch` (409 on mismatch)."""
        raise NotImplementedError

    async def get_log(
        self,
        name: str,
        namespace: str,
        *,
        container: Optional[str] = None,
        previous: bool = False,
        tail_bytes: Optional[int] = None,
    ) -> str:
        raise NotImplementedError

    def watch(
        self,
        kind: str,
        namespace: Optional[str] = None,
        resource_version: Optional[str] = None,
    ) -> AsyncIterator[WatchEvent]:
        """Stream events.  With ``resource_version`` the stream RESUMES
        from that point (events after it are replayed), raising
        :class:`WatchExpired` when the server compacted past it.  BOOKMARK
        events surface to the caller (cursor refresh), everything else is
        ADDED/MODIFIED/DELETED."""
        raise NotImplementedError


# --------------------------------------------------------------------------
# fake implementation
# --------------------------------------------------------------------------


def _deep_merge(base: dict, patch: dict) -> dict:
    """JSON-merge-patch semantics: dicts merge recursively, ``None`` deletes,
    everything else (lists included) replaces."""
    out = dict(base)
    for key, value in patch.items():
        if value is None:
            out.pop(key, None)
        elif isinstance(value, dict) and isinstance(out.get(key), dict):
            out[key] = _deep_merge(out[key], value)
        else:
            out[key] = copy.deepcopy(value)
    return out


@dataclass
class _WatchRegistration:
    kind: str
    namespace: Optional[str]
    queue: asyncio.Queue = field(default_factory=asyncio.Queue)


async def iter_watch_resumed(
    api: KubeApi,
    kind: str,
    namespace: Optional[str],
    get_cursor: Callable[[], Optional[str]],
    set_cursor: Callable[[Optional[str]], None],
) -> AsyncIterator[tuple[WatchEvent, Optional[str]]]:
    """The shared resumable-watch discipline for every watch consumer.

    Opens ``api.watch`` at the current cursor and yields
    ``(event, resourceVersion)`` pairs for non-BOOKMARK events.  Bookmarks
    refresh the cursor silently; a 410 (WatchExpired) CLEARS the cursor —
    so the caller's restart path re-lists — before propagating.  The
    caller applies the event and then advances the cursor itself (advance
    must follow a successful apply: on an apply failure the restart
    resumes AT the unapplied event and the server replays it).
    """
    try:
        # graftlint: disable=GL003 reason=watch streams are deliberately unbounded; liveness comes from server-side close + the resume discipline, not a deadline
        async for event in api.watch(
            kind, namespace, resource_version=get_cursor()
        ):
            version = (event.object.get("metadata") or {}).get(
                "resourceVersion"
            )
            if event.type == "BOOKMARK":
                # cursor-refresh only: its object is bare metadata that
                # would parse into a phantom object downstream
                if version:
                    set_cursor(version)
                continue
            yield event, version
    except WatchExpired:
        # compacted past the cursor: resuming would silently drop events
        set_cursor(None)
        raise


#: error-injection hook: (op, kind, name) -> Exception to raise, or None
ErrorHook = Callable[[str, str, str], Optional[Exception]]


class FakeKubeApi(KubeApi):
    #: watch-history ring size per kind: events older than this are
    #: compacted away and a resume from before them gets 410 (WatchExpired),
    #: the real apiserver's etcd-compaction behavior
    WATCH_HISTORY = 1024

    def __init__(self) -> None:
        self._objects: dict[str, dict[tuple[str, str], dict]] = {}
        self._logs: dict[tuple[str, str, bool], str] = {}
        self._rv = 0
        self._watches: list[_WatchRegistration] = []
        # per-kind replay buffer [(rv_at_event, event)] + highest rv ever
        # compacted out of it (0 = full history retained)
        self._history: dict[str, list[tuple[int, WatchEvent]]] = {}
        self._trimmed_through: dict[str, int] = {}
        self.error_hooks: list[ErrorHook] = []
        #: opt-in chaos seam (utils/faultinject.py FaultPlan): consulted on
        #: every API op ("kube.<op>"), at watch-stream open
        #: ("kube.watch_open.<kind>") and per delivered watch event
        #: ("kube.watch.<kind>") — declarative 409 storms, 410 relists and
        #: disconnect storms that replay deterministically
        self.fault_plan = None

    # --- error injection --------------------------------------------------
    def inject_errors(
        self,
        op: str,
        error_factory: Callable[[], Exception],
        times: int = 1,
        *,
        kind: Optional[str] = None,
    ) -> None:
        """Raise ``error_factory()`` for the next ``times`` calls of ``op``
        (op is 'get'/'list'/'create'/'patch'/'patch_status'/'delete'/
        'get_log'/'get_scale'/'patch_scale').  ``kind`` narrows the fault
        to one object kind — e.g. partitioning a leader away from its
        Lease (``kind="Lease"``) without touching its Pod/Podmortem
        traffic (tests/test_leader.py), or partitioning the autoscaler
        away from the Deployment scale subresource mid-scale-up
        (``kind="Deployment"``, tests/test_chaos.py)."""
        remaining = {"n": times}

        def hook(actual_op: str, actual_kind: str, name: str) -> Optional[Exception]:
            if kind is not None and actual_kind != kind:
                return None
            if actual_op == op and remaining["n"] > 0:
                remaining["n"] -= 1
                return error_factory()
            return None

        self.error_hooks.append(hook)

    def inject_conflicts(self, times: int, op: str = "patch_status") -> None:
        self.inject_errors(op, lambda: ConflictError("the object has been modified"), times)

    async def _check_hooks(self, op: str, kind: str, name: str) -> None:
        for hook in self.error_hooks:
            exc = hook(op, kind, name)
            if exc is not None:
                raise exc
        if self.fault_plan is not None:
            # apply_async: a delay/jitter action holds the op without
            # blocking the loop (latency-shaped apiserver)
            await self.fault_plan.apply_async(f"kube.{op}", kind=kind, name=name)

    # --- store helpers ----------------------------------------------------
    def _bucket(self, kind: str) -> dict[tuple[str, str], dict]:
        return self._objects.setdefault(kind, {})

    def _next_rv(self) -> str:
        self._rv += 1
        return str(self._rv)

    def _notify(self, event_type: str, kind: str, obj: dict) -> None:
        namespace = obj.get("metadata", {}).get("namespace")
        event = WatchEvent(event_type, copy.deepcopy(obj))
        history = self._history.setdefault(kind, [])
        history.append((self._rv, event))
        if len(history) > self.WATCH_HISTORY:
            trimmed_rv, _ = history.pop(0)
            self._trimmed_through[kind] = max(
                self._trimmed_through.get(kind, 0), trimmed_rv
            )
        for registration in list(self._watches):
            if registration.kind != kind:
                continue
            if registration.namespace is not None and registration.namespace != namespace:
                continue
            registration.queue.put_nowait(
                WatchEvent(event_type, copy.deepcopy(obj))
            )

    # --- KubeApi ----------------------------------------------------------
    async def get(self, kind: str, name: str, namespace: str) -> dict:
        await self._check_hooks("get", kind, name)
        obj = self._bucket(kind).get((namespace, name))
        if obj is None:
            raise NotFoundError(f"{kind} {namespace}/{name} not found")
        return copy.deepcopy(obj)

    async def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[LabelSelector] = None,
    ) -> list[dict]:
        await self._check_hooks("list", kind, "*")
        out = []
        for (ns, _), obj in sorted(self._bucket(kind).items()):
            if namespace is not None and ns != namespace:
                continue
            if label_selector is not None and not label_selector.matches(
                obj.get("metadata", {}).get("labels") or {}
            ):
                continue
            out.append(copy.deepcopy(obj))
        return out

    async def create(self, kind: str, obj: dict) -> dict:
        meta = obj.setdefault("metadata", {})
        name, namespace = meta.get("name"), meta.get("namespace")
        if not name or not namespace:
            raise ApiError(f"{kind} requires metadata.name and metadata.namespace", 422)
        await self._check_hooks("create", kind, name)
        bucket = self._bucket(kind)
        if (namespace, name) in bucket:
            raise ConflictError(f"{kind} {namespace}/{name} already exists")
        stored = copy.deepcopy(obj)
        stored["metadata"].setdefault("uid", str(uuid.uuid4()))
        stored["metadata"].setdefault("creationTimestamp", now_iso())
        stored["metadata"]["resourceVersion"] = self._next_rv()
        bucket[(namespace, name)] = stored
        self._notify("ADDED", kind, stored)
        return copy.deepcopy(stored)

    async def _patch_impl(
        self,
        op: str,
        kind: str,
        name: str,
        namespace: str,
        patch: dict,
        resource_version: Optional[str],
    ) -> dict:
        await self._check_hooks(op, kind, name)
        bucket = self._bucket(kind)
        current = bucket.get((namespace, name))
        if current is None:
            raise NotFoundError(f"{kind} {namespace}/{name} not found")
        if resource_version is not None and current["metadata"].get("resourceVersion") != resource_version:
            raise ConflictError(
                f"Operation cannot be fulfilled on {kind} {namespace}/{name}: "
                f"the object has been modified"
            )
        merged = _deep_merge(current, patch)
        merged["metadata"]["resourceVersion"] = self._next_rv()
        bucket[(namespace, name)] = merged
        self._notify("MODIFIED", kind, merged)
        return copy.deepcopy(merged)

    async def patch(
        self,
        kind: str,
        name: str,
        namespace: str,
        patch: dict,
        *,
        resource_version: Optional[str] = None,
    ) -> dict:
        return await self._patch_impl("patch", kind, name, namespace, patch, resource_version)

    async def patch_status(
        self,
        kind: str,
        name: str,
        namespace: str,
        status: dict,
        *,
        resource_version: Optional[str] = None,
    ) -> dict:
        return await self._patch_impl(
            "patch_status", kind, name, namespace, {"status": status}, resource_version
        )

    async def delete(self, kind: str, name: str, namespace: str) -> None:
        await self._check_hooks("delete", kind, name)
        bucket = self._bucket(kind)
        obj = bucket.pop((namespace, name), None)
        if obj is None:
            raise NotFoundError(f"{kind} {namespace}/{name} not found")
        # deletion is a store write: it gets its own resourceVersion (so a
        # watch resume strictly after the previous event still replays it)
        obj["metadata"]["resourceVersion"] = self._next_rv()
        self._notify("DELETED", kind, obj)

    # --- scale subresource ------------------------------------------------
    async def get_scale(self, kind: str, name: str, namespace: str) -> dict:
        await self._check_hooks("get_scale", kind, name)
        obj = self._bucket(kind).get((namespace, name))
        if obj is None:
            raise NotFoundError(f"{kind} {namespace}/{name} not found")
        spec_replicas = (obj.get("spec") or {}).get("replicas")
        return {
            "apiVersion": "autoscaling/v1",
            "kind": "Scale",
            "metadata": {
                "name": name,
                "namespace": namespace,
                "resourceVersion": obj["metadata"].get("resourceVersion"),
            },
            "spec": {"replicas": int(spec_replicas or 0)},
            "status": {
                "replicas": int(
                    (obj.get("status") or {}).get("replicas")
                    or spec_replicas
                    or 0
                ),
            },
        }

    async def patch_scale(
        self,
        kind: str,
        name: str,
        namespace: str,
        replicas: int,
        *,
        resource_version: Optional[str] = None,
    ) -> dict:
        await self._check_hooks("patch_scale", kind, name)
        bucket = self._bucket(kind)
        current = bucket.get((namespace, name))
        if current is None:
            raise NotFoundError(f"{kind} {namespace}/{name} not found")
        if (
            resource_version is not None
            and current["metadata"].get("resourceVersion") != resource_version
        ):
            raise ConflictError(
                f"Operation cannot be fulfilled on {kind} "
                f"{namespace}/{name}: the object has been modified"
            )
        merged = _deep_merge(current, {"spec": {"replicas": int(replicas)}})
        merged["metadata"]["resourceVersion"] = self._next_rv()
        bucket[(namespace, name)] = merged
        # a scale write IS a Deployment modification: watchers of the kind
        # see it exactly as they would from the real apiserver
        self._notify("MODIFIED", kind, merged)
        return {
            "apiVersion": "autoscaling/v1",
            "kind": "Scale",
            "metadata": {
                "name": name,
                "namespace": namespace,
                "resourceVersion": merged["metadata"]["resourceVersion"],
            },
            "spec": {"replicas": int(replicas)},
            "status": {"replicas": int(replicas)},
        }

    # --- pod logs ---------------------------------------------------------
    def set_pod_log(self, namespace: str, name: str, text: str, *, previous: bool = False) -> None:
        self._logs[(namespace, name, previous)] = text

    async def get_log(
        self,
        name: str,
        namespace: str,
        *,
        container: Optional[str] = None,
        previous: bool = False,
        tail_bytes: Optional[int] = None,
    ) -> str:
        await self._check_hooks("get_log", "Pod", name)
        text = self._logs.get((namespace, name, previous))
        if text is None and previous:
            text = self._logs.get((namespace, name, False))
        if text is None:
            if (namespace, name) not in self._bucket("Pod"):
                raise NotFoundError(f"Pod {namespace}/{name} not found")
            return ""
        if tail_bytes is not None and len(text) > tail_bytes:
            text = text[-tail_bytes:]
        return text

    # --- watch ------------------------------------------------------------
    async def watch(  # type: ignore[override]
        self,
        kind: str,
        namespace: Optional[str] = None,
        resource_version: Optional[str] = None,
    ) -> AsyncIterator[WatchEvent]:
        if self.fault_plan is not None:
            # stream-open faults: inject a 410 on a resume attempt
            # (WatchExpired forces the consumer's relist path) or refuse the
            # connection (WatchClosed) before any replay happens
            await self.fault_plan.apply_async(
                f"kube.watch_open.{kind}", resource_version=resource_version
            )
        replayed: list[WatchEvent] = []
        if resource_version is not None:
            since = int(resource_version)
            if since < self._trimmed_through.get(kind, 0):
                raise WatchExpired(
                    f"resourceVersion {resource_version} for {kind} is too "
                    f"old (compacted through "
                    f"{self._trimmed_through.get(kind, 0)})"
                )
            for rv, event in self._history.get(kind, []):
                if rv <= since:
                    continue
                obj_ns = event.object.get("metadata", {}).get("namespace")
                if namespace is not None and obj_ns != namespace:
                    continue
                replayed.append(
                    WatchEvent(event.type, copy.deepcopy(event.object))
                )
        # snapshot-then-register runs with no await in between, so no event
        # can land in both the replay list and the live queue
        registration = _WatchRegistration(kind=kind, namespace=namespace)
        self._watches.append(registration)
        try:
            for event in replayed:
                if self.fault_plan is not None:
                    # per-event faults ("drop the stream after N events"):
                    # WatchClosed/WatchExpired here reaches the consumer
                    # exactly as a server-side stream death would
                    await self.fault_plan.apply_async(f"kube.watch.{kind}", event=event.type)
                yield event
            while True:
                event = await registration.queue.get()
                if isinstance(event, Exception):
                    raise WatchClosed(str(event)) from event
                if self.fault_plan is not None:
                    await self.fault_plan.apply_async(f"kube.watch.{kind}", event=event.type)
                yield event
        finally:
            if registration in self._watches:
                self._watches.remove(registration)

    def close_watches(self, error: str = "server closed the watch") -> int:
        """Simulate the apiserver dropping all watch streams."""
        closed = 0
        for registration in list(self._watches):
            registration.queue.put_nowait(RuntimeError(error))
            closed += 1
        return closed

    def bookmark_watches(self, kind: Optional[str] = None) -> int:
        """Deliver a BOOKMARK event (current resourceVersion, no object
        payload) to open watches — the apiserver's periodic cursor
        refresh when allowWatchBookmarks is on."""
        sent = 0
        for registration in list(self._watches):
            if kind is not None and registration.kind != kind:
                continue
            registration.queue.put_nowait(WatchEvent(
                "BOOKMARK",
                {
                    "kind": registration.kind,
                    "metadata": {"resourceVersion": str(self._rv)},
                },
            ))
            sent += 1
        return sent

    def compact_watch_history(self, kind: str) -> None:
        """Drop the retained event history for ``kind`` — a subsequent
        resume from any pre-compaction resourceVersion gets 410
        (WatchExpired), the etcd-compaction path."""
        self._history[kind] = []
        self._trimmed_through[kind] = self._rv

    async def list_rv(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[LabelSelector] = None,
    ) -> tuple[list[dict], Optional[str]]:
        return await self.list(kind, namespace, label_selector), str(self._rv)

    # --- typed convenience (tests) ---------------------------------------
    async def create_obj(self, obj: Any) -> dict:
        return await self.create(obj.kind, obj.to_dict())
