"""Git pattern sync: PatternLibrary reconciler + repository sync service.

Parity with reference PatternLibraryReconciler + PatternSyncService
(SURVEY.md §3.4): clone-or-pull each spec.repository into
``<cache>/<library>/<repo>``, refresh on ``spec.refreshInterval``
(30s/5m/1h/2d/1h30m), discover available libraries, and maintain status —
including per-repo ``syncedRepositories`` entries, which the reference CRD
declares but its reconciler stubs out (PatternLibraryReconciler.java:171-176).

Improvements over the reference, both called out by the survey:
- the credentials secret namespace follows the secretRef / CR namespace
  instead of a hardcoded ``podmortem-system`` (:149);
- after a successful sync the in-process PatternEngine reloads, so new
  patterns apply without a restart (the reference relies on the parser
  service re-reading the PVC).

Git runs as a subprocess (the JGit role) with credentials injected through
a temporary ``GIT_ASKPASS`` helper so tokens never appear in argv or remote
URLs on disk.
"""

from __future__ import annotations

import asyncio
import datetime
import logging
import os
import stat
import tempfile
from dataclasses import dataclass
from pathlib import Path
from typing import Optional

from ..patterns.engine import PatternEngine
from ..patterns.loader import discover_library_files
from ..schema.crds import (
    PatternLibrary,
    PatternRepository,
    SyncedRepository,
    parse_refresh_interval,
)
from ..schema.kube import Secret
from ..schema.meta import now_iso
from ..utils.config import OperatorConfig
from .kubeapi import ApiError, KubeApi, NotFoundError

log = logging.getLogger(__name__)


class GitSyncError(Exception):
    pass


@dataclass
class SyncOutcome:
    repo_name: str
    commit: Optional[str] = None
    pattern_count: int = 0
    error: Optional[str] = None

    @property
    def ok(self) -> bool:
        return self.error is None


class GitSyncService:
    def __init__(self, config: Optional[OperatorConfig] = None) -> None:
        self.config = config or OperatorConfig()
        #: opt-in chaos seam (utils/faultinject.py): consulted per git verb
        #: under "git.<verb>" — e.g. fail a clone twice then let it succeed
        self.fault_plan = None

    # ------------------------------------------------------------------
    async def _git(
        self,
        *args: str,
        cwd: Optional[str] = None,
        token: Optional[str] = None,
    ) -> str:
        env = dict(os.environ)
        env["GIT_TERMINAL_PROMPT"] = "0"
        askpass_path: Optional[str] = None
        if token:
            # username/token both answered by the helper; covers the
            # reference's user:pass and bare-token forms (PatternSyncService
            # .java:141-151) without leaking the token into argv
            fd, askpass_path = tempfile.mkstemp(prefix="askpass-", suffix=".sh")
            user = "token"
            if ":" in token:
                user, token = token.split(":", 1)
            with os.fdopen(fd, "w") as f:
                f.write(
                    "#!/bin/sh\ncase \"$1\" in\n*sername*) echo '%s' ;;\n*) echo '%s' ;;\nesac\n"
                    % (user.replace("'", ""), token.replace("'", ""))
                )
            os.chmod(askpass_path, stat.S_IRWXU)
            env["GIT_ASKPASS"] = askpass_path
        # human-readable verb for error messages: skip -C <path> and flags
        arg_list = list(args)
        verb_args = arg_list[2:] if arg_list[:1] == ["-C"] else arg_list
        verb = next((a for a in verb_args if not a.startswith("-")), "command")
        if self.fault_plan is not None:
            # chaos seam: injected GitSyncError/OSError surfaces exactly as
            # a real subprocess failure would (SyncOutcome.error populated,
            # per-repo status entry "Failed")
            self.fault_plan.apply(f"git.{verb}", cwd=cwd)
        try:
            proc = await asyncio.create_subprocess_exec(
                self.config.git_binary,
                *args,
                cwd=cwd,
                env=env,
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.PIPE,
            )
            try:
                stdout, stderr = await asyncio.wait_for(
                    proc.communicate(), timeout=self.config.sync_timeout_s
                )
            except asyncio.TimeoutError:
                proc.kill()
                raise GitSyncError(f"git {verb} timed out")
            if proc.returncode != 0:
                raise GitSyncError(
                    f"git {verb} failed: {stderr.decode(errors='replace').strip()[:500]}"
                )
            return stdout.decode(errors="replace")
        finally:
            if askpass_path:
                try:
                    os.unlink(askpass_path)
                except OSError:
                    pass

    # ------------------------------------------------------------------
    async def sync_repository(
        self,
        library_name: str,
        repo: PatternRepository,
        *,
        token: Optional[str] = None,
    ) -> SyncOutcome:
        """Clone-or-pull (idempotent/incremental, reference
        PatternSyncService.java:42-58)."""
        target = Path(self.config.pattern_cache_directory) / library_name / (repo.name or "repo")
        outcome = SyncOutcome(repo_name=repo.name or "repo")
        try:
            if (target / ".git").is_dir():
                await self._git("-C", str(target), "fetch", "origin", token=token)
                await self._git(
                    "-C", str(target), "reset", "--hard", f"origin/{repo.branch}", token=token
                )
            else:
                target.parent.mkdir(parents=True, exist_ok=True)
                await self._git(
                    "clone",
                    "--depth", "1",
                    "--branch", repo.branch,
                    repo.url or "",
                    str(target),
                    token=token,
                )
            commit = (await self._git("-C", str(target), "rev-parse", "HEAD")).strip()
            outcome.commit = commit
            outcome.pattern_count = len(discover_library_files(target))
            if outcome.pattern_count == 0:
                log.warning("repo %s synced but contains no pattern YAMLs (reference "
                            "validatePatterns warning, PatternSyncService.java:228-245)",
                            repo.name)
        except GitSyncError as exc:
            outcome.error = str(exc)
        except OSError as exc:
            outcome.error = f"filesystem error: {exc}"
        return outcome


class PatternLibraryReconciler:
    def __init__(
        self,
        api: KubeApi,
        sync: Optional[GitSyncService] = None,
        *,
        engine: Optional[PatternEngine] = None,
        config: Optional[OperatorConfig] = None,
    ) -> None:
        self.api = api
        self.config = config or OperatorConfig()
        self.sync = sync or GitSyncService(self.config)
        self.engine = engine

    # ------------------------------------------------------------------
    def needs_sync(self, library: PatternLibrary, *, now: Optional[datetime.datetime] = None) -> bool:
        """now > lastSyncTime + refreshInterval (reference :207-245)."""
        status = library.status
        if status is None or not status.last_sync_time:
            return True
        try:
            last = datetime.datetime.fromisoformat(status.last_sync_time.replace("Z", "+00:00"))
        except ValueError:
            return True
        interval = parse_refresh_interval(library.spec.refresh_interval)
        now = now or datetime.datetime.now(datetime.timezone.utc)
        return now >= last + datetime.timedelta(seconds=interval)

    async def _credentials_for(self, library: PatternLibrary, repo: PatternRepository) -> Optional[str]:
        """Token from the repo's secretRef; namespace defaults to the CR's
        (fixing the reference's hardcoded podmortem-system, :145-161)."""
        creds = repo.credentials
        if creds is None or creds.secret_ref is None or not creds.secret_ref.name:
            return None
        ref = creds.secret_ref
        namespace = ref.namespace or library.metadata.namespace or "default"
        try:
            secret = Secret.parse(await asyncio.wait_for(
                self.api.get("Secret", ref.name, namespace),
                timeout=self.config.kube_call_timeout_s,
            ))
        except NotFoundError:
            log.warning("credentials secret %s/%s not found", namespace, ref.name)
            return None
        except (ApiError, asyncio.TimeoutError) as exc:
            log.warning("credentials secret fetch failed: %s",
                        str(exc) or "timed out")
            return None
        return secret.decoded(ref.key or "token")

    # ------------------------------------------------------------------
    async def reconcile(self, library: PatternLibrary, *, force: bool = False) -> Optional[int]:
        """Sync all repos if due; returns seconds until next sync (the
        rescheduleAfter contract, reference :94-95), or None on no-op."""
        interval = parse_refresh_interval(library.spec.refresh_interval)
        if not force and not self.needs_sync(library):
            return None
        name = library.qualified_name()
        await self._patch_status(library, {"phase": "Syncing", "message": "sync in progress"})
        outcomes: list[SyncOutcome] = []
        for repo in library.spec.repositories:
            token = await self._credentials_for(library, repo)
            outcome = await self.sync.sync_repository(
                library.metadata.name or "library", repo, token=token
            )
            outcomes.append(outcome)
            if outcome.ok:
                log.info("synced %s/%s @ %s (%d pattern files)",
                         name, outcome.repo_name, (outcome.commit or "")[:12], outcome.pattern_count)
            else:
                log.error("sync failed %s/%s: %s", name, outcome.repo_name, outcome.error)
        from ..patterns.loader import available_libraries

        libs = available_libraries(self.config.pattern_cache_directory)
        failures = [o for o in outcomes if not o.ok]
        phase = "Ready" if not failures else "Failed"
        message = (
            f"{len(outcomes) - len(failures)}/{len(outcomes)} repositories synced"
            if outcomes
            else "no repositories configured"
        )
        synced = [
            SyncedRepository(
                name=o.repo_name,
                last_sync_time=now_iso(),
                last_sync_commit=o.commit,
                status="Synced" if o.ok else "Failed",
                message=o.error,
                pattern_count=o.pattern_count,
            )
            for o in outcomes
        ]
        from ..schema.serde import to_dict

        await self._patch_status(
            library,
            {
                "phase": phase,
                "message": message,
                "lastSyncTime": now_iso(),
                "syncedRepositories": [to_dict(s) for s in synced],
                "availableLibraries": libs,
            },
        )
        if self.engine is not None:
            await asyncio.to_thread(self.engine.reload)
        return interval

    async def _patch_status(self, library: PatternLibrary, status: dict) -> None:
        try:
            await asyncio.wait_for(
                self.api.patch_status(
                    "PatternLibrary", library.metadata.name,
                    library.metadata.namespace, status,
                ),
                timeout=self.config.kube_call_timeout_s,
            )
        except (ApiError, asyncio.TimeoutError) as exc:
            log.warning("patternlibrary status patch failed for %s: %s",
                        library.qualified_name(), str(exc) or "timed out")

    # ------------------------------------------------------------------
    async def run(self, stop: asyncio.Event, *, poll_interval_s: float = 15.0) -> None:
        """Self-rescheduling loop: check each CR's due time periodically
        (the reference reschedules per-CR via the operator SDK; a poll at
        15s granularity gives the same behaviour within one tick)."""
        while not stop.is_set():
            try:
                libraries = await asyncio.wait_for(
                    self.api.list("PatternLibrary"),
                    timeout=self.config.kube_call_timeout_s,
                )
                for raw in libraries:
                    if stop.is_set():
                        return
                    await self.reconcile(PatternLibrary.parse(raw))
            except (ApiError, asyncio.TimeoutError) as exc:
                log.warning("patternlibrary list failed: %s",
                            str(exc) or "timed out")
            try:
                await asyncio.wait_for(stop.wait(), timeout=poll_interval_s)
            except asyncio.TimeoutError:
                pass
