"""SLO-judged serving-fleet autoscaler — scale-to-zero included
(docs/SCALING.md).

A leader-only control loop (same ``_leader_cycle`` discipline as the
other reconcilers) that sizes the serving Deployment through the
``scale`` subresource from TWO live signals:

- the router's fleet rollup (``GET /fleet``): queue depth, inflight, and
  ``fleet_pressure`` — the least-loaded healthy replica's queue pressure,
  the same signal the overload ladder keys on.  Scale-up is the rung
  ABOVE degrade: when even the best offer the fleet can make crosses
  ``target_pressure``, add a replica instead of degrading deeper;
- the SLO ledger's per-class attainment (obs/sloledger.py): a class
  below its attainment target with work pending bursts the fleet out
  even when raw pressure looks tolerable — the autoscaler is judged on
  attainment, not utilisation.

Scale-DOWN is deliberately slower than scale-up: only after the fleet
has been completely idle (no queue, no inflight, no pending admissions)
for ``idle_s`` does the desired count drop to ``min_replicas`` — and
when that floor is zero, to ZERO.  The first pending arrival against an
empty fleet wakes it back up (the ``cold_start`` bench lane measures
token-one latency from exactly this state).

Every decision is observable: ``podmortem_autoscale_{up,down,to_zero,
blocked}_total`` counters, ``desired_replicas`` / ``last_scale_reason``
on ``GET /fleet``, and a log line per actuation.  Apiserver calls are
bounded by ``kube_timeout_s`` (graftlint GL003); a failed patch is a
blocked decision retried next tick, never a crash.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass
from typing import Callable, Optional

from ..utils.config import OperatorConfig
from ..utils.timing import METRICS, MetricsRegistry
from .kubeapi import KubeApi

log = logging.getLogger(__name__)

__all__ = ["AutoscaleController", "ScaleDecision"]


@dataclass(frozen=True)
class ScaleDecision:
    """One autoscale verdict: the target replica count, what kind of move
    it is (``up`` / ``down`` / ``to_zero`` / ``hold`` / ``blocked``), and
    the human-readable why that ``/fleet`` surfaces."""

    desired: int
    action: str
    reason: str


class AutoscaleController:
    """Size one serving Deployment from fleet pressure + SLO attainment."""

    def __init__(
        self,
        api: KubeApi,
        *,
        deployment: str,
        namespace: str = "default",
        min_replicas: int = 0,
        max_replicas: int = 8,
        target_pressure: float = 4.0,
        idle_s: float = 600.0,
        interval_s: float = 15.0,
        kube_timeout_s: float = 15.0,
        attainment_target: float = 0.9,
        fleet: Optional[Callable[[], dict]] = None,
        attainment: Optional[Callable[[], "dict[str, Optional[float]]"]] = None,
        pending: Optional[Callable[[], int]] = None,
        metrics: Optional[MetricsRegistry] = None,
        clock: Optional[Callable[[], float]] = None,
    ) -> None:
        self.api = api
        self.deployment = deployment
        self.namespace = namespace
        self.min_replicas = max(0, min_replicas)
        self.max_replicas = max(self.min_replicas, 1, max_replicas)
        self.target_pressure = target_pressure
        self.idle_s = idle_s
        self.interval_s = interval_s
        #: per-call apiserver budget (graftlint GL003)
        self.kube_timeout_s = kube_timeout_s
        self.attainment_target = attainment_target
        #: fleet rollup feed — the ``fleet`` half of
        #: ``OpenAICompatProvider.fleet_view()`` (queueDepth / inflight /
        #: pressure); None or an empty dict reads as "no signal"
        self.fleet = fleet
        #: per-class SLO attainment feed (SLOLedger.attainment_by_class)
        self.attainment = attainment
        #: admitted-but-unsettled work feed (SLOLedger.pending) — what
        #: wakes a scaled-to-zero fleet
        self.pending = pending
        self.metrics = metrics or METRICS
        self._clock = clock or time.monotonic
        #: when the fleet last went COMPLETELY idle (None = busy now)
        self._idle_since: Optional[float] = None
        #: last decision, surfaced on GET /fleet
        self.desired_replicas: Optional[int] = None
        self.last_scale_reason: str = ""

    @classmethod
    def from_config(
        cls,
        api: KubeApi,
        config: OperatorConfig,
        *,
        fleet: Optional[Callable[[], dict]] = None,
        attainment: Optional[Callable[[], "dict[str, Optional[float]]"]] = None,
        pending: Optional[Callable[[], int]] = None,
        metrics: Optional[MetricsRegistry] = None,
    ) -> "AutoscaleController":
        namespace = (
            config.autoscale_namespace
            or getattr(api, "namespace", None)
            or "default"
        )
        return cls(
            api,
            deployment=config.autoscale_deployment,
            namespace=namespace,
            min_replicas=config.autoscale_min_replicas,
            max_replicas=config.autoscale_max_replicas,
            target_pressure=config.autoscale_target_pressure,
            idle_s=config.scale_to_zero_idle_s,
            interval_s=config.autoscale_interval_s,
            kube_timeout_s=config.kube_call_timeout_s,
            attainment_target=config.slo_attainment_target,
            fleet=fleet,
            attainment=attainment,
            pending=pending,
            metrics=metrics,
        )

    # -- policy (pure: no I/O, injectable clock) -----------------------
    def decide(self, current: int, *, now: Optional[float] = None) -> ScaleDecision:
        """The sizing policy for one tick.  Pure so tests drive it
        directly: reads the signal feeds, tracks the idle window, returns
        what the fleet SHOULD be — ``tick()`` does the actuation."""
        now = self._clock() if now is None else now
        rollup = (self.fleet() if self.fleet is not None else {}) or {}
        queue = int(rollup.get("queueDepth") or 0)
        inflight = int(rollup.get("inflight") or 0)
        pressure = rollup.get("pressure")
        pending = int(self.pending()) if self.pending is not None else 0
        busy = (queue + inflight + pending) > 0
        if busy:
            self._idle_since = None
        elif self._idle_since is None:
            self._idle_since = now

        # wake-from-zero: ANY admitted work against an empty fleet brings
        # at least one replica back — this transition is the cold-start
        # path the bench lane times
        if current <= 0:
            if busy:
                desired = max(1, self.min_replicas)
                return ScaleDecision(
                    desired, "up",
                    f"wake-from-zero: {pending} pending / {queue} queued "
                    f"arrivals against an empty fleet",
                )
            return ScaleDecision(
                max(current, self.min_replicas),
                "up" if current < self.min_replicas else "hold",
                "idle at zero" if self.min_replicas <= 0
                else f"floor min_replicas={self.min_replicas}",
            )

        # burst out: storm pressure (the overload ladder's fleet_pressure
        # signal) or an SLO class already missing its target with work
        # still pending
        burst_reason = None
        if pressure is not None and float(pressure) >= self.target_pressure:
            burst_reason = (
                f"fleet_pressure {float(pressure):.1f} >= "
                f"target {self.target_pressure:.1f}"
            )
        if burst_reason is None:
            # role-aware signal (fabric/disagg.py): a disaggregated
            # fleet can starve ONE role behind a calm aggregate — all
            # prefill replicas saturated while decode sits idle keeps
            # fleet_pressure (the best offer anywhere) low.  Judge each
            # role tier by its own mean pressure per ready replica.
            for role, tier in sorted((rollup.get("roles") or {}).items()):
                if role == "mixed":
                    continue  # the aggregate signal already covers mixed
                ready = max(1, int(tier.get("ready") or 0))
                tier_pressure = float(tier.get("pressure") or 0) / ready
                if tier_pressure >= self.target_pressure:
                    burst_reason = (
                        f"role {role!r} pressure {tier_pressure:.1f}/replica"
                        f" >= target {self.target_pressure:.1f} "
                        f"({tier.get('ready')}/{tier.get('replicas')} ready)"
                    )
                    break
        if burst_reason is None and pending > 0 and self.attainment is not None:
            lagging = [
                (cls, att)
                for cls, att in sorted((self.attainment() or {}).items())
                if att is not None and att < self.attainment_target
            ]
            if lagging:
                cls, att = lagging[0]
                burst_reason = (
                    f"slo class {cls!r} attainment {att:.2f} < "
                    f"{self.attainment_target:.2f} with {pending} pending"
                )
        if burst_reason is not None:
            if current >= self.max_replicas:
                return ScaleDecision(
                    current, "blocked",
                    f"{burst_reason}, but at max_replicas={self.max_replicas}",
                )
            return ScaleDecision(current + 1, "up", burst_reason)

        # settle down: only after a FULL idle window, and all the way to
        # the floor — replicas are interchangeable behind the ring, so
        # there is nothing to drain gradually once nothing is in flight
        idle_for = (now - self._idle_since) if self._idle_since is not None else 0.0
        if not busy and idle_for >= self.idle_s and current > self.min_replicas:
            action = "to_zero" if self.min_replicas <= 0 else "down"
            return ScaleDecision(
                self.min_replicas, action,
                f"idle {idle_for:.0f}s >= {self.idle_s:.0f}s",
            )
        return ScaleDecision(current, "hold",
                             "busy" if busy else f"idle {idle_for:.0f}s")

    # -- actuation -----------------------------------------------------
    async def tick(self) -> ScaleDecision:
        """One control cycle: read the scale subresource, decide, patch.
        A patch failure (partition, conflict) demotes the decision to
        ``blocked`` — the signal feeds are live, so next tick re-derives
        a fresh target instead of retrying a stale one."""
        scale = await asyncio.wait_for(
            self.api.get_scale("Deployment", self.deployment, self.namespace),
            timeout=self.kube_timeout_s,
        )
        current = int((scale.get("spec") or {}).get("replicas") or 0)
        decision = self.decide(current)
        self.desired_replicas = decision.desired
        self.last_scale_reason = decision.reason
        if decision.action == "blocked":
            self.metrics.incr("autoscale_blocked")
            log.warning("autoscale blocked at %d: %s", current, decision.reason)
            return decision
        if decision.action == "hold" or decision.desired == current:
            return decision
        try:
            await asyncio.wait_for(
                self.api.patch_scale(
                    "Deployment", self.deployment, self.namespace,
                    decision.desired,
                    resource_version=(scale.get("metadata") or {}).get(
                        "resourceVersion"
                    ),
                ),
                timeout=self.kube_timeout_s,
            )
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # noqa: BLE001 - a failed patch is a blocked
            # decision retried next tick, never a controller crash
            self.metrics.incr("autoscale_blocked")
            log.warning("autoscale patch %s/%s -> %d failed (%s); retrying "
                        "next tick", self.namespace, self.deployment,
                        decision.desired, exc)
            return ScaleDecision(decision.desired, "blocked",
                                 f"{decision.reason}; patch failed: {exc}")
        self.metrics.incr(f"autoscale_{decision.action}")
        log.info("autoscale %s: %s/%s %d -> %d (%s)", decision.action,
                 self.namespace, self.deployment, current, decision.desired,
                 decision.reason)
        return decision

    async def run(self, stop: asyncio.Event) -> None:
        """Tick every ``interval_s`` until ``stop`` — leader-only (spawned
        by ``_spawn_control_tasks``): two replicas scaling one Deployment
        would fight through the rv guard forever."""
        while not stop.is_set():
            try:
                await asyncio.wait_for(stop.wait(), timeout=self.interval_s)
                return  # stopping
            except asyncio.TimeoutError:
                pass
            try:
                await self.tick()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - NotFound before first deploy,
                # apiserver blips: the loop must outlive one bad tick
                log.warning("autoscale tick failed", exc_info=True)

    # -- introspection -------------------------------------------------
    def view(self) -> dict:
        """The ``GET /fleet`` fields this controller owns."""
        return {
            "desiredReplicas": self.desired_replicas,
            "lastScaleReason": self.last_scale_reason or None,
        }
