"""Health + metrics HTTP endpoint (stdlib asyncio, no framework).

The reference serves MicroProfile health at ``/q/health/{live,ready}`` and
is probed by the kubelet (reference operator-deployment.yaml:61-78); it has
no metrics endpoint at all (SURVEY.md §5 tracing entry).  Here one tiny
asyncio HTTP server exposes:

- ``GET /healthz/live``  — liveness (event loop answers)
- ``GET /healthz/ready`` — readiness (pattern cache gating, health.py)
- ``GET /metrics``       — Prometheus text exposition of the per-stage
  latency registry (detect→collect→parse→prefill→decode→store), scrapeable
  by any standard collector — the observability the p50<2s SLO needs
- ``GET /metrics.json``  — the same data as a JSON snapshot
- ``GET /incidents``     — the incident-memory store, newest first
  (``?limit=N``; docs/MEMORY.md)
- ``GET /incidents/query`` — free-text similarity query over the incident
  index (``?q=...&k=N``): which remembered failures does this log line
  look like?

Probe responses are JSON; failures return 503 so the kubelet treats the
pod exactly as it treats the reference's native binary.
"""

from __future__ import annotations

import asyncio
import json
import logging
import urllib.parse
from typing import TYPE_CHECKING, Optional

from ..utils.timing import METRICS, MetricsRegistry
from .health import LivenessCheck, ReadinessCheck

if TYPE_CHECKING:  # import cycle guard: memory is constructed by the app
    from ..memory import IncidentMemory

log = logging.getLogger(__name__)

_MAX_REQUEST_LINE = 8192


class HealthServer:
    """Minimal HTTP/1.1 server for kubelet probes and metrics scrapes.

    Close-delimited responses (``Connection: close``) keep the parser
    trivial: read the request line, ignore headers, answer, close.
    """

    def __init__(
        self,
        liveness: LivenessCheck,
        readiness: ReadinessCheck,
        *,
        metrics: Optional[MetricsRegistry] = None,
        memory: "Optional[IncidentMemory]" = None,
        incidents_token: Optional[str] = None,
        host: str = "0.0.0.0",
        port: int = 8080,
    ) -> None:
        self.liveness = liveness
        self.readiness = readiness
        self.metrics = metrics or METRICS
        self.memory = memory
        #: bearer token gating /incidents* (None/"" = open); probes and
        #: /metrics stay unauthenticated — incident records quote log
        #: evidence, which is more sensitive than latency numbers
        self.incidents_token = incidents_token or None
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None

    @property
    def bound_port(self) -> Optional[int]:
        """The actual port (differs from ``port`` when 0 = ephemeral)."""
        if self._server is None or not self._server.sockets:
            return None
        return self._server.sockets[0].getsockname()[1]

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        log.info("health server listening on %s:%s", self.host, self.bound_port)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    # ------------------------------------------------------------------
    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            try:
                line = await reader.readline()
            except ValueError:
                # readline() raises ValueError past the StreamReader limit
                # (a >64 KiB request line); drop the connection quietly —
                # this catch is deliberately NARROW so ValueErrors from
                # routing/health checks/metrics still surface in logs
                return
            if len(line) > _MAX_REQUEST_LINE or not line:
                return
            parts = line.decode("latin-1").split()
            if len(parts) < 2:
                return
            method, target = parts[0], parts[1]
            path, _, raw_query = target.partition("?")
            query = urllib.parse.parse_qs(raw_query)
            # drain the (bounded) header block; only Authorization is
            # consumed — the /incidents* routes may require a token
            authorization = ""
            for _ in range(100):
                try:
                    header = await reader.readline()
                except ValueError:
                    return
                if not header or header in (b"\r\n", b"\n"):
                    break
                if header.lower().startswith(b"authorization:"):
                    authorization = header.split(b":", 1)[1].strip().decode("latin-1")
            status, body = await self._route(
                method, path, query, authorization=authorization
            )
            if isinstance(body, bytes):  # pre-rendered (Prometheus text)
                payload = body
                content_type = b"text/plain; version=0.0.4; charset=utf-8"
            else:
                payload = json.dumps(body).encode()
                content_type = b"application/json"
            writer.write(
                b"HTTP/1.1 %d %s\r\n"
                b"Content-Type: %s\r\n"
                b"Content-Length: %d\r\n"
                b"Connection: close\r\n\r\n"
                % (status, b"OK" if status == 200 else b"ERR", content_type, len(payload))
            )
            if method != "HEAD":  # HEAD: headers only, no body
                writer.write(payload)
            await writer.drain()
        except (ConnectionError, asyncio.CancelledError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _route(
        self,
        method: str,
        path: str,
        query: "Optional[dict[str, list[str]]]" = None,
        *,
        authorization: str = "",
    ) -> "tuple[int, dict | bytes]":
        query = query or {}
        if method not in ("GET", "HEAD"):
            return 405, {"error": "method not allowed"}
        if path.startswith("/incidents") and self.incidents_token:
            import hmac

            if not hmac.compare_digest(
                authorization.encode(), f"Bearer {self.incidents_token}".encode()
            ):
                return 401, {"error": "missing or invalid bearer token"}
        if path in ("/healthz/live", "/livez"):
            status = await self.liveness.check()
            return (200 if status.ready else 503), {
                "status": "UP" if status.ready else "DOWN",
                "reason": status.reason,
            }
        if path in ("/healthz/ready", "/readyz"):
            status = await self.readiness.check()
            return (200 if status.ready else 503), {
                "status": "UP" if status.ready else "DOWN",
                "reason": status.reason,
            }
        if path == "/metrics":
            return 200, self.metrics.prometheus().encode()
        if path == "/metrics.json":
            return 200, self.metrics.snapshot()
        if path == "/incidents":
            if self.memory is None:
                return 404, {"error": "incident memory disabled"}
            try:
                limit = int(query.get("limit", ["100"])[0])
            except ValueError:
                return 400, {"error": "limit must be an integer"}
            # serialize off-loop and only the requested page — a full-store
            # to_dict on the probe loop would stall kubelet probes
            incidents = await asyncio.to_thread(
                self.memory.store.to_dicts, True, limit
            )
            return 200, {"count": len(self.memory.store), "incidents": incidents}
        if path == "/incidents/query":
            if self.memory is None:
                return 404, {"error": "incident memory disabled"}
            text = query.get("q", [""])[0]
            if not text.strip():
                return 400, {"error": "missing query parameter q"}
            try:
                k = int(query.get("k", ["3"])[0])
            except ValueError:
                return 400, {"error": "k must be an integer"}
            # embedding runs off-loop: a neural embedder must not stall
            # probe handling on this same server
            matches = await asyncio.to_thread(self.memory.query_text, text, k)
            payload = []
            for incident, score in matches:
                # re-serialize under the store lock: the Incident is live
                data = self.memory.store.dump(incident.fingerprint)
                if data is not None:
                    payload.append({"score": round(score, 4), **data})
            return 200, {"matches": payload}
        return 404, {"error": f"no route {path}"}
