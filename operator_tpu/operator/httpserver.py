"""Health + metrics HTTP endpoint (stdlib asyncio, no framework).

The reference serves MicroProfile health at ``/q/health/{live,ready}`` and
is probed by the kubelet (reference operator-deployment.yaml:61-78); it has
no metrics endpoint at all (SURVEY.md §5 tracing entry).  Here one tiny
asyncio HTTP server exposes:

- ``GET /healthz/live``  — liveness (event loop answers)
- ``GET /healthz/ready`` — readiness (pattern cache gating, health.py)
- ``GET /metrics``       — Prometheus text exposition of the per-stage
  latency registry (detect→collect→parse→prefill→decode→store), scrapeable
  by any standard collector — the observability the p50<2s SLO needs
- ``GET /metrics.json``  — the same data as a JSON snapshot
- ``GET /incidents``     — the incident-memory store, newest first
  (``?limit=N``; docs/MEMORY.md)
- ``GET /incidents/query`` — free-text similarity query over the incident
  index (``?q=...&k=N``): which remembered failures does this log line
  look like?
- ``GET /traces``        — the flight recorder's recent analysis traces
  (``?limit=N&blackbox=1``; docs/OBSERVABILITY.md)
- ``GET /traces/{id}``   — one trace: full span JSON plus the rendered
  flame-style text tree (the ``obs.view`` CLI's online twin)
- ``GET /fleet``         — fleet-wide perf roll-up: every routed serving
  replica's step-clock summary (decode MFU, host-gap fraction, slot
  occupancy, queue depth) plus step-weighted fleet aggregates, as fed by
  the background ``/healthz`` poll (docs/OBSERVABILITY.md "Step clock");
  token-gated like /incidents and /traces

Inbound W3C ``traceparent`` headers are honoured: the request handler
runs under a trace joining the caller's trace id, recorded into the same
flight recorder — a client can follow its own request into the operator.

Probe responses are JSON; failures return 503 so the kubelet treats the
pod exactly as it treats the reference's native binary.
"""

from __future__ import annotations

import asyncio
import json
import logging
import urllib.parse
from typing import TYPE_CHECKING, Callable, Optional

from ..obs import FlightRecorder, Tracer, parse_traceparent, render_tree
from ..utils.timing import METRICS, MetricsRegistry
from .health import LivenessCheck, ReadinessCheck

if TYPE_CHECKING:  # import cycle guard: memory is constructed by the app
    from ..memory import IncidentMemory

log = logging.getLogger(__name__)

_MAX_REQUEST_LINE = 8192


class HealthServer:
    """Minimal HTTP/1.1 server for kubelet probes and metrics scrapes.

    Close-delimited responses (``Connection: close``) keep the parser
    trivial: read the request line, ignore headers, answer, close.
    """

    def __init__(
        self,
        liveness: LivenessCheck,
        readiness: ReadinessCheck,
        *,
        metrics: Optional[MetricsRegistry] = None,
        memory: "Optional[IncidentMemory]" = None,
        recorder: Optional[FlightRecorder] = None,
        tracer: Optional[Tracer] = None,
        incidents_token: Optional[str] = None,
        fleet: Optional[Callable[[], dict]] = None,
        slo: Optional[Callable[[], dict]] = None,
        host: str = "0.0.0.0",
        port: int = 8080,
    ) -> None:
        self.liveness = liveness
        self.readiness = readiness
        self.metrics = metrics or METRICS
        self.memory = memory
        #: flight recorder behind GET /traces* (None = endpoints 404)
        self.recorder = recorder
        #: tracer for inbound-traceparent request traces (None = headers
        #: accepted but ignored)
        self.tracer = tracer
        #: bearer token gating /incidents* AND /traces* (None/"" = open);
        #: probes and /metrics stay unauthenticated — incident records and
        #: trace attributes quote pod identities and evidence, which is
        #: more sensitive than latency numbers
        self.incidents_token = incidents_token or None
        #: zero-arg callable returning the fleet perf roll-up
        #: (OpenAICompatProvider.fleet_view) behind GET /fleet (None =
        #: 404: no routed replica sets on this operator)
        self.fleet = fleet
        #: zero-arg callable returning the SLO ledger's current state
        #: (per-class pending depth + attainment, obs/sloledger.py) —
        #: folded into GET /healthz/ready so one probe answers both
        #: "am I up" and "am I keeping my SLOs" (None = omitted)
        self.slo = slo
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None

    @property
    def bound_port(self) -> Optional[int]:
        """The actual port (differs from ``port`` when 0 = ephemeral)."""
        if self._server is None or not self._server.sockets:
            return None
        return self._server.sockets[0].getsockname()[1]

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        log.info("health server listening on %s:%s", self.host, self.bound_port)

    async def stop(self) -> None:
        # swap-then-act: clear the attribute before awaiting so a concurrent
        # stop() can't close the same server twice across the suspension
        server, self._server = self._server, None
        if server is not None:
            server.close()
            await server.wait_closed()

    # ------------------------------------------------------------------
    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            try:
                line = await reader.readline()
            except ValueError:
                # readline() raises ValueError past the StreamReader limit
                # (a >64 KiB request line); drop the connection quietly —
                # this catch is deliberately NARROW so ValueErrors from
                # routing/health checks/metrics still surface in logs
                return
            if len(line) > _MAX_REQUEST_LINE or not line:
                return
            parts = line.decode("latin-1").split()
            if len(parts) < 2:
                return
            method, target = parts[0], parts[1]
            path, _, raw_query = target.partition("?")
            query = urllib.parse.parse_qs(raw_query)
            # drain the (bounded) header block; Authorization (the
            # /incidents* and /traces* token), traceparent (inbound W3C
            # trace context) and Accept (OpenMetrics negotiation for
            # /metrics) are the only headers consumed
            authorization = ""
            traceparent = ""
            accept = ""
            for _ in range(100):
                try:
                    header = await reader.readline()
                except ValueError:
                    return
                if not header or header in (b"\r\n", b"\n"):
                    break
                if header.lower().startswith(b"authorization:"):
                    authorization = header.split(b":", 1)[1].strip().decode("latin-1")
                elif header.lower().startswith(b"traceparent:"):
                    traceparent = header.split(b":", 1)[1].strip().decode("latin-1")
                elif header.lower().startswith(b"accept:"):
                    accept = header.split(b":", 1)[1].strip().decode("latin-1")
            remote = parse_traceparent(traceparent)
            if remote is not None and not self._authorized(authorization):
                # recording inbound request traces consumes ring slots; on
                # a token-gated deployment only token-holders may do that
                # (an unauthenticated client could otherwise churn every
                # forensic trace out of the bounded ring)
                remote = None
            # join the caller's distributed trace when one was offered: the
            # handler's work is recorded under THEIR trace id, findable via
            # GET /traces/{their-id} afterwards
            if remote is not None and self.tracer is not None:
                trace_ctx = self.tracer.trace(
                    f"http {path}", trace_id=remote[0], parent_id=remote[1],
                    attributes={"path": path},
                )
            else:
                import contextlib

                trace_ctx = contextlib.nullcontext()
            with trace_ctx:
                status, body = await self._route(
                    method, path, query, authorization=authorization,
                    accept=accept,
                )
            openmetrics = "application/openmetrics-text" in accept
            if isinstance(body, bytes):  # pre-rendered (Prometheus text)
                payload = body
                content_type = (
                    b"application/openmetrics-text; version=1.0.0; charset=utf-8"
                    if openmetrics
                    else b"text/plain; version=0.0.4; charset=utf-8"
                )
            else:
                payload = json.dumps(body).encode()
                content_type = b"application/json"
            writer.write(
                b"HTTP/1.1 %d %s\r\n"
                b"Content-Type: %s\r\n"
                b"Content-Length: %d\r\n"
                b"Connection: close\r\n\r\n"
                % (status, b"OK" if status == 200 else b"ERR", content_type, len(payload))
            )
            if method != "HEAD":  # HEAD: headers only, no body
                writer.write(payload)
            await writer.drain()
        except (ConnectionError, asyncio.CancelledError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    def _authorized(self, authorization: str) -> bool:
        """Bearer-token check shared by the /incidents|/traces route gate
        and the inbound-traceparent gate; no token configured = open."""
        if not self.incidents_token:
            return True
        import hmac

        return hmac.compare_digest(
            authorization.encode(), f"Bearer {self.incidents_token}".encode()
        )

    async def _route(
        self,
        method: str,
        path: str,
        query: "Optional[dict[str, list[str]]]" = None,
        *,
        authorization: str = "",
        accept: str = "",
    ) -> "tuple[int, dict | bytes]":
        query = query or {}
        if method not in ("GET", "HEAD"):
            return 405, {"error": "method not allowed"}
        if (
            path.startswith("/incidents")
            or path.startswith("/traces")
            or path.startswith("/fleet")
        ) and not self._authorized(authorization):
            return 401, {"error": "missing or invalid bearer token"}
        if path in ("/healthz/live", "/livez"):
            status = await self.liveness.check()
            return (200 if status.ready else 503), {
                "status": "UP" if status.ready else "DOWN",
                "reason": status.reason,
            }
        if path in ("/healthz/ready", "/readyz"):
            status = await self.readiness.check()
            payload: dict = {
                "status": "UP" if status.ready else "DOWN",
                "reason": status.reason,
            }
            if self.slo is not None:
                # per-class admission queue depth + attainment from the
                # SLO ledger — probes ignore extra keys, operators and
                # the storm harness read them
                try:
                    payload["slo"] = self.slo()
                except Exception:  # a ledger fault must not fail probes
                    payload["slo"] = None
            return (200 if status.ready else 503), payload
        if path == "/metrics":
            # OpenMetrics only on negotiation: exemplars (trace ids on the
            # podmortem_trace_* counters) are illegal in classic text 0.0.4
            # — a mid-line '#' would fail the WHOLE legacy scrape
            openmetrics = "application/openmetrics-text" in accept
            return 200, self.metrics.prometheus(openmetrics=openmetrics).encode()
        if path == "/metrics.json":
            return 200, self.metrics.snapshot()
        if path == "/fleet":
            if self.fleet is None:
                return 404, {"error": "no routed replica sets"}
            # the roll-up walks every router's health board; small, but
            # keep it off the probe loop like the other forensic reads
            return 200, await asyncio.to_thread(self.fleet)
        if path == "/incidents":
            if self.memory is None:
                return 404, {"error": "incident memory disabled"}
            try:
                limit = int(query.get("limit", ["100"])[0])
            except ValueError:
                return 400, {"error": "limit must be an integer"}
            # serialize off-loop and only the requested page — a full-store
            # to_dict on the probe loop would stall kubelet probes
            incidents = await asyncio.to_thread(
                self.memory.store.to_dicts, True, limit
            )
            return 200, {"count": len(self.memory.store), "incidents": incidents}
        if path == "/incidents/query":
            if self.memory is None:
                return 404, {"error": "incident memory disabled"}
            text = query.get("q", [""])[0]
            if not text.strip():
                return 400, {"error": "missing query parameter q"}
            try:
                k = int(query.get("k", ["3"])[0])
            except ValueError:
                return 400, {"error": "k must be an integer"}
            # embedding runs off-loop: a neural embedder must not stall
            # probe handling on this same server
            matches = await asyncio.to_thread(self.memory.query_text, text, k)
            payload = []
            for incident, score in matches:
                # re-serialize under the store lock: the Incident is live
                data = self.memory.store.dump(incident.fingerprint)
                if data is not None:
                    payload.append({"score": round(score, 4), **data})
            return 200, {"matches": payload}
        if path == "/traces":
            if self.recorder is None:
                return 404, {"error": "flight recorder disabled"}
            try:
                limit = int(query.get("limit", ["50"])[0])
            except ValueError:
                return 400, {"error": "limit must be an integer"}
            blackbox_only = query.get("blackbox", ["0"])[0] in ("1", "true")
            records = self.recorder.traces(limit, blackbox_only=blackbox_only)
            return 200, {
                "count": len(self.recorder),
                "traces": [r.summary() for r in records],
            }
        if path.startswith("/traces/"):
            if self.recorder is None:
                return 404, {"error": "flight recorder disabled"}
            trace_id = path[len("/traces/"):]
            record = self.recorder.get(trace_id)
            if record is None:
                return 404, {"error": f"no trace {trace_id} in the ring "
                                      "(it may have been evicted)"}
            payload = record.to_dict()
            # the flame-style text tree (the obs.view CLI's rendering),
            # so a curl is readable without tooling
            payload["rendered"] = render_tree(record.trace)
            return 200, payload
        return 404, {"error": f"no route {path}"}
